"""Layer catalog — config + runtime in one class per layer.

Ref: deeplearning4j-nn `nn/conf/layers/*.java` (configs) + `nn/layers/**`
(runtimes). The reference splits config and runtime classes; TPU-first we
fuse them: a Layer is a pure-functional module with
  - ``build(input_shape, defaults)``   resolve shapes/defaults (ref: setNIn)
  - ``init_params(rng, dtype)``        -> params dict
  - ``init_state()``                   -> state dict (e.g. BN running stats)
  - ``apply(params, x, state, train, rng)`` -> (out, new_state)
  - ``output_shape(input_shape)``
Shapes exclude the batch dimension. Data layouts are TPU-native:
NHWC for images (XLA TPU's preferred conv layout — the reference is NCHW),
[B, T, C] for sequences (reference is [B, C, T]).

Forward math is jnp/lax only; backprop comes from JAX autodiff (the
reference hand-writes backpropGradient per layer, e.g.
`nn/layers/BaseLayer.java:73-108`). XLA fuses bias+activation into the
matmul/conv epilogue, so the MXU sees large fused GEMMs.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ... import activations as A
from ... import losses as L
from ... import learning as U
from ...weightinit import init_weights

Shape = Tuple[int, ...]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


class Layer:
    """Base layer config+runtime. Ref: `nn/conf/layers/Layer.java` +
    `nn/api/Layer.java:38`."""

    kind = "layer"

    def __init__(self, name: Optional[str] = None, dropout=None,
                 activation=None, weight_init: Optional[str] = None,
                 bias_init: float = 0.0, updater=None,
                 l1: Optional[float] = None, l2: Optional[float] = None,
                 l1_bias: Optional[float] = None, l2_bias: Optional[float] = None,
                 weight_noise=None, constraints=None):
        # None means "unset — inherit the conf-level default at build()";
        # an explicit 0.0 opts out of a nonzero global default (the
        # reference distinguishes unset from set-to-zero the same way).
        self.name = name
        # dropout: float shorthand (drop prob) or an IDropout scheme
        # (ref: Layer.Builder.dropOut(double) vs .dropOut(IDropout))
        if dropout is None or isinstance(dropout, (int, float)):
            self.dropout = None if dropout is None else float(dropout)
        else:
            from ..conf.dropout import get as _dropout_get
            self.dropout = _dropout_get(dropout)
        # weight noise (ref: Layer.Builder.weightNoise — DropConnect etc.)
        from ..conf.weightnoise import get as _wn_get
        self.weight_noise = _wn_get(weight_noise)
        # post-update constraints (ref: Layer.Builder.constrainWeights)
        from ..conf.constraint import get as _con_get
        self.constraints = [_con_get(c) for c in (constraints or [])]
        self.activation = A.get(activation) if activation is not None else None
        self.weight_init = weight_init
        self.bias_init = float(bias_init)
        self.updater = U.get(updater) if updater is not None else None
        self.l1 = None if l1 is None else float(l1)
        self.l2 = None if l2 is None else float(l2)
        self.l1_bias = None if l1_bias is None else float(l1_bias)
        self.l2_bias = None if l2_bias is None else float(l2_bias)
        self.input_shape: Optional[Shape] = None
        self._built = False

    # -- lifecycle -----------------------------------------------------
    def build(self, input_shape: Shape, defaults: Optional[dict] = None):
        """Resolve input shape + inherit unset defaults (ref: the conf
        builder's layer defaults + InputTypeUtil shape inference)."""
        defaults = defaults or {}
        if self.activation is None:
            self.activation = A.get(defaults.get("activation") or "identity")
        if self.weight_init is None:
            self.weight_init = defaults.get("weight_init", "xavier")
        if self.updater is None and defaults.get("updater") is not None:
            self.updater = U.get(defaults["updater"])
        if self.l1 is None:
            self.l1 = defaults.get("l1", 0.0)
        if self.l2 is None:
            self.l2 = defaults.get("l2", 0.0)
        if self.l1_bias is None:
            self.l1_bias = defaults.get("l1_bias", 0.0)
        if self.l2_bias is None:
            self.l2_bias = defaults.get("l2_bias", 0.0)
        if self.dropout is None:
            dd = defaults.get("dropout", 0.0)
            if dd is not None and not isinstance(dd, (int, float)):
                from ..conf.dropout import get as _dropout_get
                dd = _dropout_get(dd)
            self.dropout = dd
        if self.weight_noise is None and defaults.get("weight_noise") is not None:
            from ..conf.weightnoise import get as _wn_get
            self.weight_noise = _wn_get(defaults["weight_noise"])
        if not self.constraints and defaults.get("constraints"):
            from ..conf.constraint import get as _con_get
            self.constraints = [_con_get(c) for c in defaults["constraints"]]
        self.input_shape = tuple(input_shape)
        self._built = True

    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
        return {}

    def init_state(self) -> Dict[str, jnp.ndarray]:
        return {}

    def apply(self, params, x, state, train: bool, rng: Optional[jax.Array]):
        raise NotImplementedError

    def output_shape(self, input_shape: Shape) -> Shape:
        return tuple(input_shape)

    # -- helpers -------------------------------------------------------
    def _maybe_dropout(self, x, train, rng):
        """Dropout/noise applied to the layer INPUT (reference semantics:
        `dropOut` in BaseLayer applies to input activations). A float is
        plain inverted dropout; an IDropout scheme (Gaussian/Alpha/
        Spatial/noise — `nn/conf/dropout.py`) applies itself."""
        d = self.dropout
        if not train or d is None or rng is None:
            return x
        if isinstance(d, (int, float)):
            if not d:
                return x
            from ..conf.dropout import Dropout
            d = Dropout(float(d))  # float shorthand shares the one impl
        return d.apply(x, rng, train)

    def _maybe_weight_noise(self, params, train, rng):
        """Apply the configured IWeightNoise (DropConnect / Gaussian) to
        this layer's weight params for one forward pass (ref:
        `BaseLayer.getParamWithNoise`). Biases/norm gains are exempt."""
        wn = self.weight_noise
        if wn is None or not train or rng is None or not params:
            return params
        bias = self.bias_param_names()
        base = jax.random.fold_in(rng, 0x5EED)
        out = dict(params)
        for i, n in enumerate(sorted(params)):
            if n not in bias:
                out[n] = wn.apply(params[n], jax.random.fold_in(base, i),
                                  train)
        return out

    @property
    def has_params(self) -> bool:
        return bool(self.param_shapes())

    def param_shapes(self) -> Dict[str, Shape]:
        return {}

    def bias_param_names(self) -> set:
        """Params regularized with the *_bias coefficients (ref:
        BaseMultiLayerUpdater.preApply — only weight params use l1/l2;
        biases and norm offsets/gains use the bias coefficients, which
        default to 0 i.e. unregularized). Convention over this package's
        layer params: 'b'/'beta'/'b1'/'b2'/'gamma', any '*_b' offset and
        any '*_g' norm gain, including composite 'attn_'-prefixed ones."""
        names = set()
        for n in self.param_shapes():
            base = n[5:] if n.startswith("attn_") else n
            if (base in ("b", "beta", "b1", "b2", "gamma")
                    or base.endswith("_b") or base.endswith("_g")):
                names.add(n)
        return names

    def n_params(self) -> int:
        return sum(int(math.prod(s)) for s in self.param_shapes().values())

    # -- serde ---------------------------------------------------------
    _JSON_FIELDS = ("name", "dropout", "weight_init", "bias_init", "l1", "l2",
                    "l1_bias", "l2_bias")

    def to_json(self) -> dict:
        d: Dict[str, Any] = {"@class": self.kind}
        for f in self._JSON_FIELDS:
            v = getattr(self, f, None)
            if f == "dropout" and v is not None and \
                    not isinstance(v, (int, float)):
                v = v.to_json()
            if v is not None:
                d[f] = v
        if self.activation is not None:
            d["activation"] = self.activation.to_json()
        if self.updater is not None:
            d["updater"] = self.updater.to_json()
        if self.weight_noise is not None:
            d["weight_noise"] = self.weight_noise.to_json()
        if self.constraints:
            d["constraints"] = [c.to_json() for c in self.constraints]
        d.update(self._extra_json())
        return d

    def _extra_json(self) -> dict:
        return {}

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name})"


class DenseLayer(Layer):
    """Fully connected. Ref config: `nn/conf/layers/DenseLayer.java`;
    runtime math: `nn/layers/BaseLayer.preOutputWithPreNorm`
    (`nn/layers/BaseLayer.java:296-318`, z = x·W + b)."""

    kind = "dense"

    def __init__(self, n_out: int = None, n_in: Optional[int] = None,
                 has_bias: bool = True, **kw):
        super().__init__(**kw)
        self.n_in = n_in
        self.n_out = int(n_out)
        self.has_bias = bool(has_bias)

    def build(self, input_shape, defaults=None):
        super().build(input_shape, defaults)
        # CNN input feeding a dense layer flattens — the equivalent of the
        # reference's auto-added CnnToFeedForwardPreProcessor
        # (ref: nn/conf/preprocessor/CnnToFeedForwardPreProcessor.java).
        # Rank-2 [T, C] sequence input stays unflattened: dense applies
        # per-timestep (ref: RnnToFeedForwardPreProcessor semantics).
        # rank-3 NHWC and rank-4 NDHWC spatial inputs flatten; rank-2
        # [T, C] sequences stay per-timestep
        self._flatten_input = len(input_shape) >= 3
        if self.n_in is None:
            self.n_in = int(math.prod(input_shape)) if self._flatten_input \
                else int(input_shape[-1])

    def param_shapes(self):
        sh = {"W": (self.n_in, self.n_out)}
        if self.has_bias:
            sh["b"] = (self.n_out,)
        return sh

    def init_params(self, rng, dtype=jnp.float32):
        kW, = jax.random.split(rng, 1)
        p = {"W": init_weights(kW, (self.n_in, self.n_out), self.n_in, self.n_out,
                               self.weight_init, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def pre_output(self, params, x, train: bool = False, rng=None):
        """Shared preactivation primitive — both apply() (inference/forward)
        and OutputLayer.compute_loss (training loss) route through here so
        the flatten/dropout/matmul/bias logic cannot diverge."""
        if getattr(self, "_flatten_input", False) and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        x = self._maybe_dropout(x, train, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z

    def apply(self, params, x, state, train, rng):
        return self.activation(self.pre_output(params, x, train, rng)), state

    def output_shape(self, input_shape):
        if len(input_shape) >= 3:  # flattened CNN/CNN3D input
            return (self.n_out,)
        return tuple(input_shape[:-1]) + (self.n_out,)

    def _extra_json(self):
        return {"n_out": self.n_out, "n_in": self.n_in, "has_bias": self.has_bias}


class OutputLayer(DenseLayer):
    """Dense + loss head. Ref: `nn/conf/layers/OutputLayer.java` /
    `nn/layers/BaseOutputLayer.java`."""

    kind = "output"

    def __init__(self, n_out: int = None, loss="mcxent", **kw):
        kw.setdefault("activation", "softmax")
        super().__init__(n_out=n_out, **kw)
        self.loss = L.get(loss)

    def compute_loss(self, params, x, labels, mask=None, train: bool = False,
                     rng=None):
        return self.loss.score(labels, self.pre_output(params, x, train, rng),
                               self.activation, mask)

    def _extra_json(self):
        d = super()._extra_json()
        d["loss"] = self.loss.to_json()
        return d


class LossLayer(Layer):
    """Loss on raw input, no params. Ref: `nn/conf/layers/LossLayer.java`."""

    kind = "loss"

    def __init__(self, loss="mcxent", **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        self.loss = L.get(loss)

    def apply(self, params, x, state, train, rng):
        return self.activation(x), state

    def compute_loss(self, params, x, labels, mask=None, train: bool = False,
                     rng=None):
        x = self._maybe_dropout(x, train, rng)
        return self.loss.score(labels, x, self.activation, mask)

    def _extra_json(self):
        return {"loss": self.loss.to_json()}


class ActivationLayer(Layer):
    """Ref: `nn/conf/layers/ActivationLayer.java`."""

    kind = "activation"

    def apply(self, params, x, state, train, rng):
        return self.activation(x), state


class DropoutLayer(Layer):
    """Ref: `nn/conf/layers/DropoutLayer.java`."""

    kind = "dropoutlayer"

    def __init__(self, dropout: Optional[float] = 0.5, **kw):
        super().__init__(dropout=dropout, **kw)

    def build(self, input_shape, defaults=None):
        d = dict(defaults or {})
        d["activation"] = d.get("activation", "identity")
        super().build(input_shape, d)

    def apply(self, params, x, state, train, rng):
        return self._maybe_dropout(x, train, rng), state


class ConvolutionLayer(Layer):
    """2D convolution, NHWC. Ref: `nn/conf/layers/ConvolutionLayer.java`;
    runtime `nn/layers/convolution/ConvolutionLayer.java` (im2col+gemm on
    CPU, cudnn on GPU). Here: `lax.conv_general_dilated`, which XLA maps
    straight onto the MXU."""

    kind = "conv2d"

    def __init__(self, n_out: int = None, kernel=(3, 3), stride=(1, 1),
                 padding="same", dilation=(1, 1), n_in: Optional[int] = None,
                 has_bias: bool = True, groups: int = 1, **kw):
        super().__init__(**kw)
        self.n_out = int(n_out)
        self.kernel = _pair(kernel)
        self.stride = _pair(stride)
        self.dilation = _pair(dilation)
        self.padding = padding  # "same" | "valid" | ((top,bot),(l,r))
        self.n_in = n_in
        self.has_bias = bool(has_bias)
        self.groups = int(groups)

    def build(self, input_shape, defaults=None):
        super().build(input_shape, defaults)
        if self.n_in is None:
            self.n_in = int(input_shape[-1])

    def _pad(self):
        if isinstance(self.padding, str):
            return self.padding.upper()
        return tuple(tuple(int(x) for x in p) for p in self.padding)

    def param_shapes(self):
        kh, kw_ = self.kernel
        sh = {"W": (kh, kw_, self.n_in // self.groups, self.n_out)}
        if self.has_bias:
            sh["b"] = (self.n_out,)
        return sh

    def init_params(self, rng, dtype=jnp.float32):
        kh, kw_ = self.kernel
        fan_in = kh * kw_ * (self.n_in // self.groups)
        fan_out = kh * kw_ * self.n_out
        p = {"W": init_weights(rng, (kh, kw_, self.n_in // self.groups, self.n_out),
                               fan_in, fan_out, self.weight_init, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params, x, state, train, rng):
        x = self._maybe_dropout(x, train, rng)
        z = lax.conv_general_dilated(
            x, params["W"], window_strides=self.stride, padding=self._pad(),
            rhs_dilation=self.dilation, feature_group_count=self.groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def output_shape(self, input_shape):
        h, w, _ = input_shape
        kh, kw_ = self.kernel
        sh, sw = self.stride
        dh, dw = self.dilation
        ekh, ekw = (kh - 1) * dh + 1, (kw_ - 1) * dw + 1
        if isinstance(self.padding, str) and self.padding.lower() == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        elif isinstance(self.padding, str):  # valid
            oh, ow = (h - ekh) // sh + 1, (w - ekw) // sw + 1
        else:
            (pt, pb), (pl, pr) = self.padding
            oh = (h + pt + pb - ekh) // sh + 1
            ow = (w + pl + pr - ekw) // sw + 1
        return (oh, ow, self.n_out)

    def _extra_json(self):
        return {"n_out": self.n_out, "n_in": self.n_in, "kernel": list(self.kernel),
                "stride": list(self.stride), "padding": self.padding,
                "dilation": list(self.dilation), "has_bias": self.has_bias,
                "groups": self.groups}


class SubsamplingLayer(Layer):
    """Pooling (max/avg/pnorm). Ref: `nn/conf/layers/SubsamplingLayer.java`."""

    kind = "subsampling"

    def __init__(self, kernel=(2, 2), stride=(2, 2), padding="valid",
                 pooling="max", pnorm: int = 2, **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        self.kernel = _pair(kernel)
        self.stride = _pair(stride)
        self.padding = padding
        self.pooling = pooling
        self.pnorm = int(pnorm)

    def _pad(self):
        if isinstance(self.padding, str):
            return self.padding.upper()
        return ((0, 0),) + tuple(tuple(int(x) for x in p) for p in self.padding) + ((0, 0),)

    def apply(self, params, x, state, train, rng):
        kh, kw_ = self.kernel
        sh, sw = self.stride
        window = (1, kh, kw_, 1)
        strides = (1, sh, sw, 1)
        if self.pooling == "max":
            z = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, self._pad())
        elif self.pooling == "avg":
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, self._pad())
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, self._pad())
            z = s / cnt
        elif self.pooling == "pnorm":
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, self._pad())
            z = s ** (1.0 / p)
        else:
            raise ValueError(f"unknown pooling {self.pooling!r}")
        return z, state

    def output_shape(self, input_shape):
        h, w, c = input_shape
        kh, kw_ = self.kernel
        sh, sw = self.stride
        if isinstance(self.padding, str) and self.padding.lower() == "same":
            return (-(-h // sh), -(-w // sw), c)
        if isinstance(self.padding, str):
            return ((h - kh) // sh + 1, (w - kw_) // sw + 1, c)
        (pt, pb), (pl, pr) = self.padding
        return ((h + pt + pb - kh) // sh + 1, (w + pl + pr - kw_) // sw + 1, c)

    def _extra_json(self):
        return {"kernel": list(self.kernel), "stride": list(self.stride),
                "padding": self.padding, "pooling": self.pooling, "pnorm": self.pnorm}


class BatchNormalization(Layer):
    """Ref: `nn/conf/layers/BatchNormalization.java` (decay 0.9 default) /
    `nn/layers/normalization/BatchNormalization.java`. Works on the last
    (channel/feature) axis for both NC and NHWC inputs."""

    kind = "batchnorm"

    def __init__(self, decay: float = 0.9, eps: float = 1e-5,
                 gamma_init: float = 1.0, beta_init: float = 0.0,
                 lock_gamma_beta: bool = False, **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        self.decay = float(decay)
        self.eps = float(eps)
        self.gamma_init = float(gamma_init)
        self.beta_init = float(beta_init)
        self.lock_gamma_beta = bool(lock_gamma_beta)
        self.n_feat: Optional[int] = None

    def build(self, input_shape, defaults=None):
        super().build(input_shape, defaults)
        self.n_feat = int(input_shape[-1])

    def param_shapes(self):
        if self.lock_gamma_beta:
            return {}
        return {"gamma": (self.n_feat,), "beta": (self.n_feat,)}

    def init_params(self, rng, dtype=jnp.float32):
        if self.lock_gamma_beta:
            return {}
        return {"gamma": jnp.full((self.n_feat,), self.gamma_init, dtype),
                "beta": jnp.full((self.n_feat,), self.beta_init, dtype)}

    def init_state(self):
        return {"mean": jnp.zeros((self.n_feat,), jnp.float32),
                "var": jnp.ones((self.n_feat,), jnp.float32)}

    def apply(self, params, x, state, train, rng):
        axes = tuple(range(x.ndim - 1))
        # statistics in AT LEAST f32 even under a bf16 compute policy:
        # batch mean/var over ~1e5 elements loses real precision in
        # bf16, and the running stats (state) are f32. promote_types
        # keeps f64 inputs in f64 (x64 mode) instead of truncating
        xs = x.astype(jnp.promote_types(x.dtype, jnp.float32))
        if train:
            mean = jnp.mean(xs, axis=axes)
            var = jnp.var(xs, axis=axes)
            # running stats keep THEIR dtype (f32 checkpoint contract):
            # promoting the carried state with an f64 input would change
            # the net-state pytree dtype mid-training (scan carries and
            # donated buffers would mismatch)
            new_state = {
                "mean": (self.decay * state["mean"] +
                         (1 - self.decay) * mean
                         ).astype(state["mean"].dtype),
                "var": (self.decay * state["var"] +
                        (1 - self.decay) * var
                        ).astype(state["var"].dtype),
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        xn = (xs - mean) * jax.lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta:
            xn = xn * params["gamma"].astype(xs.dtype) \
                + params["beta"].astype(xs.dtype)
        return self.activation(xn).astype(x.dtype), new_state

    def _extra_json(self):
        return {"decay": self.decay, "eps": self.eps,
                "gamma_init": self.gamma_init, "beta_init": self.beta_init,
                "lock_gamma_beta": self.lock_gamma_beta}


class EmbeddingLayer(Layer):
    """Index -> vector lookup. Ref: `nn/conf/layers/EmbeddingLayer.java`
    (input: [B] or [B,1] int indices)."""

    kind = "embedding"

    def __init__(self, n_in: int = None, n_out: int = None, has_bias: bool = False, **kw):
        super().__init__(**kw)
        self.n_in = int(n_in)
        self.n_out = int(n_out)
        self.has_bias = bool(has_bias)

    def param_shapes(self):
        sh = {"W": (self.n_in, self.n_out)}
        if self.has_bias:
            sh["b"] = (self.n_out,)
        return sh

    def init_params(self, rng, dtype=jnp.float32):
        p = {"W": init_weights(rng, (self.n_in, self.n_out), self.n_in, self.n_out,
                               self.weight_init, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params, x, state, train, rng):
        idx = x.astype(jnp.int32)
        if idx.ndim > 1 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        z = params["W"][idx]
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def output_shape(self, input_shape):
        if input_shape and input_shape[-1] == 1:
            return tuple(input_shape[:-1]) + (self.n_out,)
        return tuple(input_shape) + (self.n_out,)

    def _extra_json(self):
        return {"n_in": self.n_in, "n_out": self.n_out, "has_bias": self.has_bias}


class GlobalPoolingLayer(Layer):
    """Pool over all spatial/time dims. Ref:
    `nn/conf/layers/GlobalPoolingLayer.java` (MAX/AVG/SUM/PNORM,
    collapseDimensions — `keep_dims=True` is collapseDimensions(false):
    pooled dims stay as size-1 axes)."""

    kind = "globalpool"
    wants_mask = True

    def __init__(self, pooling: str = "avg", pnorm: int = 2,
                 keep_dims: bool = False, **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        self.pooling = pooling
        self.pnorm = int(pnorm)
        self.keep_dims = bool(keep_dims)

    def apply(self, params, x, state, train, rng):
        return self.apply_with_mask(params, x, state, train, rng, None)

    def apply_with_mask(self, params, x, state, train, rng, mask):
        """Masked pooling over time (ref: GlobalPoolingLayer.java
        activateHelperFullArray vs the masked path — padded timesteps
        are EXCLUDED, so avg divides by the true length and max ignores
        padding entirely)."""
        axes = tuple(range(1, x.ndim - 1))  # all but batch & channel
        kd = self.keep_dims
        if mask is not None and x.ndim == 3:
            m = mask[:, :, None].astype(x.dtype)
            if self.pooling == "max":
                z = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=axes,
                            keepdims=kd)
                # a fully-masked row (ragged batching) would pool to
                # -inf and NaN-poison downstream; emit 0 like an empty
                # average instead
                any_valid = jnp.sum(m, axis=axes, keepdims=kd) > 0
                z = jnp.where(any_valid, z, 0.0)
            elif self.pooling == "avg":
                z = (jnp.sum(x * m, axis=axes, keepdims=kd) /
                     jnp.maximum(jnp.sum(m, axis=axes, keepdims=kd), 1.0))
            elif self.pooling == "sum":
                z = jnp.sum(x * m, axis=axes, keepdims=kd)
            elif self.pooling == "pnorm":
                p = float(self.pnorm)
                z = jnp.sum(jnp.abs(x * m) ** p, axis=axes,
                            keepdims=kd) ** (1.0 / p)
            else:
                raise ValueError(self.pooling)
            return z, state
        if self.pooling == "max":
            z = jnp.max(x, axis=axes, keepdims=kd)
        elif self.pooling == "avg":
            z = jnp.mean(x, axis=axes, keepdims=kd)
        elif self.pooling == "sum":
            z = jnp.sum(x, axis=axes, keepdims=kd)
        elif self.pooling == "pnorm":
            p = float(self.pnorm)
            z = jnp.sum(jnp.abs(x) ** p, axis=axes,
                        keepdims=kd) ** (1.0 / p)
        else:
            raise ValueError(self.pooling)
        return z, state

    def output_shape(self, input_shape):
        if self.keep_dims:
            return (1,) * (len(input_shape) - 1) + (input_shape[-1],)
        return (input_shape[-1],)

    def _extra_json(self):
        return {"pooling": self.pooling, "pnorm": self.pnorm,
                "keep_dims": self.keep_dims}


class LocalResponseNormalization(Layer):
    """Ref: `nn/conf/layers/LocalResponseNormalization.java` (k=2, n=5,
    alpha=1e-4, beta=0.75 defaults)."""

    kind = "lrn"

    def __init__(self, k: float = 2.0, n: int = 5, alpha: float = 1e-4,
                 beta: float = 0.75, **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        self.k = float(k)
        self.n = int(n)
        self.alpha = float(alpha)
        self.beta = float(beta)

    def apply(self, params, x, state, train, rng):
        # sum of squares over a window of n channels (last axis)
        half = self.n // 2
        sq = jnp.square(x)
        padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
        windows = [padded[..., i:i + x.shape[-1]] for i in range(self.n)]
        ssum = sum(windows)
        denom = jnp.power(self.k + self.alpha * ssum, self.beta)
        return x / denom, state

    def _extra_json(self):
        return {"k": self.k, "n": self.n, "alpha": self.alpha, "beta": self.beta}


class ZeroPaddingLayer(Layer):
    """Ref: `nn/conf/layers/ZeroPaddingLayer.java` (NHWC here)."""

    kind = "zeropad"

    def __init__(self, padding=((1, 1), (1, 1)), **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        if isinstance(padding, int):
            padding = ((padding, padding), (padding, padding))
        self.padding = tuple(tuple(int(x) for x in p) for p in padding)

    def apply(self, params, x, state, train, rng):
        (pt, pb), (pl, pr) = self.padding
        return jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0))), state

    def output_shape(self, input_shape):
        h, w, c = input_shape
        (pt, pb), (pl, pr) = self.padding
        return (h + pt + pb, w + pl + pr, c)

    def _extra_json(self):
        return {"padding": [list(p) for p in self.padding]}


class Upsampling2D(Layer):
    """Nearest-neighbour upsampling. Ref: `nn/conf/layers/Upsampling2D.java`."""

    kind = "upsampling2d"

    def __init__(self, size=(2, 2), **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        self.size = _pair(size)

    def apply(self, params, x, state, train, rng):
        sh, sw = self.size
        z = jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return z, state

    def output_shape(self, input_shape):
        h, w, c = input_shape
        return (h * self.size[0], w * self.size[1], c)

    def _extra_json(self):
        return {"size": list(self.size)}


REGISTRY: Dict[str, type] = {}
for _cls in list(globals().values()):
    if isinstance(_cls, type) and issubclass(_cls, Layer) and _cls is not Layer:
        REGISTRY[_cls.kind] = _cls


def register(cls: type) -> type:
    """Register a Layer subclass for JSON round-trip (submodules call this)."""
    REGISTRY[cls.kind] = cls
    return cls


def from_json(d: dict) -> Layer:
    d = dict(d)
    kind = d.pop("@class")
    if kind.startswith("samediff"):
        # custom SameDiff layers reconstruct by import path (reference:
        # reflective JSON subtyping of SameDiffLayer subclasses) — no
        # registry lookup, so subclasses may use their own kind strings
        from .samediff_layer import samediff_layer_from_json
        return samediff_layer_from_json(d)
    cls = REGISTRY[kind]
    if "activation" in d and isinstance(d["activation"], dict):
        d["activation"] = A.get(d["activation"])
    if "updater" in d and isinstance(d["updater"], dict):
        d["updater"] = U.get(d["updater"])
    if "loss" in d and isinstance(d["loss"], dict):
        d["loss"] = L.get(d["loss"])
    if isinstance(d.get("kernel"), list):
        d["kernel"] = tuple(d["kernel"])
    if isinstance(d.get("stride"), list):
        d["stride"] = tuple(d["stride"])
    if isinstance(d.get("dilation"), list):
        d["dilation"] = tuple(d["dilation"])
    if isinstance(d.get("size"), list):
        d["size"] = tuple(d["size"])
    if "padding" in d and isinstance(d["padding"], list):
        d["padding"] = tuple(tuple(p) for p in d["padding"])
    return cls(**d)


# -- submodule layer catalogs (registered on import) -------------------
from .recurrent import (BaseRecurrentLayer, Bidirectional,  # noqa: E402
                        EmbeddingSequenceLayer, GravesBidirectionalLSTM,
                        GravesLSTM, GRU, LastTimeStep, LSTM, MaskZeroLayer,
                        RepeatVector, RnnLossLayer, RnnOutputLayer, SimpleRnn)

for _cls in (LSTM, GravesLSTM, GRU, SimpleRnn, Bidirectional,
             GravesBidirectionalLSTM, LastTimeStep, MaskZeroLayer,
             EmbeddingSequenceLayer, RnnOutputLayer, RnnLossLayer,
             RepeatVector):
    register(_cls)

from . import convolutional  # noqa: E402,F401  (registers conv-family layers)
from .attention import (SelfAttentionLayer,  # noqa: E402,F401
                        TransformerEncoderLayer)
from .variational import VariationalAutoencoder  # noqa: E402,F401
from .specialized_outputs import (CenterLossOutputLayer,  # noqa: E402,F401
                                  OCNNOutputLayer)
from .misc import (AutoEncoder, Cnn3DLossLayer,  # noqa: E402,F401
                   CnnLossLayer, FrozenLayerWithBackprop, MaskLayer,
                   MaskingLayer)
from .samediff_layer import (SameDiffLambdaLayer,  # noqa: E402,F401
                             SameDiffLayer, SameDiffOutputLayer,
                             SDLayerParams)
