"""Specialized output layers (ref: deeplearning4j-nn
`nn/conf/layers/CenterLossOutputLayer.java` +
`nn/layers/training/CenterLossOutputLayer.java`, and
`nn/conf/layers/misc/OCNNOutputLayer.java` +
`nn/layers/ocnn/OCNNOutputLayer.java`) — the last two D2 inventory rows.

TPU-first redesign notes:

- CenterLoss (Wen et al. 2016): the reference updates class centers with
  a dedicated alpha moving-average pass inside backprop. Here centers
  are ordinary params and the center term's gradient (lambda * (c_y - x)
  per assigned sample) IS the update — the paper's center update rule is
  exactly a scaled gradient step, so the same jitted updater chain
  covers it (alpha maps to the learning rate on the centers).
- OCNN (Chalapathy et al. 2018): the reference re-solves the bias r as
  the nu-quantile of scores every windowSize iterations on the host.
  Here r is a parameter of the same jitted loss: d/dr of
  (1/nu)*mean(relu(r - s)) - r vanishes exactly when
  P(s < r) = nu, so gradient descent drives r to the nu-quantile with
  no host round-trip or dynamic control flow — the XLA-friendly form of
  the same alternating optimization.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ...weightinit import init_weights
from . import Layer, OutputLayer, register


class CenterLossOutputLayer(OutputLayer):
    """Softmax head + center loss: total = CE + (lambda/2) * mean
    ||x - c_y||^2 (ref: CenterLossOutputLayer.java — alpha/lambda/
    gradientCheck config at :~50)."""

    kind = "centerloss_output"

    def __init__(self, n_out: int = None, alpha: float = 0.05,
                 lambda_: float = 2e-4, **kw):
        super().__init__(n_out=n_out, **kw)
        self.alpha = float(alpha)
        self.lambda_ = float(lambda_)

    def param_shapes(self):
        sh = dict(super().param_shapes())
        sh["centers"] = (self.n_out, self.n_in)
        return sh

    def init_params(self, rng, dtype=jnp.float32):
        p = super().init_params(rng, dtype)
        p["centers"] = jnp.zeros((self.n_out, self.n_in), dtype)
        return p

    def bias_param_names(self) -> set:
        # centers are not weights: exempt from l1/l2 weight decay and
        # from weight noise/constraints (ref: centers bypass the
        # regular updater's regularization entirely)
        return super().bias_param_names() | {"centers"}

    def compute_loss(self, params, x, labels, mask=None, train: bool = False,
                     rng=None):
        if getattr(self, "_flatten_input", False) and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        ce = self.loss.score(labels, super().pre_output(params, x, train,
                                                        rng),
                             self.activation, mask)
        # center term: squared distance of each sample to ITS class
        # center. alpha scales the centers' own gradient (their update
        # rate) without changing the features' pull strength.
        assigned = labels @ params["centers"]          # [B, n_in]
        assigned = self.alpha * assigned + \
            (1.0 - self.alpha) * jax.lax.stop_gradient(assigned)
        d2 = jnp.sum(jnp.square(x - assigned), axis=-1)
        if mask is not None and mask.ndim == 1:
            d2 = d2 * mask
            denom = jnp.maximum(jnp.sum(mask), 1.0)
        else:
            denom = d2.shape[0]
        return ce + 0.5 * self.lambda_ * jnp.sum(d2) / denom

    def _extra_json(self):
        d = super()._extra_json()
        d.update(alpha=self.alpha, lambda_=self.lambda_)
        return d


class OCNNOutputLayer(Layer):
    """One-class NN output layer for anomaly detection (ref:
    OCNNOutputLayer.java — hiddenSize/nu/initialRValue/windowSize
    config; score = w·g(Vx) - r, objective eq. 4 of the paper):

        L = 0.5||V||^2 + 0.5||w||^2 + (1/nu) mean relu(r - s) - r

    `apply` returns the decision score s - r ([B, 1]); >= 0 means
    inlier at the nu working point. Labels are ignored (one-class =
    unsupervised), matching the reference layer which trains on
    features only."""

    kind = "ocnn_output"

    def __init__(self, hidden_size: int = 100, nu: float = 0.04,
                 initial_r: float = 0.1, window_size: int = 10000, **kw):
        kw.setdefault("activation", "sigmoid")
        super().__init__(**kw)
        self.hidden_size = int(hidden_size)
        self.nu = float(nu)
        self.initial_r = float(initial_r)
        self.window_size = int(window_size)  # accepted for API parity
        self.n_in: Optional[int] = None

    def build(self, input_shape, defaults=None):
        super().build(input_shape, defaults)
        self._flatten_input = len(input_shape) == 3
        self.n_in = int(math.prod(input_shape)) if self._flatten_input \
            else int(input_shape[-1])

    def param_shapes(self):
        return {"V": (self.n_in, self.hidden_size),
                "w": (self.hidden_size, 1),
                "r_b": (1,)}

    def bias_param_names(self) -> set:
        return {"r_b"}

    def init_params(self, rng, dtype=jnp.float32):
        kV, kw_ = jax.random.split(rng)
        return {"V": init_weights(kV, (self.n_in, self.hidden_size),
                                  self.n_in, self.hidden_size,
                                  self.weight_init, dtype),
                "w": init_weights(kw_, (self.hidden_size, 1),
                                  self.hidden_size, 1, self.weight_init,
                                  dtype),
                "r_b": jnp.full((1,), self.initial_r, dtype)}

    def _score(self, params, x, train=False, rng=None):
        if getattr(self, "_flatten_input", False) and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        if x.ndim != 2:
            raise ValueError(
                "OCNNOutputLayer expects flat [B, F] features; reduce "
                "sequences first (LastTimeStep / GlobalPoolingLayer), "
                f"got rank-{x.ndim} input")
        x = self._maybe_dropout(x, train, rng)
        return self.activation(x @ params["V"]) @ params["w"]   # [B, 1]

    def apply(self, params, x, state, train, rng):
        s = self._score(params, x, train, rng)
        return s - params["r_b"], state

    def output_shape(self, input_shape) -> Tuple[int, ...]:
        return (1,)

    def compute_loss(self, params, x, labels=None, mask=None,
                     train: bool = False, rng=None):
        s = self._score(params, x, train, rng)[:, 0]
        r = params["r_b"][0]
        hinge = jnp.maximum(0.0, r - s)
        if mask is not None and mask.ndim == 1:
            mean_h = jnp.sum(hinge * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        else:
            mean_h = jnp.mean(hinge)
        reg = 0.5 * jnp.sum(jnp.square(params["V"])) \
            + 0.5 * jnp.sum(jnp.square(params["w"]))
        return reg + mean_h / self.nu - r

    def _extra_json(self):
        return {"hidden_size": self.hidden_size, "nu": self.nu,
                "initial_r": self.initial_r,
                "window_size": self.window_size}


register(CenterLossOutputLayer)
register(OCNNOutputLayer)
