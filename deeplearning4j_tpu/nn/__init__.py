"""Neural-network framework (ref: deeplearning4j-nn).

- :mod:`.conf`   — configuration DSL (NeuralNetConfiguration builder, JSON round-trip)
- :mod:`.layers` — layer catalog (Dense, Conv, Subsampling, BatchNorm, LSTM, ...)
- :mod:`.multilayer` — MultiLayerNetwork (sequential stack + fit/evaluate)
- :mod:`.graph` — ComputationGraph (arbitrary DAG)
"""
from .conf import NeuralNetConfiguration, MultiLayerConfiguration  # noqa: F401
from .multilayer import MultiLayerNetwork  # noqa: F401
from .graph import (ComputationGraph,  # noqa: F401
                    ComputationGraphConfiguration, GraphBuilder)
