"""MultiLayerNetwork — sequential layer stack with fit/evaluate.

Ref: deeplearning4j-nn `nn/multilayer/MultiLayerNetwork.java` (fit :1571,
feedForward, calcBackpropGradients :1760, score, evaluate) and the Solver
chain `optimize/solvers/{BaseOptimizer,StochasticGradientDescent}.java`.

TPU-first redesign: the whole optimize step — forward, loss, backward,
regularization, clipping, updater — is ONE jit-compiled pure function
(params, opt_state, net_state, step, batch) -> (params, opt_state,
net_state, loss). The reference's Solver/StepFunction/updater-view
machinery collapses into this function; XLA fuses and schedules it onto
the MXU. Listeners observe from the host between steps.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .conf import MultiLayerConfiguration
from .layers import Layer

Params = Dict[str, Any]


def _clip_grads(grads, max_norm, clip_value):
    """Ref: GradientNormalization — per-layer L2 clip and elementwise clip."""
    if clip_value:
        grads = jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -clip_value, clip_value), grads)
    if max_norm:
        def clip_layer(g):
            leaves = jax.tree_util.tree_leaves(g)
            norm = jnp.sqrt(sum(jnp.sum(jnp.square(l)) for l in leaves) + 1e-12)
            scale = jnp.minimum(1.0, max_norm / norm)
            return jax.tree_util.tree_map(lambda l: l * scale, g)
        grads = {k: clip_layer(g) for k, g in grads.items()}
    return grads


def _finite_ok(loss, grads):
    """Scalar bool: loss and EVERY gradient leaf finite (the in-graph
    anomaly flag of the guarded train step — one fused reduction per
    leaf, no host sync)."""
    ok = jnp.all(jnp.isfinite(loss))
    for g in jax.tree_util.tree_leaves(grads):
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
    return ok


def _select_ok(ok, new, old):
    """Per-leaf `where(ok, new, old)` — when ok is True this is the
    new value BITWISE (XLA select of identical shapes), which is what
    makes guarded and unguarded clean runs trajectory-identical."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new, old)


def _regularization_penalty(params, layers_meta):
    """Ref: BaseMultiLayerUpdater.preApply :395 — L1/L2 penalty over layer
    params; biases use the *_bias coefficients."""
    reg = 0.0
    for key, meta in layers_meta.items():
        if key not in params:
            continue
        bias_names = meta.get("bias_params", ("b", "beta"))
        for pname, w in params[key].items():
            is_bias = pname in bias_names
            l1 = meta["l1_bias"] if is_bias else meta["l1"]
            l2 = meta["l2_bias"] if is_bias else meta["l2"]
            if l2:
                reg = reg + 0.5 * l2 * jnp.sum(jnp.square(w))
            if l1:
                reg = reg + l1 * jnp.sum(jnp.abs(w))
    return reg


class MultiLayerNetwork:
    """Sequential network. Public surface mirrors the reference class."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: List[Layer] = conf.layers
        self._params: Optional[Params] = None
        self._net_state: Optional[Params] = None
        self._opt_state: Optional[Any] = None
        self._updaters: Optional[List] = None
        self._step = 0
        self._epoch = 0
        self.listeners: List = []
        self._last_loss = None
        self._rng = jax.random.PRNGKey(conf.seed)
        self._jit_step = None
        self._tbptt_step = None
        self._jit_rnn_step = None
        self._stored_carries = None
        self._jit_forward = {}
        self._input_kind = conf.input_type.kind if conf.input_type else "ff"
        self._input_shape = conf.input_type.shape if conf.input_type else None

    # -- init ----------------------------------------------------------
    def init(self, dtype=jnp.float32) -> "MultiLayerNetwork":
        """Build layer shapes + params (ref: MultiLayerNetwork.init())."""
        if self._input_shape is None:
            raise ValueError("Configuration needs an input_type to init()")
        shape = tuple(self._input_shape)
        if self._input_kind == "cnnflat":
            pass  # layers see the unflattened NHWC shape
        defaults = self.conf.defaults
        keys = jax.random.split(self._rng, len(self.layers) + 1)
        self._rng = keys[0]
        params: Params = {}
        state: Params = {}
        self._layer_keys = []
        for i, layer in enumerate(self.layers):
            layer.build(shape, defaults)
            key = f"layer_{i}" + (f"_{layer.name}" if layer.name else "")
            self._layer_keys.append(key)
            p = layer.init_params(keys[i + 1], dtype)
            if p:
                params[key] = p
            s = layer.init_state()
            if s:
                state[key] = s
            shape = layer.output_shape(shape)
        self._params = params
        self._net_state = state
        # per-layer updaters (ref: layer-level IUpdater overrides the global)
        self._updaters = [l.updater if l.updater is not None else self.conf.updater
                          for l in self.layers]
        self._opt_state = {
            self._layer_keys[i]: self._updaters[i].init_state(params[self._layer_keys[i]])
            for i in range(len(self.layers)) if self._layer_keys[i] in params
        }
        self._layers_meta = {
            self._layer_keys[i]: {"l1": l.l1, "l2": l.l2,
                                  "l1_bias": l.l1_bias, "l2_bias": l.l2_bias,
                                  "bias_params": frozenset(l.bias_param_names())}
            for i, l in enumerate(self.layers)
        }
        self._step = 0
        return self

    # -- forward -------------------------------------------------------
    def _reshape_input(self, x):
        if self._input_kind == "cnnflat":
            h, w, c = self._input_shape
            return x.reshape(x.shape[0], h, w, c)
        return x

    def _init_carries(self, batch: int, dtype=jnp.float32):
        """Zero RNN carries, one slot per layer (None for stateless layers).
        Carries are always floating (int token inputs feed embeddings whose
        outputs — and therefore scan carries — are float)."""
        if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            dtype = jnp.float32
        return [l.init_carry(batch, dtype) if getattr(l, "is_rnn", False) else None
                for l in self.layers]

    def _forward(self, params, net_state, x, train: bool, rng,
                 upto: Optional[int] = None, carries=None, fmask=None):
        """Run layers [0, upto). Returns (activation, new_state, new_carries).

        `carries` holds per-layer RNN state (TBPTT / rnnTimeStep — ref:
        MultiLayerNetwork.rnnActivateUsingStoredState); `fmask` is the
        [B, T] feature mask applied while the activation is a sequence
        (ref: setLayerMaskArrays)."""
        upto = len(self.layers) if upto is None else upto
        new_state = dict(net_state)
        new_carries = list(carries) if carries is not None else \
            self._init_carries(x.shape[0], x.dtype)
        act = x
        if rng is not None:
            layer_rngs = jax.random.split(rng, max(upto, 1))
        for i in range(upto):
            layer = self.layers[i]
            key = self._layer_keys[i]
            p = params.get(key, {})
            s = net_state.get(key, {})
            r = layer_rngs[i] if rng is not None else None
            if layer.weight_noise is not None:
                p = layer._maybe_weight_noise(p, train, r)
            remat = getattr(self.conf, "remat", False) and train
            if getattr(layer, "derives_mask", False):
                # MaskingLayer: derive the feature mask from the data
                # and inject it into the chain for downstream consumers
                derived = layer.derive_mask(act)
                if derived is not None:
                    fmask = derived if fmask is None else fmask * derived
            if getattr(layer, "is_rnn", False):
                m = fmask if act.ndim == 3 else None
                if remat:
                    act, s2, c2 = jax.checkpoint(
                        lambda p_, a_, s_, r_, c_, m_, _l=layer:
                        _l.apply_seq(p_, a_, s_, train, r_, c_, m_))(
                            p, act, s, r, new_carries[i], m)
                else:
                    act, s2, c2 = layer.apply_seq(p, act, s, train, r,
                                                  new_carries[i], m)
                new_carries[i] = c2
            elif getattr(layer, "wants_mask", False):
                # MaskLayer: consumes the current feature mask directly
                # (ref: nn/conf/layers/util/MaskLayer.java). Only [B,T,C]
                # sequence activations take the [B,T] mask — 4D CNN
                # activations don't have a time axis (same rule as the
                # RNN branch above)
                m = fmask if act.ndim == 3 else None
                act, s2 = layer.apply_with_mask(p, act, s, train, r, m)
            elif remat and layer.has_params:
                # jax.checkpoint: recompute this layer's activations in
                # the backward pass instead of storing them (conf.remat)
                act, s2 = jax.checkpoint(
                    lambda p_, a_, s_, r_, _l=layer:
                    _l.apply(p_, a_, s_, train, r_))(p, act, s, r)
            else:
                act, s2 = layer.apply(p, act, s, train, r)
            if s:
                new_state[key] = s2
        return act, new_state, new_carries

    @property
    def _cdt(self):
        """Compute dtype under the mixed-precision policy, or None
        (see nn/precision.py for the policy)."""
        from .precision import compute_dtype
        return compute_dtype(self.conf.dtype)

    def _loss_fn(self, params, net_state, x, y, mask, train: bool, rng,
                 carries=None):
        """Data loss + L1/L2 score terms (ref: BaseLayer.calcRegularizationScore).
        `mask` doubles as the per-timestep feature+label mask for sequence
        models (the common DL4J case where both coincide)."""
        from .precision import (cast_feats_to_f32, cast_input_for_compute,
                                cast_params_for_compute)
        r_fwd = r_out = None
        if rng is not None:
            r_fwd, r_out = jax.random.split(rng)
        cdt = self._cdt
        params_c = cast_params_for_compute(params, {self._layer_keys[-1]},
                                           cdt)
        x = cast_input_for_compute(x, cdt)
        feats, new_state, new_carries = self._forward(
            params_c, net_state, x, train, r_fwd,
            upto=len(self.layers) - 1, carries=carries, fmask=mask)
        feats = cast_feats_to_f32(feats)
        out_layer = self.layers[-1]
        out_key = self._layer_keys[-1]
        lmask = mask
        if mask is not None and feats.ndim == 2 and x.ndim == 3:
            # sequence input collapsed to [B, C] (e.g. LastTimeStep): the
            # [B, T] mask was consumed by the RNN layers and no longer
            # applies per-label. A per-SAMPLE mask on 2D input passes through.
            lmask = None
        data_loss = out_layer.compute_loss(params.get(out_key, {}), feats, y,
                                           lmask, train=train, rng=r_out)
        reg = _regularization_penalty(params, self._layers_meta)
        return data_loss + reg, (new_state, new_carries)

    # -- the one true train step (jitted) ------------------------------
    def _make_step_fn(self, guard: bool = False):
        """The raw (un-jitted) pure train-step function — also consumed by
        parallel.ParallelWrapper, which jits it with mesh shardings.

        ``guard=True`` compiles in the anomaly guard (the training
        analog of serving's poison quarantine): the step additionally
        returns a scalar ``ok`` flag — loss AND every gradient leaf
        finite — and when ``ok`` is False every state output is the
        in-graph-selected ORIGINAL (params, updater state, net state
        unchanged), so one NaN/Inf batch can never corrupt the run.
        The select is `jnp.where(True, new, old) == new` bitwise, so a
        guarded and unguarded run over clean data produce identical
        trajectories. Chosen at build time: one extra compile at
        warmup, zero recompiles after."""
        updaters = self._updaters
        layer_keys = self._layer_keys
        max_norm = self.conf.max_grad_norm
        clip_value = self.conf.grad_clip_value

        layers = self.layers

        def step_fn(params, opt_state, net_state, step, x, y, mask, rng):
            # NOTE: _loss_fn includes the L1/L2 penalty terms, so these
            # grads already carry l2*W + l1*sign(W) (ref semantics:
            # BaseMultiLayerUpdater.preApply adds them to the gradient,
            # and the score includes calcRegularizationScore).
            (loss, (new_net_state, _)), grads = jax.value_and_grad(
                lambda p: self._loss_fn(p, net_state, x, y, mask, True, rng),
                has_aux=True)(params)
            if guard:
                ok = _finite_ok(loss, grads)
            grads = _clip_grads(grads, max_norm, clip_value)
            new_opt = {}
            new_params = {}
            for i, key in enumerate(layer_keys):
                if key not in params:
                    continue
                st, upd = updaters[i].apply(opt_state[key], grads[key], step)
                new_opt[key] = st
                new_p = jax.tree_util.tree_map(
                    lambda p, u: p - u, params[key], upd)
                if layers[i].constraints:
                    # ref: BaseConstraint.applyConstraint — post-update
                    from .conf.constraint import apply_constraints
                    new_p = apply_constraints(layers[i].constraints, new_p,
                                              layers[i].bias_param_names())
                new_params[key] = new_p
            if guard:
                new_params = _select_ok(ok, new_params, params)
                new_opt = _select_ok(ok, new_opt, opt_state)
                new_net_state = _select_ok(ok, new_net_state, net_state)
                return new_params, new_opt, new_net_state, loss, ok
            return new_params, new_opt, new_net_state, loss

        return step_fn

    def _make_step(self, guard: bool = False):
        return jax.jit(self._make_step_fn(guard=guard),
                       donate_argnums=(0, 1, 2))

    def _make_tbptt_step(self):
        """Truncated-BPTT chunk step: like the regular step but threads RNN
        carries across chunks, gradient-stopped at the boundary (ref:
        MultiLayerNetwork.doTruncatedBPTT :1637 + rnnActivateUsingStoredState)."""
        updaters = self._updaters
        layer_keys = self._layer_keys
        max_norm = self.conf.max_grad_norm
        clip_value = self.conf.grad_clip_value

        def step_fn(params, opt_state, net_state, step, x, y, mask, rng, carries):
            carries = jax.tree_util.tree_map(lax.stop_gradient, carries)
            (loss, (new_net_state, new_carries)), grads = jax.value_and_grad(
                lambda p: self._loss_fn(p, net_state, x, y, mask, True, rng,
                                        carries=carries),
                has_aux=True)(params)
            grads = _clip_grads(grads, max_norm, clip_value)
            new_opt = {}
            new_params = {}
            for i, key in enumerate(layer_keys):
                if key not in params:
                    continue
                st, upd = updaters[i].apply(opt_state[key], grads[key], step)
                new_opt[key] = st
                new_params[key] = jax.tree_util.tree_map(
                    lambda p, u: p - u, params[key], upd)
            return new_params, new_opt, new_net_state, loss, new_carries

        return jax.jit(step_fn, donate_argnums=(0, 1, 2))

    # -- public API ----------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1, mask=None):
        """Train. `data` is a DataSetIterator-like (yields (x, y) or DataSet)
        or a raw array with `labels` (ref: MultiLayerNetwork.fit overloads)."""
        if self._params is None:
            self.init()
        if self._jit_step is None:
            self._jit_step = self._make_step()
        if labels is not None:
            batches = [(data, labels, mask)]
            iterator = None
        else:
            iterator = data
            if not hasattr(iterator, "reset") and not isinstance(iterator, (list, tuple)):
                # a plain generator exhausts after one epoch and would
                # silently yield nothing on later epochs — materialize it
                iterator = list(iterator)
        tbptt = self.conf.tbptt_fwd_length
        for _ in range(epochs):
            if iterator is not None:
                batches = ((b[0], b[1], b[2] if len(b) > 2 else None)
                           for b in (self._unpack(it) for it in iterator))
            for x, y, m in batches:
                x = self._reshape_input(jnp.asarray(x))
                y = jnp.asarray(y)
                t0 = time.perf_counter()
                self._rng, sub = jax.random.split(self._rng)
                if tbptt and x.ndim == 3 and x.shape[1] > tbptt:
                    loss = self._fit_tbptt(x, y, m, tbptt)
                else:
                    self._params, self._opt_state, self._net_state, loss = self._jit_step(
                        self._params, self._opt_state, self._net_state,
                        jnp.asarray(self._step), x, y,
                        None if m is None else jnp.asarray(m), sub)
                self._step += 1
                # keep the loss on device: converting forces a host sync and
                # defeats async dispatch; listeners that read .score_ pay the
                # sync only at their reporting frequency
                self._last_loss = loss
                dur = time.perf_counter() - t0
                for lst in self.listeners:
                    lst.iteration_done(self, self._step, self._epoch)
                    if hasattr(lst, "on_timing"):
                        lst.on_timing(self, dur, x.shape[0])
            self._epoch += 1
            for lst in self.listeners:
                if hasattr(lst, "on_epoch_end"):
                    lst.on_epoch_end(self)
        return self

    def _fit_tbptt(self, x, y, m, tbptt: int):
        """Chunked fwd/bwd over time with carried (gradient-stopped) RNN
        state — ref: MultiLayerNetwork.doTruncatedBPTT (:1637): equal
        fwd/bwd truncation lengths, state carried via stored-state activate.
        Ragged tails are padded to the chunk length with mask=0 so every
        chunk hits the same compiled program (XLA: one shape signature)."""
        if self._tbptt_step is None:
            self._tbptt_step = self._make_tbptt_step()
        T = x.shape[1]
        if m is None:
            m = jnp.ones(x.shape[:2], x.dtype)
        else:
            m = jnp.asarray(m)
        carries = self._init_carries(x.shape[0], x.dtype)
        loss = None
        for t0 in range(0, T, tbptt):
            xc = x[:, t0:t0 + tbptt]
            yc = y[:, t0:t0 + tbptt] if y.ndim == 3 else y
            mc = m[:, t0:t0 + tbptt]
            pad = tbptt - xc.shape[1]
            if pad:
                xc = jnp.pad(xc, ((0, 0), (0, pad), (0, 0)))
                if yc.ndim == 3:
                    yc = jnp.pad(yc, ((0, 0), (0, pad), (0, 0)))
                mc = jnp.pad(mc, ((0, 0), (0, pad)))
            self._rng, sub = jax.random.split(self._rng)
            (self._params, self._opt_state, self._net_state, loss,
             carries) = self._tbptt_step(
                self._params, self._opt_state, self._net_state,
                jnp.asarray(self._step), xc, yc, mc, sub, carries)
        return loss

    # -- layerwise unsupervised pretraining (ref: MultiLayerNetwork.pretrain
    # :~1100 — used by the VariationalAutoencoder layer) -----------------
    def pretrain(self, iterator, epochs: int = 1):
        """Unsupervised layerwise pretraining: every pretrainable layer
        (VAE) is trained in stack order on the activations of the layers
        below it (ref: MultiLayerNetwork.pretrain(DataSetIterator))."""
        if self._params is None:
            self.init()
        # materialize generators once — a plain generator would be
        # exhausted by the first pretrainable layer and silently yield
        # zero batches for the next (same guard as fit())
        if not hasattr(iterator, "reset") and \
                not isinstance(iterator, (list, tuple)):
            iterator = list(iterator)
        for i, layer in enumerate(self.layers):
            if getattr(layer, "is_pretrain_layer", False):
                self.pretrain_layer(i, iterator, epochs=epochs)
        return self

    def pretrain_layer(self, i: int, iterator, epochs: int = 1):
        """Pretrain layer i on its unsupervised loss (ref:
        MultiLayerNetwork.pretrainLayer). Inputs are the frozen forward
        activations of layers [0, i); only layer i's params move."""
        layer = self.layers[i]
        if not getattr(layer, "is_pretrain_layer", False):
            raise ValueError(f"layer {i} ({type(layer).__name__}) is not "
                             "pretrainable")
        key = self._layer_keys[i]
        updater = self._updaters[i]

        @jax.jit
        def pre_step(p, opt, step, feats, rng):
            loss, g = jax.value_and_grad(
                lambda pp: layer.pretrain_loss(pp, feats, rng))(p)
            st, upd = updater.apply(opt, g, step)
            new_p = jax.tree_util.tree_map(lambda a, u: a - u, p, upd)
            return new_p, st, loss

        @jax.jit
        def features(params, net_state, x):
            act, _, _ = self._forward(params, net_state, x, False, None,
                                      upto=i)
            return act

        p, opt = self._params[key], self._opt_state[key]
        step = 0
        data = iterator if isinstance(iterator, (list, tuple)) \
            else list(iterator)
        loss = None
        for _ in range(epochs):
            for item in data:
                x = self._unpack(item)[0]
                x = self._reshape_input(jnp.asarray(x))
                feats = features(self._params, self._net_state, x)
                self._rng, sub = jax.random.split(self._rng)
                p, opt, loss = pre_step(p, opt, jnp.asarray(step), feats,
                                        sub)
                step += 1
        self._params[key] = p
        self._opt_state[key] = opt
        self._last_loss = loss
        return self

    # -- stateful RNN inference (ref: rnnTimeStep / rnnClearPreviousState)
    def rnn_time_step(self, x):
        """Run a [B, T, C] (or [B, C] single-step) segment, carrying hidden
        state across calls (ref: MultiLayerNetwork.rnnTimeStep)."""
        x = jnp.asarray(x)
        # [B, C] float = one timestep (ref rnnTimeStep 2D overload);
        # [B, T] int = a token sequence for an embedding front-end
        squeeze = x.ndim == 2 and jnp.issubdtype(x.dtype, jnp.floating)
        if squeeze:
            x = x[:, None, :]
        if self._stored_carries is None:
            self._stored_carries = self._init_carries(x.shape[0], x.dtype)
        if self._jit_rnn_step is None:
            def fwd(params, net_state, x, carries):
                act, _, c2 = self._forward(params, net_state, x, False, None,
                                           carries=carries)
                return act, c2
            # donate the carries: each streaming step replaces them, so
            # the old buffers can be reused in place
            self._jit_rnn_step = jax.jit(fwd, donate_argnums=(3,))
        out, self._stored_carries = self._jit_rnn_step(
            self._params, self._net_state, x, self._stored_carries)
        return out[:, 0] if squeeze and out.ndim == 3 else out

    def rnn_clear_previous_state(self):
        self._stored_carries = None

    @staticmethod
    def _unpack(item):
        if isinstance(item, tuple):
            return item
        # DataSet-like
        return (item.features, item.labels,
                getattr(item, "labels_mask", None))

    def output(self, x, train: bool = False, mask=None):
        """Inference forward pass (ref: MultiLayerNetwork.output; `mask`
        is the [B, T] feature mask — ref: the featuresMask overload /
        setLayerMaskArrays)."""
        if self._params is None:
            self.init()
        x = self._reshape_input(jnp.asarray(x))
        key = ("out", train, mask is not None)
        if key not in self._jit_forward:
            def fwd(params, net_state, x, fmask):
                act, _, _ = self._forward(params, net_state, x, train, None,
                                          fmask=fmask)
                return act
            self._jit_forward[key] = jax.jit(fwd)
        return self._jit_forward[key](
            self._params, self._net_state, x,
            None if mask is None else jnp.asarray(mask))

    def feed_forward(self, x, train: bool = False):
        """All layer activations (ref: feedForward returns the list)."""
        x = self._reshape_input(jnp.asarray(x))
        acts = [x]
        act = x
        carries = self._init_carries(x.shape[0], x.dtype)
        for i in range(len(self.layers)):
            layer = self.layers[i]
            p = self._params.get(self._layer_keys[i], {})
            s = self._net_state.get(self._layer_keys[i], {})
            if getattr(layer, "is_rnn", False):
                act, _, _ = layer.apply_seq(p, act, s, train, None,
                                            carries[i], None)
            else:
                act, _ = layer.apply(p, act, s, train, None)
            acts.append(act)
        return acts

    @property
    def score_(self) -> float:
        """Last minibatch loss (host-syncs on read)."""
        return float("nan") if self._last_loss is None else float(self._last_loss)

    def score(self, x=None, y=None, mask=None) -> float:
        """Loss on a dataset, or last minibatch score (ref: score())."""
        if x is None:
            return self.score_
        x = self._reshape_input(jnp.asarray(x))
        loss, _ = self._loss_fn(self._params, self._net_state, x, jnp.asarray(y),
                                None if mask is None else jnp.asarray(mask),
                                False, None)
        return float(loss)

    def evaluate(self, iterator):
        """Classification evaluation (ref: MultiLayerNetwork.evaluate)."""
        from ..eval import Evaluation
        ev = Evaluation()
        for item in iterator:
            if isinstance(item, tuple):
                x, y, *rest = item
                m = rest[0] if rest else None
            else:
                x, y = item.features, item.labels
                m = getattr(item, "labels_mask", None)
            out = self.output(x)
            ev.eval(np.asarray(y), np.asarray(out),
                    None if m is None else np.asarray(m))
        return ev

    # -- introspection (ref: summary(), numParams(), params()) ---------
    def num_params(self) -> int:
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(self._params))

    def params(self) -> Params:
        return self._params

    def set_params(self, params: Params):
        self._params = params

    def get_updater_state(self):
        return self._opt_state

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def summary(self) -> str:
        if self._params is None:
            self.init()
        lines = ["=" * 70,
                 f"{'idx':<4}{'layer':<22}{'out shape':<20}{'params':<10}",
                 "-" * 70]
        shape = tuple(self._input_shape)
        for i, l in enumerate(self.layers):
            out = l.output_shape(shape) if l._built else "?"
            lines.append(f"{i:<4}{type(l).__name__:<22}{str(out):<20}{l.n_params():<10}")
            shape = out if isinstance(out, tuple) else shape
        lines.append("-" * 70)
        lines.append(f"Total params: {self.num_params()}")
        lines.append("=" * 70)
        return "\n".join(lines)

    def clone(self) -> "MultiLayerNetwork":
        from copy import deepcopy
        m = MultiLayerNetwork(MultiLayerConfiguration.from_json(self.conf.to_json()))
        if self._params is not None:
            m.init()
            m._params = deepcopy(self._params)
            m._net_state = deepcopy(self._net_state)
        return m
