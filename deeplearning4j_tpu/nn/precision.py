"""Mixed-precision policy shared by MultiLayerNetwork and ComputationGraph.

Ref: the reference's global dtype switch (`ND4JSystemProperties.DTYPE`,
`NeuralNetConfiguration.Builder.dataType` — DataType.HALF on CUDA). TPU
redesign: "half" is bfloat16 on the MXU; the policy is standard bf16
mixed precision — cast the forward/backward COMPUTE to bf16 while master
params, updater state, BatchNorm statistics, the output layer, and the
loss stay float32. bf16 keeps f32's exponent range, so no loss scaling
is needed (unlike fp16).
"""
from __future__ import annotations

from typing import Dict, Optional, Set

import jax
import jax.numpy as jnp

_HALF_NAMES = ("bfloat16", "bf16", "half", "float16", "fp16")


def compute_dtype(conf_dtype: Optional[str]):
    """Map a configuration dtype string to the compute dtype, or None
    for pure f32."""
    if (conf_dtype or "float").lower() in _HALF_NAMES:
        return jnp.bfloat16
    return None


def cast_params_for_compute(params: Dict, exempt_keys: Set[str], cdt):
    """Cast every f32 param leaf to `cdt`, except layers in
    `exempt_keys` (the output layers — logits/softmax/loss stay f32)."""
    if cdt is None:
        return params
    return {
        k: (p if k in exempt_keys else jax.tree_util.tree_map(
            lambda a: a.astype(cdt) if a.dtype == jnp.float32 else a, p))
        for k, p in params.items()}


def cast_input_for_compute(x, cdt):
    if cdt is None or not jnp.issubdtype(x.dtype, jnp.floating):
        return x
    return x.astype(cdt)


def cast_feats_to_f32(feats):
    """Promote pre-output activations back to f32 for the loss."""
    if feats.dtype != jnp.float32 and jnp.issubdtype(feats.dtype,
                                                     jnp.floating):
        return feats.astype(jnp.float32)
    return feats
