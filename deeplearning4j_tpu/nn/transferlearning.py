"""Transfer learning (ref: D7 —
`nn/transferlearning/TransferLearning.java:54-108`: Builder over a
trained network with setFeatureExtractor (freeze up to a layer),
removeOutputLayer / removeLayersFromOutput, addLayer,
nOutReplace, fineTuneConfiguration; `FineTuneConfiguration.java`).

The rebuilt network copies retained layers' trained params; frozen
layers wrap in FrozenLayer (stop_gradient — see
nn/layers/convolutional.FrozenLayer), so the compiled step simply never
produces gradients for them.
"""
from __future__ import annotations

import copy
from typing import List, Optional

import jax
import jax.numpy as jnp

from .. import learning
from .conf import MultiLayerConfiguration
from .layers import Layer
from .layers.convolutional import FrozenLayer
from .multilayer import MultiLayerNetwork


class FineTuneConfiguration:
    """Ref: FineTuneConfiguration.java — overrides applied to the whole
    rebuilt network (updater/lr, seed)."""

    def __init__(self, updater=None, seed: Optional[int] = None):
        self.updater = learning.get(updater) if updater is not None \
            else None
        self.seed = seed

    class Builder:
        def __init__(self):
            self._kw = {}

        def updater(self, u):
            self._kw["updater"] = u
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def build(self):
            return FineTuneConfiguration(**self._kw)

    @staticmethod
    def builder():
        return FineTuneConfiguration.Builder()


class TransferLearning:
    """Ref: TransferLearning.Builder (:54)."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if net._params is None:
                net.init()
            self._net = net
            self._layers: List[Layer] = [copy.deepcopy(l)
                                         for l in net.layers]
            # params copied per original layer index (None once removed)
            self._params: List = [
                jax.tree_util.tree_map(
                    jnp.copy, net._params.get(net._layer_keys[i]))
                if net._layer_keys[i] in net._params else None
                for i in range(len(net.layers))]
            self._state: List = [
                jax.tree_util.tree_map(
                    jnp.copy, net._net_state[net._layer_keys[i]])
                if net._layer_keys[i] in net._net_state else None
                for i in range(len(net.layers))]
            self._freeze_until = -1
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._appended: List[Layer] = []

        def fine_tune_configuration(self, cfg: FineTuneConfiguration):
            self._fine_tune = cfg
            return self

        def set_feature_extractor(self, layer_index: int):
            """Freeze layers [0..layer_index] (ref: setFeatureExtractor)."""
            self._freeze_until = layer_index
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            for _ in range(n):
                self._layers.pop()
                self._params.pop()
                self._state.pop()
            return self

        def add_layer(self, layer: Layer):
            self._layers.append(layer)
            self._params.append(None)
            self._state.append(None)
            return self

        def build(self) -> MultiLayerNetwork:
            old_conf = self._net.conf
            layers: List[Layer] = []
            for i, l in enumerate(self._layers):
                if i <= self._freeze_until:
                    layers.append(l if isinstance(l, FrozenLayer)
                                  else FrozenLayer(l))
                else:
                    layers.append(l)
            updater = old_conf.updater
            seed = old_conf.seed
            if self._fine_tune is not None:
                if self._fine_tune.updater is not None:
                    updater = self._fine_tune.updater
                if self._fine_tune.seed is not None:
                    seed = self._fine_tune.seed
            conf = MultiLayerConfiguration(
                layers=layers, seed=seed, updater=updater,
                defaults=old_conf.defaults,
                input_type=old_conf.input_type,
                tbptt_fwd_length=old_conf.tbptt_fwd_length,
                tbptt_bwd_length=old_conf.tbptt_bwd_length,
                max_grad_norm=old_conf.max_grad_norm,
                grad_clip_value=old_conf.grad_clip_value,
                dtype=old_conf.dtype,
                remat=getattr(old_conf, "remat", False))
            net = MultiLayerNetwork(conf).init()
            # restore trained params/state for retained layers
            for i, (p, s) in enumerate(zip(self._params, self._state)):
                key = net._layer_keys[i]
                if p is not None and key in net._params:
                    net._params[key] = p
                if s is not None and key in net._net_state:
                    net._net_state[key] = s
            # rebuild optimizer state against the restored params
            net._opt_state = {
                net._layer_keys[i]: net._updaters[i].init_state(
                    net._params[net._layer_keys[i]])
                for i in range(len(net.layers))
                if net._layer_keys[i] in net._params}
            return net

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearning.Builder":
        return TransferLearning.Builder(net)
