"""Dropout / noise schemes (ref: `nn/conf/dropout/` in deeplearning4j-nn:
`Dropout.java`, `GaussianDropout.java`, `AlphaDropout.java`,
`SpatialDropout.java`, `GaussianNoise.java` — all implementing
`IDropout.applyDropout`).

TPU-first: each scheme is a pure function of (x, rng, train); layers call
``apply`` on their configured scheme inside the jitted step, so the mask
generation fuses into the surrounding compute. A plain float ``dropout=p``
on a layer remains shorthand for ``Dropout(p)`` (reference behaviour:
``dropOut(double)`` wraps into a ``Dropout``).

Note on convention: the reference's ``Dropout(x)`` constructor takes the
RETAIN probability; this package follows the modern convention where
``dropout=p`` is the DROP probability (documented divergence — kept
because every other config in this package already used drop-probability
floats). ``AlphaDropout``/``GaussianDropout`` take the drop/rate params
with the reference's own meanings.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


class IDropout:
    """Base scheme (ref: `nn/conf/dropout/IDropout.java`)."""

    kind = "dropout"

    def apply(self, x, rng, train: bool):
        raise NotImplementedError

    def to_json(self) -> Dict[str, Any]:
        d = {"@class": self.kind}
        d.update(self._extra_json())
        return d

    def _extra_json(self) -> Dict[str, Any]:
        return {}

    def __eq__(self, other):
        return type(self) is type(other) and self.to_json() == other.to_json()


class Dropout(IDropout):
    """Inverted Bernoulli dropout (ref: `nn/conf/dropout/Dropout.java` —
    zero with probability p, scale survivors by 1/(1-p))."""

    kind = "dropout"

    def __init__(self, p: float = 0.5):
        self.p = float(p)

    def apply(self, x, rng, train):
        if not train or not self.p or rng is None:
            return x
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))

    def _extra_json(self):
        return {"p": self.p}


class GaussianDropout(IDropout):
    """Multiplicative unit-mean Gaussian noise (ref:
    `GaussianDropout.java`: x * N(1, rate/(1-rate)) — Srivastava et al.'s
    Gaussian variant; already unbiased, no inverted rescale)."""

    kind = "gaussian_dropout"

    def __init__(self, rate: float = 0.5):
        self.rate = float(rate)

    def apply(self, x, rng, train):
        if not train or not self.rate or rng is None:
            return x
        stddev = jnp.sqrt(self.rate / (1.0 - self.rate))
        noise = 1.0 + stddev * jax.random.normal(rng, x.shape, x.dtype)
        return x * noise

    def _extra_json(self):
        return {"rate": self.rate}


class GaussianNoise(IDropout):
    """Additive zero-mean Gaussian noise (ref: `GaussianNoise.java`)."""

    kind = "gaussian_noise"

    def __init__(self, stddev: float = 0.1):
        self.stddev = float(stddev)

    def apply(self, x, rng, train):
        if not train or not self.stddev or rng is None:
            return x
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)

    def _extra_json(self):
        return {"stddev": self.stddev}


class AlphaDropout(IDropout):
    """SELU-preserving dropout (ref: `AlphaDropout.java`, Klambauer et al.
    2017): dropped units are set to alpha' = -lambda*alpha, then the
    affine (a, b) correction restores zero mean / unit variance so
    self-normalizing nets stay self-normalizing."""

    kind = "alpha_dropout"

    # SELU constants (ref: AlphaDropout.java DEFAULT_ALPHA/LAMBDA)
    ALPHA = 1.6732632423543772
    LAMBDA = 1.0507009873554805

    def __init__(self, p: float = 0.05):
        self.p = float(p)

    def apply(self, x, rng, train):
        if not train or not self.p or rng is None:
            return x
        keep = 1.0 - self.p
        alpha_prime = -self.LAMBDA * self.ALPHA
        a = (keep + alpha_prime ** 2 * keep * (1 - keep)) ** -0.5
        b = -a * alpha_prime * (1 - keep)
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return a * jnp.where(mask, x, jnp.asarray(alpha_prime, x.dtype)) + b

    def _extra_json(self):
        return {"p": self.p}


class SpatialDropout(IDropout):
    """Drop whole feature maps / channels (ref: `SpatialDropout.java`,
    Tompson et al. 2015). For NHWC images the mask is per (batch,
    channel); for [B, T, C] sequences per (batch, channel) across time;
    for 2D input falls back to plain dropout."""

    kind = "spatial_dropout"

    def __init__(self, p: float = 0.5):
        self.p = float(p)

    def apply(self, x, rng, train):
        if not train or not self.p or rng is None:
            return x
        keep = 1.0 - self.p
        if x.ndim <= 2:
            mask = jax.random.bernoulli(rng, keep, x.shape)
        else:
            # broadcast over all middle (spatial/time) axes: [B, 1..., C]
            shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
            mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))

    def _extra_json(self):
        return {"p": self.p}


_REGISTRY = {c.kind: c for c in
             (Dropout, GaussianDropout, GaussianNoise, AlphaDropout,
              SpatialDropout)}


def get(spec) -> Optional[IDropout]:
    """Normalize a layer's dropout spec: None | float | IDropout | json
    dict -> IDropout or None (ref: Layer.Builder.dropOut overloads)."""
    if spec is None:
        return None
    if isinstance(spec, IDropout):
        return spec
    if isinstance(spec, dict):
        d = dict(spec)
        kind = d.pop("@class")
        return _REGISTRY[kind](**d)
    p = float(spec)
    return Dropout(p) if p else None


def from_json(d: dict) -> IDropout:
    return get(d)
