"""Parameter constraints (ref: `nn/conf/constraint/` in deeplearning4j-nn:
`BaseConstraint.java` (applyConstraint — called AFTER each parameter
update), `MaxNormConstraint.java`, `MinMaxNormConstraint.java`,
`UnitNormConstraint.java`, `NonNegativeConstraint.java`).

TPU-first: a constraint is a pure projection applied to the updated
weight inside the jitted train step (`MultiLayerNetwork._make_step_fn` /
`ComputationGraph._make_step_fn`), so it fuses with the updater math.
Reference semantics preserved:
- norms are computed over the input dimensions of the weight (all axes
  except the last — the reference defaults to dimension 0 for dense,
  [1,2,3] for conv, i.e. "per output unit"),
- constraints apply to WEIGHT params only by default
  (`BaseConstraint.applyToWeights`; biases opt-in via apply_to_biases).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax.numpy as jnp


class LayerConstraint:
    """Base (ref: `api/layers/LayerConstraint.java` + BaseConstraint)."""

    kind = "constraint"

    def __init__(self, apply_to_weights: bool = True,
                 apply_to_biases: bool = False):
        self.apply_to_weights = bool(apply_to_weights)
        self.apply_to_biases = bool(apply_to_biases)

    def project(self, w):
        """The projection itself (ref: BaseConstraint.apply)."""
        raise NotImplementedError

    def applies_to(self, param_name: str, bias_names) -> bool:
        is_bias = param_name in bias_names
        return self.apply_to_biases if is_bias else self.apply_to_weights

    @staticmethod
    def _norm(w, eps: float = 1e-8):
        """L2 norm per output unit: reduce over all axes except the last
        (dense [in, out] -> per-column; conv HWIO -> per output channel;
        matches BaseConstraint's default dimensions)."""
        axes = tuple(range(w.ndim - 1)) or (0,)
        return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True)) \
            + eps

    def to_json(self) -> Dict[str, Any]:
        d = {"@class": self.kind,
             "apply_to_weights": self.apply_to_weights,
             "apply_to_biases": self.apply_to_biases}
        d.update(self._extra_json())
        return d

    def _extra_json(self) -> Dict[str, Any]:
        return {}

    def __eq__(self, other):
        return type(self) is type(other) and self.to_json() == other.to_json()


class MaxNormConstraint(LayerConstraint):
    """Rescale any unit whose norm exceeds max_norm down to it (ref:
    `MaxNormConstraint.java`)."""

    kind = "max_norm"

    def __init__(self, max_norm: float = 1.0, **kw):
        super().__init__(**kw)
        self.max_norm = float(max_norm)

    def project(self, w):
        n = self._norm(w)
        return w * jnp.minimum(1.0, self.max_norm / n)

    def _extra_json(self):
        return {"max_norm": self.max_norm}


class MinMaxNormConstraint(LayerConstraint):
    """Clamp unit norms into [min, max] with interpolation rate (ref:
    `MinMaxNormConstraint.java`: w *= (rate*clip(n,min,max)/n + 1-rate))."""

    kind = "min_max_norm"

    def __init__(self, min_norm: float = 0.0, max_norm: float = 1.0,
                 rate: float = 1.0, **kw):
        super().__init__(**kw)
        self.min_norm = float(min_norm)
        self.max_norm = float(max_norm)
        self.rate = float(rate)

    def project(self, w):
        n = self._norm(w)
        clipped = jnp.clip(n, self.min_norm, self.max_norm)
        scale = self.rate * clipped / n + (1.0 - self.rate)
        return w * scale

    def _extra_json(self):
        return {"min_norm": self.min_norm, "max_norm": self.max_norm,
                "rate": self.rate}


class UnitNormConstraint(LayerConstraint):
    """Normalize every unit to norm 1 (ref: `UnitNormConstraint.java`)."""

    kind = "unit_norm"

    def project(self, w):
        return w / self._norm(w)


class NonNegativeConstraint(LayerConstraint):
    """Clamp negatives to zero (ref: `NonNegativeConstraint.java`)."""

    kind = "non_negative"

    def __init__(self, **kw):
        # applies to everything by default in the reference
        kw.setdefault("apply_to_biases", True)
        super().__init__(**kw)

    def project(self, w):
        return jnp.maximum(w, 0.0)


_REGISTRY = {c.kind: c for c in
             (MaxNormConstraint, MinMaxNormConstraint, UnitNormConstraint,
              NonNegativeConstraint)}


def get(spec) -> Optional[LayerConstraint]:
    if spec is None or isinstance(spec, LayerConstraint):
        return spec
    d = dict(spec)
    kind = d.pop("@class")
    return _REGISTRY[kind](**d)


def from_json(d: dict) -> LayerConstraint:
    return get(d)


def apply_constraints(constraints: Sequence[LayerConstraint],
                      params: Dict[str, Any], bias_names) -> Dict[str, Any]:
    """Project a layer's updated params (ref: BaseConstraint.applyConstraint
    invoked from the updater path post-update)."""
    if not constraints:
        return params
    out = dict(params)
    for name, w in params.items():
        for c in constraints:
            if c.applies_to(name, bias_names):
                w = c.project(w)
        out[name] = w
    return out
