"""Weight noise schemes (ref: `nn/conf/weightnoise/` in deeplearning4j-nn:
`DropConnect.java`, `WeightNoise.java` implementing `IWeightNoise` —
applied to the WEIGHTS each forward pass during training, as opposed to
dropout which perturbs activations).

TPU-first: a pure transform over the layer's weight params inside the
jitted step; the per-step Bernoulli/Gaussian mask fuses into the
matmul's producers. Applied to weight params only (reference:
`DropConnect.getParameter` applies to weights via the
paramname-is-weight check), never to biases or norm gains.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


class IWeightNoise:
    """Base (ref: `nn/conf/weightnoise/IWeightNoise.java`)."""

    kind = "weightnoise"

    def apply(self, w, rng, train: bool):
        raise NotImplementedError

    def to_json(self) -> Dict[str, Any]:
        d = {"@class": self.kind}
        d.update(self._extra_json())
        return d

    def _extra_json(self) -> Dict[str, Any]:
        return {}

    def __eq__(self, other):
        return type(self) is type(other) and self.to_json() == other.to_json()


class DropConnect(IWeightNoise):
    """Bernoulli weight masking (ref: `DropConnect.java`, Wan et al. 2013):
    each weight is zeroed with probability 1-keep each step. Like the
    reference, applied at train time only and NOT rescaled (the reference
    applies the raw mask)."""

    kind = "dropconnect"

    def __init__(self, keep_prob: float = 0.5):
        self.keep_prob = float(keep_prob)

    def apply(self, w, rng, train):
        if not train or self.keep_prob >= 1.0 or rng is None:
            return w
        mask = jax.random.bernoulli(rng, self.keep_prob, w.shape)
        return jnp.where(mask, w, jnp.zeros((), w.dtype))

    def _extra_json(self):
        return {"keep_prob": self.keep_prob}


class WeightNoise(IWeightNoise):
    """Additive or multiplicative Gaussian weight noise (ref:
    `WeightNoise.java` — takes a distribution + additive flag)."""

    kind = "weight_gaussian_noise"

    def __init__(self, stddev: float = 0.1, mean: float = 0.0,
                 additive: bool = True):
        self.stddev = float(stddev)
        self.mean = float(mean)
        self.additive = bool(additive)

    def apply(self, w, rng, train):
        if not train or rng is None:
            return w
        noise = self.mean + self.stddev * jax.random.normal(
            rng, w.shape, w.dtype)
        return w + noise if self.additive else w * noise

    def _extra_json(self):
        return {"stddev": self.stddev, "mean": self.mean,
                "additive": self.additive}


_REGISTRY = {c.kind: c for c in (DropConnect, WeightNoise)}


def get(spec) -> Optional[IWeightNoise]:
    if spec is None or isinstance(spec, IWeightNoise):
        return spec
    d = dict(spec)
    kind = d.pop("@class")
    return _REGISTRY[kind](**d)


def from_json(d: dict) -> IWeightNoise:
    return get(d)
