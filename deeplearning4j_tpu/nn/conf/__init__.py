"""Configuration DSL.

Ref: deeplearning4j-nn `nn/conf/NeuralNetConfiguration.java` (builder at
:~400, ListBuilder), `MultiLayerConfiguration.java` (JSON round-trip via
Jackson — here: plain-JSON `to_json`/`from_json`).

The builder mirrors the reference's fluent surface:

    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater(Adam(1e-3))
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent", activation="softmax"))
            .input_type_convolutional(28, 28, 1)
            .build())
    model = MultiLayerNetwork(conf)

Workspace/cache modes from the reference are accepted and recorded for API
parity but are no-ops: XLA owns memory planning on TPU (SURVEY.md §7 hard
part 6).
"""
from __future__ import annotations

import json
from typing import List, Optional, Sequence, Tuple

from ... import learning as U
from ..layers import Layer, from_json as layer_from_json

Shape = Tuple[int, ...]


class InputType:
    """Ref: `nn/conf/inputs/InputType.java` — feedForward / recurrent /
    convolutional (here NHWC) / convolutionalFlat."""

    def __init__(self, kind: str, shape: Shape):
        self.kind = kind
        self.shape = tuple(int(s) for s in shape)

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", (size,))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType("rnn", (timesteps or -1, size))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", (height, width, channels))  # NHWC

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnnflat", (height, width, channels))

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int,
                        channels: int) -> "InputType":
        """NDHWC volumes (ref: InputType.convolutional3D)."""
        return InputType("cnn3d", (depth, height, width, channels))

    def to_json(self):
        return {"kind": self.kind, "shape": list(self.shape)}

    @staticmethod
    def from_json(d):
        return InputType(d["kind"], tuple(d["shape"]))


class MultiLayerConfiguration:
    """Ref: `nn/conf/MultiLayerConfiguration.java`."""

    def __init__(self, layers: List[Layer], seed: int = 12345,
                 updater=None, defaults: Optional[dict] = None,
                 input_type: Optional[InputType] = None,
                 tbptt_fwd_length: int = 0, tbptt_bwd_length: int = 0,
                 max_grad_norm: Optional[float] = None,
                 grad_clip_value: Optional[float] = None,
                 dtype: str = "float", remat: bool = False):
        self.layers = layers
        self.seed = int(seed)
        self.updater = U.get(updater) if updater is not None else U.Sgd(0.1)
        self.defaults = defaults or {}
        self.input_type = input_type
        self.tbptt_fwd_length = tbptt_fwd_length
        self.tbptt_bwd_length = tbptt_bwd_length
        self.max_grad_norm = max_grad_norm      # GradientNormalization.ClipL2PerLayer analog
        self.grad_clip_value = grad_clip_value  # ClipElementWiseAbsoluteValue analog
        self.dtype = dtype
        # per-layer rematerialization (jax.checkpoint): trade FLOPs for
        # HBM — activations are recomputed in the backward pass instead
        # of stored. The TPU-native counterpart of the reference's
        # CacheMode.NONE workspace economy knob.
        self.remat = bool(remat)

    # -- serde (the JSON round-trip property that powers golden-file tests
    # and Keras import in the reference) ---------------------------------
    @staticmethod
    def _defaults_to_json(defaults: dict) -> dict:
        out = {}
        for k, v in defaults.items():
            if isinstance(v, list):
                out[k] = [x.to_json() if hasattr(x, "to_json") else x
                          for x in v]
            else:
                out[k] = v.to_json() if hasattr(v, "to_json") else v
        return out

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "updater": self.updater.to_json(),
            "defaults": self._defaults_to_json(self.defaults),
            "input_type": self.input_type.to_json() if self.input_type else None,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_bwd_length": self.tbptt_bwd_length,
            "max_grad_norm": self.max_grad_norm,
            "grad_clip_value": self.grad_clip_value,
            "dtype": self.dtype,
            "remat": self.remat,
            "layers": [l.to_json() for l in self.layers],
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        defaults = d.get("defaults", {})
        if isinstance(defaults.get("updater"), dict):
            defaults["updater"] = U.get(defaults["updater"])
        return MultiLayerConfiguration(
            layers=[layer_from_json(ld) for ld in d["layers"]],
            seed=d.get("seed", 12345),
            updater=U.get(d["updater"]) if d.get("updater") else None,
            defaults=defaults,
            input_type=InputType.from_json(d["input_type"]) if d.get("input_type") else None,
            tbptt_fwd_length=d.get("tbptt_fwd_length", 0),
            tbptt_bwd_length=d.get("tbptt_bwd_length", 0),
            max_grad_norm=d.get("max_grad_norm"),
            grad_clip_value=d.get("grad_clip_value"),
            dtype=d.get("dtype", "float"),
            remat=d.get("remat", False),
        )


class ListBuilder:
    """Ref: NeuralNetConfiguration.ListBuilder."""

    def __init__(self, base: "NeuralNetConfiguration"):
        self._base = base
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._tbptt = (0, 0)

    def layer(self, layer: Layer) -> "ListBuilder":
        self._layers.append(layer)
        return self

    def input_type(self, it: InputType) -> "ListBuilder":
        self._input_type = it
        return self

    def input_type_feed_forward(self, size: int) -> "ListBuilder":
        return self.input_type(InputType.feed_forward(size))

    def input_type_convolutional(self, h: int, w: int, c: int) -> "ListBuilder":
        return self.input_type(InputType.convolutional(h, w, c))

    def input_type_recurrent(self, size: int, timesteps: Optional[int] = None) -> "ListBuilder":
        return self.input_type(InputType.recurrent(size, timesteps))

    def tbptt(self, fwd: int, bwd: Optional[int] = None) -> "ListBuilder":
        self._tbptt = (fwd, bwd if bwd is not None else fwd)
        return self

    def build(self) -> MultiLayerConfiguration:
        b = self._base
        return MultiLayerConfiguration(
            layers=self._layers, seed=b._seed, updater=b._updater,
            defaults=b._defaults(), input_type=self._input_type,
            tbptt_fwd_length=self._tbptt[0], tbptt_bwd_length=self._tbptt[1],
            max_grad_norm=b._max_grad_norm, grad_clip_value=b._grad_clip_value,
            dtype=b._dtype, remat=b._remat)


class NeuralNetConfiguration:
    """Fluent builder. Ref: `nn/conf/NeuralNetConfiguration.Builder`."""

    def __init__(self):
        self._seed = 12345
        self._updater = None
        self._weight_init = None
        self._activation = None
        self._l1 = 0.0
        self._l2 = 0.0
        self._dropout = 0.0
        self._weight_noise = None
        self._constraints = []
        self._max_grad_norm = None
        self._grad_clip_value = None
        self._remat = False
        # global default dtype (ref: ND4JSystemProperties.DTYPE); the
        # builder's .data_type() overrides per configuration
        from ...flags import flags as _flags
        self._dtype = _flags.dtype or "float"

    @staticmethod
    def builder() -> "NeuralNetConfiguration":
        return NeuralNetConfiguration()

    def seed(self, s: int):
        self._seed = int(s)
        return self

    def updater(self, u):
        self._updater = U.get(u)
        return self

    def weight_init(self, w: str):
        self._weight_init = w
        return self

    def activation(self, a):
        self._activation = a
        return self

    def l1(self, v: float):
        self._l1 = float(v)
        return self

    def l2(self, v: float):
        self._l2 = float(v)
        return self

    def dropout(self, v):
        """Float = plain dropout prob; or an IDropout scheme (Gaussian/
        Alpha/Spatial/noise — ref: Builder.dropOut overloads)."""
        self._dropout = float(v) if isinstance(v, (int, float)) else v
        return self

    def weight_noise(self, wn):
        """Global DropConnect / Gaussian weight noise default (ref:
        NeuralNetConfiguration.Builder.weightNoise)."""
        from .weightnoise import get as _wn_get
        self._weight_noise = _wn_get(wn)
        return self

    def constrain_weights(self, *constraints):
        """Global weight constraints, applied post-update (ref:
        Builder.constrainWeights)."""
        from .constraint import get as _con_get
        self._constraints = [_con_get(c) for c in constraints]
        return self

    def gradient_normalization(self, max_norm: Optional[float] = None,
                               clip_value: Optional[float] = None):
        """Ref: GradientNormalization enum — ClipL2PerLayer → max_norm,
        ClipElementWiseAbsoluteValue → clip_value."""
        self._max_grad_norm = max_norm
        self._grad_clip_value = clip_value
        return self

    def data_type(self, dt: str):
        self._dtype = dt
        return self

    def remat(self, on: bool = True):
        """Per-layer activation rematerialization (jax.checkpoint):
        recompute forward activations during backprop instead of
        holding them in HBM — the standard TPU memory/FLOPs trade for
        deep or long-sequence models."""
        self._remat = bool(on)
        return self

    # accepted-for-parity no-ops (XLA owns memory on TPU)
    def training_workspace_mode(self, mode):
        return self

    def inference_workspace_mode(self, mode):
        return self

    def cache_mode(self, mode):
        return self

    def cudnn_algo_mode(self, mode):
        return self

    def _defaults(self) -> dict:
        d = {}
        if self._weight_init is not None:
            d["weight_init"] = self._weight_init
        if self._activation is not None:
            d["activation"] = self._activation
        if self._updater is not None:
            d["updater"] = self._updater
        if self._l1:
            d["l1"] = self._l1
        if self._l2:
            d["l2"] = self._l2
        if self._dropout:
            d["dropout"] = self._dropout
        if self._weight_noise is not None:
            d["weight_noise"] = self._weight_noise
        if self._constraints:
            d["constraints"] = list(self._constraints)
        return d

    def list(self) -> ListBuilder:
        return ListBuilder(self)

    def graph_builder(self):
        """Ref: NeuralNetConfiguration.Builder.graphBuilder()."""
        from ..graph import GraphBuilder
        return GraphBuilder(self)
