"""Shared stateless NN math used by both the layer DSL and the
distributed transformer — one definition so numerics cannot diverge."""
from __future__ import annotations

import jax.numpy as jnp


def layer_norm(x, gain, bias, eps: float = 1e-5):
    """LayerNorm over the last axis: (x - mean)/sqrt(var + eps)*g + b."""
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) / jnp.sqrt(v + eps) * gain + bias
