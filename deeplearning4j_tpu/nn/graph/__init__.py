"""ComputationGraph — arbitrary-DAG networks with multiple inputs/outputs.

Ref: deeplearning4j-nn `nn/graph/ComputationGraph.java` (4,687 lines;
topological order :463-464, fit :978, computeGradientAndScore :1320),
`nn/conf/ComputationGraphConfiguration.java` (GraphBuilder: addInputs /
addLayer / addVertex / setOutputs), vertex impls `nn/graph/vertex/impl/*`.

TPU-first redesign: the DAG is resolved to a static topological order at
init; the whole forward/loss/backward/update is ONE jit-compiled pure
function over a dict of per-node activations — XLA sees a flat fused
graph, not a vertex interpreter. Vertices are tiny pure functions;
layers are reused unchanged from the sequential stack.
"""
from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..conf import InputType
from ..layers import Layer, from_json as layer_from_json
from ..multilayer import _clip_grads, _regularization_penalty
from ... import learning as U

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Graph vertices — pure merge/transform functions over input activations.
# Ref: nn/graph/vertex/impl/{MergeVertex,ElementWiseVertex,SubsetVertex,
# StackVertex,UnstackVertex,ScaleVertex,ShiftVertex,L2NormalizeVertex,
# L2Vertex,ReshapeVertex,PreprocessorVertex,ElementWiseVertex}.java
# ---------------------------------------------------------------------------

class GraphVertex:
    """Parameterless DAG node. Subclasses implement apply(inputs) and
    output_shape(input_shapes)."""

    kind = "vertex"

    def apply(self, inputs: Sequence[jnp.ndarray]) -> jnp.ndarray:
        raise NotImplementedError

    def output_shape(self, input_shapes: Sequence[Tuple[int, ...]]):
        return tuple(input_shapes[0])

    def to_json(self) -> dict:
        return {"@vertex": self.kind, **self._extra_json()}

    def _extra_json(self) -> dict:
        return {}


class MergeVertex(GraphVertex):
    """Concatenate along the channel (last) axis.
    Ref: `nn/graph/vertex/impl/MergeVertex.java` (reference concatenates on
    dim 1 = channels-first; here last axis = channels in NHWC/[B,T,C])."""

    kind = "merge"

    def apply(self, inputs):
        return jnp.concatenate(list(inputs), axis=-1)

    def output_shape(self, input_shapes):
        first = tuple(input_shapes[0])
        ch = sum(s[-1] for s in input_shapes)
        return first[:-1] + (ch,)


class ElementWiseVertex(GraphVertex):
    """Add/Product/Subtract/Average/Max of same-shaped inputs.
    Ref: `nn/graph/vertex/impl/ElementWiseVertex.java` (Op enum)."""

    kind = "elementwise"
    OPS = ("add", "product", "subtract", "average", "max")

    def __init__(self, op: str = "add"):
        op = op.lower()
        assert op in self.OPS, op
        self.op = op

    def apply(self, inputs):
        if self.op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if self.op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if self.op == "subtract":
            assert len(inputs) == 2
            return inputs[0] - inputs[1]
        if self.op == "average":
            return sum(inputs) / float(len(inputs))
        out = inputs[0]
        for x in inputs[1:]:
            out = jnp.maximum(out, x)
        return out

    def _extra_json(self):
        return {"op": self.op}


class SubsetVertex(GraphVertex):
    """Channel slice [from, to] inclusive (reference semantics).
    Ref: `nn/graph/vertex/impl/SubsetVertex.java`."""

    kind = "subset"

    def __init__(self, from_idx: int = 0, to_idx: int = 0):
        self.from_idx = int(from_idx)
        self.to_idx = int(to_idx)

    def apply(self, inputs):
        return inputs[0][..., self.from_idx:self.to_idx + 1]

    def output_shape(self, input_shapes):
        s = tuple(input_shapes[0])
        return s[:-1] + (self.to_idx - self.from_idx + 1,)

    def _extra_json(self):
        return {"from_idx": self.from_idx, "to_idx": self.to_idx}


class StackVertex(GraphVertex):
    """Stack along batch: [B,...] x n -> [n*B, ...].
    Ref: `nn/graph/vertex/impl/StackVertex.java`."""

    kind = "stack"

    def apply(self, inputs):
        return jnp.concatenate(list(inputs), axis=0)


class UnstackVertex(GraphVertex):
    """Take slice `from_idx` of `stack_size` equal batch chunks.
    Ref: `nn/graph/vertex/impl/UnstackVertex.java`."""

    kind = "unstack"

    def __init__(self, from_idx: int = 0, stack_size: int = 1):
        self.from_idx = int(from_idx)
        self.stack_size = int(stack_size)

    def apply(self, inputs):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_idx * step:(self.from_idx + 1) * step]

    def _extra_json(self):
        return {"from_idx": self.from_idx, "stack_size": self.stack_size}


class ScaleVertex(GraphVertex):
    """Ref: `nn/graph/vertex/impl/ScaleVertex.java`."""

    kind = "scale"

    def __init__(self, scale: float = 1.0):
        self.scale = float(scale)

    def apply(self, inputs):
        return inputs[0] * self.scale

    def _extra_json(self):
        return {"scale": self.scale}


class ShiftVertex(GraphVertex):
    """Ref: `nn/graph/vertex/impl/ShiftVertex.java`."""

    kind = "shift"

    def __init__(self, shift: float = 0.0):
        self.shift = float(shift)

    def apply(self, inputs):
        return inputs[0] + self.shift

    def _extra_json(self):
        return {"shift": self.shift}


class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over non-batch dims.
    Ref: `nn/graph/vertex/impl/L2NormalizeVertex.java`."""

    kind = "l2normalize"

    def __init__(self, eps: float = 1e-8):
        self.eps = float(eps)

    def apply(self, inputs):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True))
        return x / (norm + self.eps)

    def _extra_json(self):
        return {"eps": self.eps}


class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs -> [B, 1].
    Ref: `nn/graph/vertex/impl/L2Vertex.java`."""

    kind = "l2"

    def __init__(self, eps: float = 1e-8):
        self.eps = float(eps)

    def apply(self, inputs):
        a, b = inputs
        axes = tuple(range(1, a.ndim))
        d = jnp.sqrt(jnp.sum(jnp.square(a - b), axis=axes) + self.eps)
        return d[:, None]

    def output_shape(self, input_shapes):
        return (1,)

    def _extra_json(self):
        return {"eps": self.eps}


class ReshapeVertex(GraphVertex):
    """Reshape non-batch dims. Ref: `nn/graph/vertex/impl/ReshapeVertex.java`."""

    kind = "reshape"

    def __init__(self, shape: Sequence[int] = ()):
        self.shape = tuple(int(s) for s in shape)

    def apply(self, inputs):
        x = inputs[0]
        return x.reshape((x.shape[0],) + self.shape)

    def output_shape(self, input_shapes):
        return self.shape

    def _extra_json(self):
        return {"shape": list(self.shape)}


class PreprocessorVertex(GraphVertex):
    """Wraps an arbitrary shape-preprocessor function by name.
    Ref: `nn/graph/vertex/impl/PreprocessorVertex.java`. Supported:
    cnn_to_ff (flatten), ff_to_rnn, rnn_to_ff (collapse time into batch is
    NOT done — we keep [B,T,C] end-to-end), rnn_last_step."""

    kind = "preprocessor"

    def __init__(self, op: str = "cnn_to_ff"):
        self.op = op

    def apply(self, inputs):
        x = inputs[0]
        if self.op == "cnn_to_ff":
            return x.reshape(x.shape[0], -1)
        if self.op == "rnn_last_step":
            return x[:, -1, :]
        if self.op == "ff_to_rnn":
            return x[:, None, :]
        raise ValueError(self.op)

    def output_shape(self, input_shapes):
        s = tuple(input_shapes[0])
        if self.op == "cnn_to_ff":
            n = 1
            for v in s:
                n *= v
            return (n,)
        if self.op == "rnn_last_step":
            return (s[-1],)
        if self.op == "ff_to_rnn":
            return (1,) + s
        raise ValueError(self.op)

    def _extra_json(self):
        return {"op": self.op}


class SameDiffLambdaVertex(GraphVertex):
    """Multi-input vertex whose forward is a SameDiff graph — subclass
    and override define_vertex(sd, *inputs) -> SDVariable, or pass fn=.
    Ref: `nn/conf/layers/samediff/SameDiffLambdaVertex.java` (the
    parameterless SameDiffVertex form). The graph is traced once and
    inlined into the ComputationGraph's jitted step."""

    kind = "samediff_lambda_vertex"

    def __init__(self, fn=None):
        self._fn = fn
        self._cache = {}

    def define_vertex(self, sd, *inputs):
        if self._fn is not None:
            return self._fn(sd, *inputs)
        raise NotImplementedError("pass fn= or override define_vertex")

    def apply(self, inputs):
        from ...autodiff.samediff import SameDiff
        key = tuple((tuple(x.shape[1:]), str(x.dtype)) for x in inputs)
        if key not in self._cache:
            sd = SameDiff.create()
            phs = [sd.placeholder(f"in_{i}", (None,) + tuple(x.shape[1:]),
                                  dtype=x.dtype)
                   for i, x in enumerate(inputs)]
            out = self.define_vertex(sd, *phs)
            self._cache[key] = (sd, out.name)
        sd, out_name = self._cache[key]
        feed = {f"in_{i}": x for i, x in enumerate(inputs)}
        return sd.output(feed, [out_name])[out_name]

    def output_shape(self, input_shapes):
        import jax
        import jax.numpy as jnp
        out = jax.eval_shape(
            lambda *xs: self.apply(xs),
            *[jax.ShapeDtypeStruct((2,) + tuple(s), jnp.float32)
              for s in input_shapes])
        return tuple(out.shape[1:])

    def _extra_json(self):
        if type(self) is not SameDiffLambdaVertex:
            from ..layers.samediff_layer import _class_path
            return {"cls": _class_path(self)}
        return {"cls": None}


VERTEX_REGISTRY: Dict[str, type] = {
    c.kind: c for c in (MergeVertex, ElementWiseVertex, SubsetVertex,
                        StackVertex, UnstackVertex, ScaleVertex, ShiftVertex,
                        L2NormalizeVertex, L2Vertex, ReshapeVertex,
                        PreprocessorVertex, SameDiffLambdaVertex)
}


def vertex_from_json(d: dict) -> GraphVertex:
    d = dict(d)
    kind = d.pop("@vertex")
    cls_path = d.pop("cls", None)
    if cls_path:
        # custom SameDiff vertex subclass: reconstruct by import path
        from ..layers.samediff_layer import _load_class
        return _load_class(cls_path)(**d)
    if kind == "samediff_lambda_vertex":
        raise ValueError("anonymous SameDiff lambda vertices (fn=...) are "
                         "not serializable — subclass SameDiffLambdaVertex")
    return VERTEX_REGISTRY[kind](**d)


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("name", "layer", "vertex", "inputs")

    def __init__(self, name, layer=None, vertex=None, inputs=()):
        self.name = name
        self.layer = layer
        self.vertex = vertex
        self.inputs = list(inputs)


class ComputationGraphConfiguration:
    """Ref: `nn/conf/ComputationGraphConfiguration.java` + GraphBuilder."""

    def __init__(self, nodes: Dict[str, _Node], graph_inputs: List[str],
                 graph_outputs: List[str], input_types: Dict[str, InputType],
                 seed: int = 12345, updater=None, defaults: Optional[dict] = None,
                 max_grad_norm: Optional[float] = None,
                 grad_clip_value: Optional[float] = None,
                 tbptt_fwd_length: int = 0, dtype: str = "float",
                 remat: bool = False):
        self.nodes = nodes
        self.graph_inputs = graph_inputs
        self.graph_outputs = graph_outputs
        self.input_types = input_types
        self.seed = int(seed)
        self.updater = U.get(updater) if updater is not None else U.Sgd(0.1)
        self.defaults = defaults or {}
        self.max_grad_norm = max_grad_norm
        self.grad_clip_value = grad_clip_value
        self.tbptt_fwd_length = tbptt_fwd_length
        self.dtype = dtype
        self.remat = bool(remat)

    # topological order (ref: ComputationGraph.topologicalSortOrder :463)
    def topo_order(self) -> List[str]:
        order: List[str] = []
        seen = set(self.graph_inputs)
        pending = dict(self.nodes)
        while pending:
            ready = [n for n, node in pending.items()
                     if all(i in seen for i in node.inputs)]
            if not ready:
                raise ValueError(f"graph has a cycle or missing input: "
                                 f"{sorted(pending)}")
            for n in sorted(ready):
                order.append(n)
                seen.add(n)
                del pending[n]
        return order

    def to_json(self) -> str:
        from ..conf import MultiLayerConfiguration as _MLC
        return json.dumps({
            "seed": self.seed,
            "updater": self.updater.to_json(),
            "defaults": _MLC._defaults_to_json(self.defaults),
            "inputs": self.graph_inputs,
            "outputs": self.graph_outputs,
            "input_types": {k: v.to_json() for k, v in self.input_types.items()},
            "max_grad_norm": self.max_grad_norm,
            "grad_clip_value": self.grad_clip_value,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "dtype": self.dtype,
            "remat": self.remat,
            "nodes": [{
                "name": n.name, "inputs": n.inputs,
                **({"layer": n.layer.to_json()} if n.layer is not None else {}),
                **({"vertex": n.vertex.to_json()} if n.vertex is not None else {}),
            } for n in self.nodes.values()],
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        d = json.loads(s)
        nodes = {}
        for nd in d["nodes"]:
            layer = layer_from_json(nd["layer"]) if "layer" in nd else None
            vertex = vertex_from_json(nd["vertex"]) if "vertex" in nd else None
            nodes[nd["name"]] = _Node(nd["name"], layer, vertex, nd["inputs"])
        defaults = d.get("defaults", {})
        if isinstance(defaults.get("updater"), dict):
            defaults["updater"] = U.get(defaults["updater"])
        return ComputationGraphConfiguration(
            nodes=nodes, graph_inputs=d["inputs"], graph_outputs=d["outputs"],
            input_types={k: InputType.from_json(v)
                         for k, v in d["input_types"].items()},
            seed=d.get("seed", 12345),
            updater=U.get(d["updater"]) if d.get("updater") else None,
            defaults=defaults, max_grad_norm=d.get("max_grad_norm"),
            grad_clip_value=d.get("grad_clip_value"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 0),
            dtype=d.get("dtype", "float"),
            remat=d.get("remat", False))


class GraphBuilder:
    """Fluent DAG builder. Ref: ComputationGraphConfiguration.GraphBuilder
    (addInputs :~, addLayer, addVertex, setOutputs, setInputTypes)."""

    def __init__(self, base=None):
        self._base = base
        self._nodes: Dict[str, _Node] = {}
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._input_types: Dict[str, InputType] = {}
        self._tbptt = 0

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        for name, t in zip(self._inputs, types):
            self._input_types[name] = t
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        layer.name = layer.name or name
        self._nodes[name] = _Node(name, layer=layer, inputs=inputs)
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        self._nodes[name] = _Node(name, vertex=vertex, inputs=inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def tbptt(self, fwd: int) -> "GraphBuilder":
        self._tbptt = int(fwd)
        return self

    def build(self) -> ComputationGraphConfiguration:
        b = self._base
        kw = {}
        if b is not None:
            kw = dict(seed=b._seed, updater=b._updater, defaults=b._defaults(),
                      max_grad_norm=b._max_grad_norm,
                      grad_clip_value=b._grad_clip_value, dtype=b._dtype,
                      remat=b._remat)
        return ComputationGraphConfiguration(
            nodes=self._nodes, graph_inputs=self._inputs,
            graph_outputs=self._outputs, input_types=self._input_types,
            tbptt_fwd_length=self._tbptt, **kw)


# ---------------------------------------------------------------------------
# Runtime
# ---------------------------------------------------------------------------

class ComputationGraph:
    """DAG network with fit/output/evaluate. Ref:
    `nn/graph/ComputationGraph.java` (public surface mirrored)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self._order = conf.topo_order()
        self._params: Optional[Params] = None
        self._net_state: Optional[Params] = None
        self._opt_state: Optional[Any] = None
        self._step = 0
        self._epoch = 0
        self.listeners: List = []
        self._last_loss = None
        self._rng = jax.random.PRNGKey(conf.seed)
        self._jit_step = None
        self._jit_forward = {}
        self._shapes: Dict[str, Tuple[int, ...]] = {}

    # -- init ----------------------------------------------------------
    def init(self, dtype=jnp.float32) -> "ComputationGraph":
        conf = self.conf
        shapes: Dict[str, Tuple[int, ...]] = {}
        for name in conf.graph_inputs:
            if name not in conf.input_types:
                raise ValueError(f"input {name} needs an InputType")
            shapes[name] = tuple(conf.input_types[name].shape)
        keys = jax.random.split(self._rng, len(self._order) + 1)
        self._rng = keys[0]
        params: Params = {}
        state: Params = {}
        self._updaters: Dict[str, Any] = {}
        self._layers_meta: Dict[str, dict] = {}
        for i, name in enumerate(self._order):
            node = conf.nodes[name]
            in_shapes = [shapes[x] for x in node.inputs]
            if node.layer is not None:
                layer = node.layer
                layer.build(in_shapes[0], conf.defaults)
                p = layer.init_params(keys[i + 1], dtype)
                if p:
                    params[name] = p
                s = layer.init_state()
                if s:
                    state[name] = s
                shapes[name] = tuple(layer.output_shape(in_shapes[0]))
                self._updaters[name] = (layer.updater if layer.updater is not None
                                        else conf.updater)
                self._layers_meta[name] = {
                    "l1": layer.l1, "l2": layer.l2,
                    "l1_bias": layer.l1_bias, "l2_bias": layer.l2_bias,
                    "bias_params": frozenset(layer.bias_param_names())}
            else:
                shapes[name] = tuple(node.vertex.output_shape(in_shapes))
        self._shapes = shapes
        self._params = params
        self._net_state = state
        self._opt_state = {name: self._updaters[name].init_state(params[name])
                           for name in params}
        self._output_layers = [conf.nodes[n].layer for n in conf.graph_outputs]
        return self

    # -- forward -------------------------------------------------------
    def _forward(self, params, net_state, inputs: Dict[str, jnp.ndarray],
                 train: bool, rng, fmask=None, stop_at: Optional[str] = None,
                 carries: Optional[Dict[str, Any]] = None):
        """Topological evaluation. Returns (activations dict, new_state),
        or (acts, new_state, new_carries) when ``carries`` is passed
        (TBPTT: per-RNN-node state threaded across time chunks — ref:
        ComputationGraph.rnnActivateUsingStoredState).

        ``fmask`` is either a single [B, T] array (applied to every
        input — the single-input convenience) or a dict keyed by input
        name. Masks PROPAGATE along each branch (ref: ComputationGraph
        feedForwardMaskArrays): a node inherits the mask of its masked
        inputs (vertices with several masked inputs combine them by
        elementwise OR, the MergeVertex rule), and the mask ends where
        activations stop carrying a time axis."""
        conf = self.conf
        acts: Dict[str, jnp.ndarray] = dict(inputs)
        if isinstance(fmask, dict):
            macts: Dict[str, Any] = {k: fmask.get(k) for k in inputs}
        else:
            macts = {k: fmask for k in inputs}
        new_state = dict(net_state)
        new_carries: Dict[str, Any] = {}
        if rng is not None:
            node_rngs = jax.random.split(rng, max(len(self._order), 1))
        for i, name in enumerate(self._order):
            node = conf.nodes[name]
            ins = [acts[x] for x in node.inputs]
            # MergeVertex.feedForwardMaskArrays: elementwise OR, where
            # an UNMASKED sequence input means all-timesteps-valid —
            # all-ones dominates the OR, so any unmasked 3-D input
            # clears the merged mask (a masked branch's padding must
            # not be imposed on a fully-valid sibling)
            seq_masks = []
            any_unmasked_seq = False
            for x in node.inputs:
                mx = macts.get(x)
                if mx is not None:
                    seq_masks.append(mx)
                elif getattr(acts[x], "ndim", 0) == 3:
                    any_unmasked_seq = True
            vkind = getattr(getattr(node, "vertex", None), "kind", None)
            if vkind == "stack" and seq_masks:
                # StackVertex concatenates along BATCH: masks stack the
                # same way, all-ones standing in for unmasked inputs
                # (ref: StackVertex.feedForwardMaskArrays)
                parts = []
                for x in node.inputs:
                    mx = macts.get(x)
                    if mx is None:
                        a = acts[x]
                        mx = jnp.ones((a.shape[0],) + seq_masks[0].shape[1:],
                                      seq_masks[0].dtype)
                    parts.append(mx)
                fm = jnp.concatenate(parts, axis=0)
            elif vkind == "unstack" and seq_masks:
                v = node.vertex
                step = seq_masks[0].shape[0] // v.stack_size
                fm = seq_masks[0][v.from_idx * step:
                                  (v.from_idx + 1) * step]
            elif any_unmasked_seq or not seq_masks:
                fm = None
            else:
                fm = seq_masks[0]
                for m2 in seq_masks[1:]:
                    fm = jnp.maximum(fm, m2)
            if node.layer is not None:
                layer = node.layer
                p = params.get(name, {})
                s = net_state.get(name, {})
                r = node_rngs[i] if rng is not None else None
                if getattr(layer, "derives_mask", False):
                    # MaskingLayer: inject the data-derived mask into
                    # this branch's propagation
                    derived = layer.derive_mask(ins[0])
                    if derived is not None:
                        fm = derived if fm is None else fm * derived
                if layer.weight_noise is not None:
                    p = layer._maybe_weight_noise(p, train, r)
                remat = getattr(conf, "remat", False) and train
                if getattr(layer, "is_rnn", False):
                    m = fm if ins[0].ndim == 3 else None
                    carry = (carries.get(name) if carries is not None
                             else None)
                    if carry is None:
                        carry = layer.init_carry(ins[0].shape[0],
                                                 ins[0].dtype)
                    if remat:
                        act, s2, c2 = jax.checkpoint(
                            lambda p_, a_, s_, r_, c_, m_, _l=layer:
                            _l.apply_seq(p_, a_, s_, train, r_, c_, m_)
                        )(p, ins[0], s, r, carry, m)
                    else:
                        act, s2, c2 = layer.apply_seq(p, ins[0], s, train,
                                                      r, carry, m)
                    new_carries[name] = c2
                elif getattr(layer, "wants_mask", False):
                    # MaskLayer (ref: nn/conf/layers/util/MaskLayer.java):
                    # consumes the [B,T] feature mask on sequence inputs
                    m = fm if ins[0].ndim == 3 else None
                    act, s2 = layer.apply_with_mask(p, ins[0], s, train,
                                                    r, m)
                elif remat and layer.has_params:
                    # conf.remat: recompute activations in backward
                    act, s2 = jax.checkpoint(
                        lambda p_, a_, s_, r_, _l=layer:
                        _l.apply(p_, a_, s_, train, r_))(p, ins[0], s, r)
                else:
                    act, s2 = layer.apply(p, ins[0], s, train, r)
                if s:
                    new_state[name] = s2
            else:
                act = node.vertex.apply(ins)
            acts[name] = act
            # mask propagation: carried while the activation keeps a
            # time axis, dropped once it collapses (pooling/last-step)
            macts[name] = fm if getattr(act, "ndim", 0) == 3 else None
            if stop_at is not None and name == stop_at:
                break
        if carries is not None:
            return acts, new_state, new_carries
        return acts, new_state

    @property
    def _cdt(self):
        """Compute dtype under mixed precision (policy shared with
        MultiLayerNetwork — see nn/precision.py)."""
        from ..precision import compute_dtype
        return compute_dtype(getattr(self.conf, "dtype", None))

    def _loss_fn(self, params, net_state, inputs, labels: Dict[str, jnp.ndarray],
                 masks, train, rng, carries=None):
        """Sum of output-layer losses + L1/L2 (ref: computeGradientAndScore
        :1320 sums scores over output layers). With ``carries``, the aux
        becomes (new_state, new_carries) — the TBPTT chunk contract."""
        from ..precision import (cast_feats_to_f32, cast_input_for_compute,
                                 cast_params_for_compute)
        r_fwd = r_out = None
        if rng is not None:
            r_fwd, r_out = jax.random.split(rng)
        cdt = self._cdt
        params_c = cast_params_for_compute(
            params, set(self.conf.graph_outputs), cdt)
        inputs_c = {k: cast_input_for_compute(v, cdt)
                    for k, v in inputs.items()} if cdt is not None else inputs
        fwd = self._forward(params_c, net_state, inputs_c, train,
                            r_fwd, fmask=self._fmask_from(masks),
                            carries=carries)
        if carries is not None:
            acts, new_state, new_carries = fwd
        else:
            acts, new_state = fwd
        total = 0.0
        for out_name in self.conf.graph_outputs:
            node = self.conf.nodes[out_name]
            feats = cast_feats_to_f32(acts[node.inputs[0]])
            y = labels[out_name]
            m = None if masks is None else masks.get(out_name)
            total = total + node.layer.compute_loss(
                params.get(out_name, {}), feats, y, m, train=train, rng=r_out)
        reg = _regularization_penalty(params, self._layers_meta)
        if carries is not None:
            return total + reg, (new_state, new_carries)
        return total + reg, new_state

    # NOTE: output layers' loss consumes the activation of their INPUT node
    # (pre-output semantics); the output node itself also appears in acts for
    # inference. This mirrors the reference where BaseOutputLayer both
    # activates and scores.

    # -- train step ----------------------------------------------------
    def _make_step_fn(self, with_carries: bool = False):
        """One step body shared by the plain and TBPTT paths (the only
        difference is RNN-carry threading) — a single definition keeps
        clipping/updater/constraint behavior identical on both."""
        updaters = self._updaters
        max_norm = self.conf.max_grad_norm
        clip_value = self.conf.grad_clip_value

        nodes = self.conf.nodes

        def _apply_updates(params, opt_state, grads, step):
            new_opt = {}
            new_params = {}
            for key, p in params.items():
                st, upd = updaters[key].apply(opt_state[key], grads[key],
                                              step)
                new_opt[key] = st
                new_p = jax.tree_util.tree_map(
                    lambda a, u: a - u, p, upd)
                layer = nodes[key].layer
                if layer is not None and layer.constraints:
                    from ..conf.constraint import apply_constraints
                    new_p = apply_constraints(layer.constraints, new_p,
                                              layer.bias_param_names())
                new_params[key] = new_p
            return new_params, new_opt

        if with_carries:
            def step_fn(params, opt_state, net_state, step, inputs,
                        labels, masks, rng, carries):
                carries = jax.tree_util.tree_map(lax.stop_gradient,
                                                 carries)
                (loss, (new_net_state, new_carries)), grads =                     jax.value_and_grad(
                        lambda p: self._loss_fn(p, net_state, inputs,
                                                labels, masks, True, rng,
                                                carries=carries),
                        has_aux=True)(params)
                grads = _clip_grads(grads, max_norm, clip_value)
                new_params, new_opt = _apply_updates(params, opt_state,
                                                     grads, step)
                return (new_params, new_opt, new_net_state, loss,
                        new_carries)
            return step_fn

        def step_fn(params, opt_state, net_state, step, inputs, labels, masks, rng):
            (loss, new_net_state), grads = jax.value_and_grad(
                lambda p: self._loss_fn(p, net_state, inputs, labels, masks,
                                        True, rng), has_aux=True)(params)
            grads = _clip_grads(grads, max_norm, clip_value)
            new_params, new_opt = _apply_updates(params, opt_state, grads,
                                                 step)
            return new_params, new_opt, new_net_state, loss

        return step_fn

    def _make_step(self):
        return jax.jit(self._make_step_fn(), donate_argnums=(0, 1, 2))

    def _init_carries(self, batch: int, dtype=jnp.float32):
        """Zero RNN carries keyed by node name (ref:
        ComputationGraph.rnnClearPreviousState's state map)."""
        out = {}
        for name in self._order:
            layer = self.conf.nodes[name].layer
            if layer is not None and getattr(layer, "is_rnn", False):
                out[name] = layer.init_carry(batch, dtype)
        return out

    def _make_tbptt_step(self):
        """Truncated-BPTT chunk step (ref:
        ComputationGraph.doTruncatedBPTT :~1870): the shared step body
        with RNN carries threaded across chunks, gradient-stopped at
        the chunk boundary."""
        return jax.jit(self._make_step_fn(with_carries=True),
                       donate_argnums=(0, 1, 2))

    def _fit_tbptt(self, inputs, labels, masks, tbptt: int):
        """Chunked fwd/bwd over time for every sequence input/label (ref:
        ComputationGraph.doTruncatedBPTT). Ragged tails pad to the chunk
        length with feature-mask zeros so every chunk reuses one
        compiled program."""
        if getattr(self, "_tbptt_step", None) is None:
            self._tbptt_step = self._make_tbptt_step()
        seq_ins = [k for k, v in inputs.items() if v.ndim == 3]
        T = max(inputs[k].shape[1] for k in seq_ins)
        B = next(iter(inputs.values())).shape[0]
        masks = dict(masks) if masks else {}
        # every sequence input carries an explicit [B, T] feature mask so
        # the pad region is masked out uniformly; inputs shorter than the
        # longest sequence are zero-padded to the SAME global T so every
        # mask/chunk pair stays shape-consistent
        inputs = dict(inputs)
        for k in seq_ins:
            Tk = inputs[k].shape[1]
            if k not in masks:
                masks[k] = jnp.ones((B, Tk), inputs[k].dtype)
            if Tk < T:
                inputs[k] = jnp.pad(
                    inputs[k], ((0, 0), (0, T - Tk), (0, 0)))
                masks[k] = jnp.pad(masks[k], ((0, 0), (0, T - Tk)))
        # ragged TAILS must also be excluded from the LOSS: sequence
        # outputs get an explicit label mask (padded with zeros below),
        # the graph analogue of multilayer TBPTT's single mask doubling
        # as feature+label mask
        labels = dict(labels)
        for out_name in self.conf.graph_outputs:
            y = labels.get(out_name)
            if y is not None and getattr(y, "ndim", 0) == 3:
                Ty = y.shape[1]
                if out_name not in masks:
                    masks[out_name] = jnp.ones((B, Ty), y.dtype)
                if Ty < T:
                    labels[out_name] = jnp.pad(
                        y, ((0, 0), (0, T - Ty), (0, 0)))
                    masks[out_name] = jnp.pad(masks[out_name],
                                              ((0, 0), (0, T - Ty)))
        dtype = inputs[seq_ins[0]].dtype
        carries = self._init_carries(B, dtype)
        loss = None
        for t0 in range(0, T, tbptt):
            def chunk(v):
                if getattr(v, "ndim", 0) != 3 and getattr(
                        v, "ndim", 0) != 2:
                    return v
                c = v[:, t0:t0 + tbptt]
                pad = tbptt - c.shape[1]
                if pad:
                    widths = ((0, 0), (0, pad)) + ((0, 0),) * (c.ndim - 2)
                    c = jnp.pad(c, widths)
                return c
            ic = {k: chunk(v) if v.ndim == 3 else v
                  for k, v in inputs.items()}
            lc = {k: chunk(v) if getattr(v, "ndim", 0) == 3 else v
                  for k, v in labels.items()}
            mc = {k: (chunk(v) if getattr(v, "ndim", 0) >= 2
                      and v.shape[1] == T else v)
                  for k, v in masks.items()}
            self._rng, sub = jax.random.split(self._rng)
            (self._params, self._opt_state, self._net_state, loss,
             carries) = self._tbptt_step(
                self._params, self._opt_state, self._net_state,
                jnp.asarray(self._step), ic, lc, mc, sub, carries)
            # per-chunk optimizer step (ref: doTruncatedBPTT runs
            # solver.optimize per segment, advancing the iteration
            # count each chunk — Adam-family bias correction and LR
            # schedules must see the same t as the moments)
            self._step += 1
        return loss

    # -- public API ----------------------------------------------------
    def _as_inputs(self, data) -> Dict[str, jnp.ndarray]:
        if isinstance(data, dict):
            return {k: jnp.asarray(v) for k, v in data.items()}
        if isinstance(data, (list, tuple)):
            return {n: jnp.asarray(v) for n, v in zip(self.conf.graph_inputs, data)}
        return {self.conf.graph_inputs[0]: jnp.asarray(data)}

    def _as_labels(self, labels) -> Dict[str, jnp.ndarray]:
        if isinstance(labels, dict):
            return {k: jnp.asarray(v) for k, v in labels.items()}
        if isinstance(labels, (list, tuple)):
            return {n: jnp.asarray(v)
                    for n, v in zip(self.conf.graph_outputs, labels)}
        return {self.conf.graph_outputs[0]: jnp.asarray(labels)}

    def fit(self, data, labels=None, epochs: int = 1):
        """fit(x, y) / fit(iterator) / fit(MultiDataSet-like iterator).
        Ref: ComputationGraph.fit overloads (:978)."""
        if self._params is None:
            self.init()
        if self._jit_step is None:
            self._jit_step = self._make_step()
        if labels is not None:
            batches = [(data, labels, None)]
            iterator = None
        else:
            iterator = data if hasattr(data, "reset") or isinstance(
                data, (list, tuple)) else list(data)
        for _ in range(epochs):
            if iterator is not None:
                batches = iterator
            for item in batches:
                x, y, m = self._unpack(item)
                t0 = time.perf_counter()
                inputs = self._as_inputs(x)
                labels = self._as_labels(y)
                masks = self._as_masks(m)
                tbptt = self.conf.tbptt_fwd_length
                seq_T = [v.shape[1] for v in inputs.values()
                         if v.ndim == 3]
                if tbptt and seq_T and max(seq_T) > tbptt:
                    # ref: ComputationGraph.doTruncatedBPTT — chunk the
                    # time axis, carry RNN state across chunks
                    # (_fit_tbptt advances _step once per chunk)
                    loss = self._fit_tbptt(inputs, labels, masks, tbptt)
                else:
                    self._rng, sub = jax.random.split(self._rng)
                    (self._params, self._opt_state, self._net_state,
                     loss) = self._jit_step(
                        self._params, self._opt_state, self._net_state,
                        jnp.asarray(self._step), inputs, labels, masks,
                        sub)
                    self._step += 1
                self._last_loss = loss
                dur = time.perf_counter() - t0
                for lst in self.listeners:
                    lst.iteration_done(self, self._step, self._epoch)
                    if hasattr(lst, "on_timing"):
                        first = next(iter(self._as_inputs(x).values()))
                        lst.on_timing(self, dur, first.shape[0])
            self._epoch += 1
            for lst in self.listeners:
                if hasattr(lst, "on_epoch_end"):
                    lst.on_epoch_end(self)
        return self

    @staticmethod
    def _unpack(item):
        if isinstance(item, tuple) and len(item) >= 2:
            return item[0], item[1], item[2] if len(item) > 2 else None
        return (item.features, item.labels,
                getattr(item, "labels_mask", None))

    # -- layerwise unsupervised pretraining (ref: ComputationGraph.pretrain
    # — used by VariationalAutoencoder nodes) ---------------------------
    def pretrain(self, iterator, epochs: int = 1):
        """Pretrain every pretrainable (VAE) node in topological order on
        the frozen activations of its upstream subgraph (ref:
        ComputationGraph.pretrain(DataSetIterator))."""
        if self._params is None:
            self.init()
        if not hasattr(iterator, "reset") and \
                not isinstance(iterator, (list, tuple)):
            iterator = list(iterator)
        for name in self._order:
            node = self.conf.nodes[name]
            if node.layer is not None and \
                    getattr(node.layer, "is_pretrain_layer", False):
                self.pretrain_node(name, iterator, epochs=epochs)
        return self

    def pretrain_node(self, name: str, iterator, epochs: int = 1):
        """Pretrain one node on its unsupervised loss (ref:
        ComputationGraph.pretrainLayer). Only that node's params move."""
        node = self.conf.nodes[name]
        layer = node.layer
        if layer is None or not getattr(layer, "is_pretrain_layer", False):
            raise ValueError(f"node {name!r} is not pretrainable")
        in_node = node.inputs[0]
        updater = self._updaters[name]

        @jax.jit
        def pre_step(p, opt, step, feats, rng):
            loss, g = jax.value_and_grad(
                lambda pp: layer.pretrain_loss(pp, feats, rng))(p)
            st, upd = updater.apply(opt, g, step)
            new_p = jax.tree_util.tree_map(lambda a, u: a - u, p, upd)
            return new_p, st, loss

        @jax.jit
        def features(params, net_state, inputs):
            acts, _ = self._forward(params, net_state, inputs, False,
                                    None, stop_at=in_node)
            return acts[in_node]

        p, opt = self._params[name], self._opt_state[name]
        step = 0
        data = iterator if isinstance(iterator, (list, tuple)) \
            else list(iterator)
        loss = None
        for _ in range(epochs):
            for item in data:
                x = self._unpack(item)[0]
                feats = features(self._params, self._net_state,
                                 self._as_inputs(x))
                self._rng, sub = jax.random.split(self._rng)
                p, opt, loss = pre_step(p, opt, jnp.asarray(step), feats,
                                        sub)
                step += 1
        self._params[name] = p
        self._opt_state[name] = opt
        self._last_loss = loss
        return self

    def _as_masks(self, m):
        if m is None:
            return None
        if isinstance(m, dict):
            return {k: jnp.asarray(v) for k, v in m.items()}
        if isinstance(m, (list, tuple)):
            return {n: jnp.asarray(v)
                    for n, v in zip(self.conf.graph_outputs, m)}
        return {self.conf.graph_outputs[0]: jnp.asarray(m)}

    def _fmask_from(self, masks):
        """Feature masks for the forward pass (RNN padding + MaskLayer).
        Only masks keyed by INPUT names are feature masks (ref:
        ComputationGraph keeps featureMaskArrays and labelMaskArrays
        distinct — setLayerMaskArrays). A bare/output-keyed mask stays a
        label mask: silently reusing it as a feature mask would corrupt
        many-to-one RNN training (a last-step-only label mask would make
        the RNN treat every earlier timestep as padding).

        Returns a dict {input_name: [B, T] mask} — `_forward` propagates
        each input's mask along its own branch (the reference's
        feedForwardMaskArrays role), so multi-input graphs take
        per-input masks."""
        if not masks:
            return None
        keyed = {n: masks[n] for n in self.conf.graph_inputs
                 if n in masks}
        return keyed or None

    def output(self, *data, train: bool = False, mask=None):
        """Returns the list of output activations (ref:
        ComputationGraph.output; `mask` carries the [B, T] input
        feature masks — ref: the featureMaskArrays overload). Accepts a
        bare array (single-input graphs) or a dict keyed by input name
        (multi-input graphs; each mask propagates along its own
        branch)."""
        if self._params is None:
            self.init()
        if mask is not None:
            if isinstance(mask, dict):
                mask = self._fmask_from(mask)
            elif len(self.conf.graph_inputs) > 1:
                # a bare mask on a multi-input graph is ambiguous —
                # which input's padding pattern is it? Pass a dict
                # keyed by input name (per-branch propagation handles
                # the rest)
                raise ValueError(
                    "a bare feature mask on a multi-input "
                    "ComputationGraph is ambiguous — pass a dict "
                    "keyed by input name")
        if len(data) == 1 and isinstance(data[0], (dict, list, tuple)):
            inputs = self._as_inputs(data[0])
        else:
            inputs = self._as_inputs(list(data))
        if isinstance(mask, dict):
            mask = {k: jnp.asarray(v) for k, v in mask.items()}
            mkey = frozenset(mask)
        else:
            mask = None if mask is None else jnp.asarray(mask)
            mkey = mask is not None
        key = ("out", train, mkey)
        if key not in self._jit_forward:
            def fwd(params, net_state, inputs, fmask):
                acts, _ = self._forward(params, net_state, inputs, train,
                                        None, fmask=fmask)
                return [acts[n] for n in self.conf.graph_outputs]
            self._jit_forward[key] = jax.jit(fwd)
        outs = self._jit_forward[key](
            self._params, self._net_state, inputs, mask)
        return outs[0] if len(outs) == 1 else outs

    def feed_forward(self, data, train: bool = False):
        inputs = self._as_inputs(data)
        acts, _ = self._forward(self._params, self._net_state, inputs,
                                train, None)
        return acts

    @property
    def score_(self) -> float:
        return float("nan") if self._last_loss is None else float(self._last_loss)

    def score(self, data, labels) -> float:
        loss, _ = self._loss_fn(self._params, self._net_state,
                                self._as_inputs(data), self._as_labels(labels),
                                None, False, None)
        return float(loss)

    def evaluate(self, iterator):
        from ...eval import Evaluation
        ev = Evaluation()
        for item in iterator:
            x, y, _ = self._unpack(item)
            out = self.output(x)
            if isinstance(out, list):
                out = out[0]
                y = y[0] if isinstance(y, (list, tuple)) else y
            ev.eval(np.asarray(y), np.asarray(out), None)
        return ev

    def num_params(self) -> int:
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(self._params))

    def params(self) -> Params:
        return self._params

    def set_params(self, params: Params):
        self._params = params

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def summary(self) -> str:
        lines = ["=" * 78,
                 f"{'name':<26}{'type':<24}{'out shape':<18}{'params':<10}",
                 "-" * 78]
        for name in self._order:
            node = self.conf.nodes[name]
            t = type(node.layer or node.vertex).__name__
            np_ = node.layer.n_params() if node.layer else 0
            lines.append(f"{name:<26}{t:<24}{str(self._shapes.get(name)):<18}{np_:<10}")
        lines.append("-" * 78)
        lines.append(f"Total params: {self.num_params()}")
        lines.append("=" * 78)
        return "\n".join(lines)

    def clone(self) -> "ComputationGraph":
        from copy import deepcopy
        g = ComputationGraph(
            ComputationGraphConfiguration.from_json(self.conf.to_json()))
        if self._params is not None:
            g.init()
            g._params = deepcopy(self._params)
            g._net_state = deepcopy(self._net_state)
        return g
