"""Hyperparameter optimization — the arbiter layer (ref: D17, ~24k LoC).

Ref: `arbiter-core/.../parameter/**` (ParameterSpace DSL:
ContinuousParameterSpace, IntegerParameterSpace, DiscreteParameterSpace,
FixedValue), `generator/{GridSearchCandidateGenerator,
RandomSearchGenerator}.java`, genetic operators under
`generator/genetic/**` (selection, crossover, mutation),
`scoring/ScoreFunction`, termination conditions
(`MaxCandidatesCondition`, `MaxTimeCondition`), and the
`LocalOptimizationRunner`.

The runner here executes candidates in-process (the reference's
LocalOptimizationRunner role); each candidate's training already
saturates the chip, so candidate-level parallelism is deliberately NOT
the TPU story — sequential candidates, fully-utilized device.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# parameter spaces (ref: arbiter-core parameter/**)
# ---------------------------------------------------------------------------
class ParameterSpace:
    def sample(self, rng: np.random.RandomState):
        raise NotImplementedError

    def grid_values(self, discretization: int) -> List:
        raise NotImplementedError


class ContinuousParameterSpace(ParameterSpace):
    """Uniform (or log-uniform) float range (ref:
    ContinuousParameterSpace.java)."""

    def __init__(self, min_value: float, max_value: float,
                 log_scale: bool = False):
        if log_scale and min_value <= 0:
            raise ValueError("log_scale needs positive min")
        self.min, self.max, self.log = min_value, max_value, log_scale

    def sample(self, rng):
        if self.log:
            return float(np.exp(rng.uniform(np.log(self.min),
                                            np.log(self.max))))
        return float(rng.uniform(self.min, self.max))

    def grid_values(self, discretization):
        if self.log:
            return [float(v) for v in np.exp(np.linspace(
                np.log(self.min), np.log(self.max), discretization))]
        return [float(v) for v in np.linspace(self.min, self.max,
                                              discretization)]


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, min_value: int, max_value: int):
        self.min, self.max = int(min_value), int(max_value)

    def sample(self, rng):
        return int(rng.randint(self.min, self.max + 1))

    def grid_values(self, discretization):
        n = min(discretization, self.max - self.min + 1)
        return [int(round(v)) for v in np.linspace(self.min, self.max, n)]


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, *values):
        if len(values) == 1 and isinstance(values[0], (list, tuple)):
            values = tuple(values[0])
        self.values = list(values)

    def sample(self, rng):
        return self.values[rng.randint(len(self.values))]

    def grid_values(self, discretization):
        return list(self.values)


class BooleanParameterSpace(DiscreteParameterSpace):
    def __init__(self):
        super().__init__(True, False)


class FixedValue(ParameterSpace):
    def __init__(self, value):
        self.value = value

    def sample(self, rng):
        return self.value

    def grid_values(self, discretization):
        return [self.value]


# ---------------------------------------------------------------------------
# candidate generators (ref: generator/**)
# ---------------------------------------------------------------------------
@dataclass
class Candidate:
    index: int
    values: Dict[str, Any]


class CandidateGenerator:
    def __init__(self, spaces: Dict[str, ParameterSpace], seed: int = 0):
        self.spaces = {k: (v if isinstance(v, ParameterSpace)
                           else FixedValue(v))
                       for k, v in spaces.items()}
        self.rng = np.random.RandomState(seed)
        self._count = 0

    def has_more(self) -> bool:
        raise NotImplementedError

    def next(self) -> Candidate:
        raise NotImplementedError

    def report_score(self, candidate: Candidate, score: float):
        """Hook for adaptive generators (genetic)."""


class RandomSearchGenerator(CandidateGenerator):
    """Ref: RandomSearchGenerator.java."""

    def __init__(self, spaces, num_candidates: int = 10, seed: int = 0):
        super().__init__(spaces, seed)
        self.num_candidates = num_candidates

    def has_more(self):
        return self._count < self.num_candidates

    def next(self):
        values = {k: s.sample(self.rng) for k, s in self.spaces.items()}
        c = Candidate(self._count, values)
        self._count += 1
        return c


class GridSearchCandidateGenerator(CandidateGenerator):
    """Ref: GridSearchCandidateGenerator.java — full cartesian product,
    Sequential or RandomOrder mode."""

    def __init__(self, spaces, discretization_count: int = 5,
                 mode: str = "sequential", seed: int = 0):
        super().__init__(spaces, seed)
        keys = list(self.spaces)
        grids = [self.spaces[k].grid_values(discretization_count)
                 for k in keys]
        self._grid: List[Dict[str, Any]] = []
        idx = [0] * len(keys)
        while True:
            self._grid.append({k: grids[i][idx[i]]
                               for i, k in enumerate(keys)})
            j = len(keys) - 1
            while j >= 0:
                idx[j] += 1
                if idx[j] < len(grids[j]):
                    break
                idx[j] = 0
                j -= 1
            if j < 0:
                break
        if mode == "random":
            order = self.rng.permutation(len(self._grid))
            self._grid = [self._grid[i] for i in order]
        elif mode != "sequential":
            raise ValueError(f"unknown mode {mode!r}")

    @property
    def total(self) -> int:
        return len(self._grid)

    def has_more(self):
        return self._count < len(self._grid)

    def next(self):
        c = Candidate(self._count, dict(self._grid[self._count]))
        self._count += 1
        return c


class GeneticSearchCandidateGenerator(CandidateGenerator):
    """Ref: generator/genetic/** — population, tournament selection,
    uniform crossover, per-gene mutation. Numeric genes mutate by
    gaussian perturbation; discrete genes resample."""

    def __init__(self, spaces, population_size: int = 10,
                 generations: int = 5, tournament: int = 3,
                 mutation_prob: float = 0.2, seed: int = 0,
                 minimize: bool = True):
        super().__init__(spaces, seed)
        self.population_size = population_size
        self.generations = generations
        self.tournament = tournament
        self.mutation_prob = mutation_prob
        self.minimize = minimize
        self._pop: List[Candidate] = []
        self._scores: Dict[int, float] = {}
        self._emitted = 0
        self._gen = 0

    def has_more(self):
        return self._emitted < self.population_size * self.generations

    def _random_candidate(self):
        values = {k: s.sample(self.rng) for k, s in self.spaces.items()}
        return Candidate(self._emitted, values)

    def _select(self) -> Candidate:
        pool = [self._pop[self.rng.randint(len(self._pop))]
                for _ in range(self.tournament)]
        key = lambda c: self._scores.get(c.index, np.inf)
        return min(pool, key=key) if self.minimize else \
            max(pool, key=lambda c: self._scores.get(c.index, -np.inf))

    def _breed(self) -> Candidate:
        a, b = self._select(), self._select()
        child: Dict[str, Any] = {}
        for k, space in self.spaces.items():
            v = a.values[k] if self.rng.rand() < 0.5 else b.values[k]
            if self.rng.rand() < self.mutation_prob:
                if isinstance(space, ContinuousParameterSpace):
                    span = space.max - space.min
                    v = float(np.clip(v + self.rng.randn() * 0.1 * span,
                                      space.min, space.max))
                elif isinstance(space, IntegerParameterSpace):
                    v = int(np.clip(v + self.rng.randint(-1, 2),
                                    space.min, space.max))
                else:
                    v = space.sample(self.rng)
            child[k] = v
        return Candidate(self._emitted, child)

    def next(self):
        in_gen = self._emitted % self.population_size
        if self._emitted // self.population_size == 0:
            c = self._random_candidate()          # seed generation
        else:
            c = self._breed()
        self._emitted += 1
        self._pop.append(c)
        if len(self._pop) > 2 * self.population_size:
            self._pop = self._pop[-2 * self.population_size:]
        return c

    def report_score(self, candidate, score):
        self._scores[candidate.index] = score


# ---------------------------------------------------------------------------
# score functions + termination (ref: scoring/**, termination conditions)
# ---------------------------------------------------------------------------
class MaxCandidatesCondition:
    def __init__(self, n: int):
        self.n = n

    def should_stop(self, runner) -> bool:
        return len(runner.results) >= self.n


class MaxTimeCondition:
    def __init__(self, seconds: float):
        self.seconds = seconds
        self._start: Optional[float] = None

    def should_stop(self, runner) -> bool:
        if self._start is None:
            self._start = time.time()
        return time.time() - self._start > self.seconds


@dataclass
class OptimizationResult:
    candidate: Candidate
    score: float
    model: Any = None


class OptimizationConfiguration:
    """Ref: OptimizationConfiguration.Builder — generator + score fn +
    termination conditions."""

    def __init__(self, candidate_generator: CandidateGenerator,
                 score_function: Callable[[Dict[str, Any]], Any],
                 termination_conditions: Sequence = (),
                 minimize: bool = True):
        self.generator = candidate_generator
        self.score_function = score_function
        self.termination_conditions = list(termination_conditions)
        self.minimize = minimize


class LocalOptimizationRunner:
    """Ref: LocalOptimizationRunner — executes candidates, tracks the
    best. `score_function(values)` returns a score or
    (score, model).

    Pass ``stats_storage`` (any StatsStorage, incl. a
    RemoteUIStatsStorageRouter) to stream per-candidate progress to the
    dashboard's arbiter view — the ArbiterModule role
    (ref: `arbiter-ui/.../module/ArbiterModule.java`: results table +
    best-score-vs-index chart)."""

    def __init__(self, config: OptimizationConfiguration,
                 stats_storage=None, session_id: str = "arbiter"):
        self.config = config
        self.results: List[OptimizationResult] = []
        self.stats_storage = stats_storage
        self.session_id = session_id

    def _report(self, idx: int, cand, score: float):
        if self.stats_storage is None:
            return
        import time as _time
        best = (min if self.config.minimize else max)(
            r.score for r in self.results)
        self.stats_storage.put_update(self.session_id, {
            "candidate": idx, "score": score, "best_score": best,
            "parameters": {k: (v if isinstance(v, (int, float, str,
                                                   bool)) else str(v))
                           for k, v in (cand.values or {}).items()},
            "timestamp": _time.time()})

    def execute(self) -> OptimizationResult:
        gen = self.config.generator
        while gen.has_more():
            if any(t.should_stop(self)
                   for t in self.config.termination_conditions):
                break
            cand = gen.next()
            out = self.config.score_function(cand.values)
            score, model = out if isinstance(out, tuple) else (out, None)
            score = float(score)
            gen.report_score(cand, score)
            self.results.append(OptimizationResult(cand, score, model))
            self._report(len(self.results) - 1, cand, score)
        if not self.results:
            raise RuntimeError("no candidates evaluated")
        key = lambda r: r.score
        return min(self.results, key=key) if self.config.minimize \
            else max(self.results, key=key)

    def best_score(self) -> float:
        best = min if self.config.minimize else max
        return best(r.score for r in self.results)
