"""Request-scoped tracing for the serving stack.

Ref role: the reference DL4J stack attributes time per-op via nd4j's
``OpProfiler`` and ships training telemetry through the ``StatsListener``
pipeline (SURVEY §1). Our :mod:`.profiler` reproduces the aggregate
view; this module adds the missing *per-request* axis: one trace follows
a request across the HTTP front-end, the :class:`~.serving.fleet
.FleetRouter` proxy hop (pick / cooldown-wait / dispatch / retry /
hedge), and the winning replica's queue / admission / prefill / decode
stages — stitched by a propagated ``X-Request-Id`` header.

Design rules:

- **Zero cost when disabled.** ``Tracer.begin`` returns ``None`` unless
  tracing was enabled (or the caller forces a one-off trace via
  ``?trace=1``); every instrumentation site guards with a single
  ``if trace is not None`` on an attribute that defaults to ``None`` —
  the same pattern the fault injector uses for its seams. The decode
  hot loop carries NO instrumentation at all: its span is constructed
  retroactively at request completion from fields the engine already
  tracks (``t_first``/``t_last``/token count), so even *enabled*
  tracing adds nothing per decode step.
- **Hedge-safe.** A hedged request's duplicate dispatches share one
  :class:`Trace`; span ids come from a per-trace counter
  (``itertools.count`` — atomic under the GIL, like ``list.append``),
  so concurrent arms record distinct spans without locking.
- **Bounded.** Finished traces are filed into fixed-size rings
  (recent / slow / errored) served at ``GET /debug/traces``; nothing
  grows with traffic.

Times are ``time.perf_counter()`` (monotonic). Serialized spans carry
offsets relative to their trace start, so dumps from different
processes can sit side by side in one report even though their
absolute clocks are unrelated.
"""
from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Trace", "Tracer", "new_request_id"]


def new_request_id() -> str:
    """A fresh 16-hex-char request id (minted by whichever HTTP hop
    sees the request first; downstream hops propagate it verbatim)."""
    return uuid.uuid4().hex[:16]


class Span:
    """One timed stage inside a trace. Create via :meth:`Trace.span`;
    close with :meth:`end` or use as a context manager. ``attrs`` is a
    plain dict of JSON-serializable annotations (verdicts, EWMA
    estimates, replica ids, ...)."""

    __slots__ = ("span_id", "parent_id", "kind", "t_start", "t_end",
                 "attrs")

    def __init__(self, span_id: int, parent_id: Optional[int], kind: str,
                 t_start: float, attrs: Dict[str, Any]):
        self.span_id = span_id
        self.parent_id = parent_id
        self.kind = kind
        self.t_start = t_start
        self.t_end: Optional[float] = None
        self.attrs = attrs

    def end(self, **attrs) -> "Span":
        """Close the span (idempotent for timing; attrs always merge)."""
        if self.t_end is None:
            self.t_end = time.perf_counter()
        if attrs:
            self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and "error" not in self.attrs:
            self.attrs["error"] = repr(exc)
        self.end()

    def to_dict(self, t0: float) -> Dict[str, Any]:
        dur = (None if self.t_end is None
               else round((self.t_end - self.t_start) * 1e3, 4))
        return {"span_id": self.span_id,
                "parent_id": self.parent_id,
                "kind": self.kind,
                "t_offset_ms": round((self.t_start - t0) * 1e3, 4),
                "duration_ms": dur,
                "attrs": dict(self.attrs)}


class Trace:
    """All spans recorded for one request by one component. The
    ``trace_id`` is the propagated request id, so dumps taken from the
    router and from each replica stitch into one logical trace."""

    __slots__ = ("trace_id", "request_id", "t_start", "t_end", "error",
                 "spans", "_ids")

    def __init__(self, request_id: str):
        self.trace_id = request_id
        self.request_id = request_id
        self.t_start = time.perf_counter()
        self.t_end: Optional[float] = None
        self.error = False
        # appends are GIL-atomic: hedge arms add spans concurrently
        self.spans: List[Span] = []
        self._ids = itertools.count(1)

    def span(self, kind: str, parent: Optional[Span] = None,
             t_start: Optional[float] = None,
             t_end: Optional[float] = None, **attrs) -> Span:
        """Open a span. Pass ``t_start``/``t_end`` to record a stage
        retroactively (how the decode span avoids touching the hot
        loop); otherwise the span opens now and closes at ``end()``.
        With no explicit ``parent``, spans after the first attach to
        the trace's root (the component's entry span — ``http`` on a
        replica, ``frontend`` on the router), giving the critical-path
        walk in ``tools/trace_report.py`` a tree to descend."""
        pid = parent.span_id if isinstance(parent, Span) else parent
        if pid is None and self.spans:
            pid = self.spans[0].span_id
        sp = Span(next(self._ids), pid, kind,
                  time.perf_counter() if t_start is None else t_start,
                  attrs)
        if t_end is not None:
            sp.t_end = t_end
        self.spans.append(sp)
        return sp

    def finish(self, error: bool = False) -> "Trace":
        if self.t_end is None:
            self.t_end = time.perf_counter()
        self.error = bool(self.error or error)
        return self

    def duration_ms(self) -> float:
        end = self.t_end if self.t_end is not None else time.perf_counter()
        return (end - self.t_start) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        """Serialize for ``/debug/traces`` / the ``?trace=1`` response
        block. Open spans serialize with ``duration_ms: null``."""
        return {"trace_id": self.trace_id,
                "request_id": self.request_id,
                "duration_ms": round(self.duration_ms(), 4),
                "error": self.error,
                "spans": [s.to_dict(self.t_start) for s in self.spans]}


class Tracer:
    """Factory + bounded retention for traces.

    ``enabled=False`` (the default) makes :meth:`begin` return ``None``
    so instrumented code paths skip all span work; a per-request
    ``force=True`` (the ``?trace=1`` escape hatch) still yields a real
    trace. Finished traces land in three fixed-size rings — every
    finish in ``recent``, finishes slower than ``slow_ms`` in ``slow``,
    errored finishes in ``errored`` — which is what ``GET
    /debug/traces`` serves.
    """

    def __init__(self, enabled: bool = False, ring: int = 256,
                 slow_ms: float = 1000.0, keep: int = 64):
        self.enabled = bool(enabled)
        self.slow_ms = float(slow_ms)
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=max(1, int(ring)))
        self._slow: deque = deque(maxlen=max(1, int(keep)))
        self._errored: deque = deque(maxlen=max(1, int(keep)))
        self._started = 0
        self._finished = 0

    def begin(self, request_id: Optional[str] = None,
              force: bool = False) -> Optional[Trace]:
        """Start a trace, or return ``None`` when tracing is off (and
        not forced) — callers guard every span with that ``None``."""
        if not (self.enabled or force):
            return None
        with self._lock:
            self._started += 1
        return Trace(request_id or new_request_id())

    def finish(self, trace: Optional[Trace], error: bool = False) -> None:
        """File a finished trace into the rings. ``None`` is accepted
        and ignored so call sites need no extra guard."""
        if trace is None:
            return
        trace.finish(error=error)
        with self._lock:
            self._finished += 1
            self._recent.append(trace)
            if trace.duration_ms() >= self.slow_ms:
                self._slow.append(trace)
            if trace.error:
                self._errored.append(trace)

    def dump(self, request_id: Optional[str] = None,
             limit: int = 50) -> List[Dict[str, Any]]:
        """Serialize retained traces, newest first, optionally filtered
        to one request id. Traces retained in several rings appear
        once."""
        with self._lock:
            ordered = (list(self._recent) + list(self._slow)
                       + list(self._errored))
        seen, out = set(), []
        for tr in reversed(ordered):
            if id(tr) in seen:
                continue
            seen.add(id(tr))
            if request_id is not None and tr.request_id != request_id:
                continue
            out.append(tr)
        out.sort(key=lambda t: t.t_start, reverse=True)
        return [t.to_dict() for t in out[:max(0, int(limit))]]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"enabled": self.enabled,
                    "started": self._started,
                    "finished": self._finished,
                    "recent": len(self._recent),
                    "slow": len(self._slow),
                    "errored": len(self._errored)}
