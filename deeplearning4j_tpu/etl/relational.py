"""Relational ETL operations: Join, reduce-by-key, convert-to-sequence.

Ref: `datavec-api/src/main/java/org/datavec/api/transform/join/Join.java`
(Inner/LeftOuter/RightOuter/FullOuter on key columns),
`.../transform/reduce/Reducer.java` (per-column ReduceOp aggregation
grouped by key), and `TransformProcess.convertToSequence` +
`.../transform/sequence/comparator/NumericalColumnComparator.java`
(group records by key into time-sorted sequences).

These run on the host (records are python lists, like the rest of the
DataVec-role layer); the output feeds the same iterators/normalizers as
any reader.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from .schema import ColumnMetaData, ColumnType, Schema


class Join:
    """Schema-checked join of two record collections on key columns.

    Ref: `transform/join/Join.java` — joinType Inner/LeftOuter/
    RightOuter/FullOuter, keyColumns, and the joined schema = left
    columns + right columns minus the (shared) keys."""

    TYPES = ("inner", "left_outer", "right_outer", "full_outer")

    def __init__(self, join_type: str, left_schema: Schema,
                 right_schema: Schema, *key_columns: str):
        jt = join_type.lower()
        if jt not in Join.TYPES:
            raise ValueError(f"join_type must be one of {Join.TYPES}, "
                             f"got {join_type!r}")
        if not key_columns:
            raise ValueError("at least one key column required")
        for k in key_columns:
            left_schema.index_of(k)
            right_schema.index_of(k)
        self.join_type = jt
        self.left_schema = left_schema
        self.right_schema = right_schema
        self.key_columns = list(key_columns)
        # precomputed key positions (index_of is an O(cols) scan — keep
        # it out of the per-record loops)
        self._lkey = [left_schema.index_of(k) for k in key_columns]
        self._rkey = [right_schema.index_of(k) for k in key_columns]
        # fail at construction, like the reference's Join.setSchemas —
        # execute() must not emit rows output_schema() would reject
        self.output_schema()

    def output_schema(self) -> Schema:
        cols = list(self.left_schema.columns)
        names = set(self.left_schema.column_names())
        for c in self.right_schema.columns:
            if c.name in self.key_columns:
                continue
            if c.name in names:
                raise ValueError(
                    f"non-key column {c.name!r} exists on both sides — "
                    "rename before joining")
            cols.append(c)
        return Schema(cols)

    def execute(self, left: Sequence[list],
                right: Sequence[list]) -> List[list]:
        r_idx: "OrderedDict[tuple, List[list]]" = OrderedDict()
        for r in right:
            r_idx.setdefault(tuple(r[i] for i in self._rkey),
                             []).append(r)
        r_keep = [i for i, c in enumerate(self.right_schema.columns)
                  if c.name not in self.key_columns]
        r_nulls = [None] * len(r_keep)
        l_width = self.left_schema.num_columns()
        key_pos_l = self._lkey
        out: List[list] = []
        matched_r = set()
        for l in left:
            key = tuple(l[i] for i in self._lkey)
            matches = r_idx.get(key)
            if matches:
                matched_r.add(key)
                for r in matches:
                    out.append(list(l) + [r[i] for i in r_keep])
            elif self.join_type in ("left_outer", "full_outer"):
                out.append(list(l) + list(r_nulls))
        if self.join_type in ("right_outer", "full_outer"):
            for key, matches in r_idx.items():
                if key in matched_r:
                    continue
                for r in matches:
                    row: List = [None] * l_width
                    for pos, k in zip(key_pos_l, key):
                        row[pos] = k
                    out.append(row + [r[i] for i in r_keep])
        return out


def _stdev(vs):
    m = sum(vs) / len(vs)  # mean computed ONCE, not per element
    return (sum((v - m) ** 2 for v in vs) / max(1, len(vs) - 1)) ** 0.5


_REDUCE_OPS = {
    "sum": lambda vs: sum(vs),
    "mean": lambda vs: sum(vs) / len(vs),
    "min": lambda vs: min(vs),
    "max": lambda vs: max(vs),
    "range": lambda vs: max(vs) - min(vs),
    "count": lambda vs: len(vs),
    "count_unique": lambda vs: len(set(vs)),
    "first": lambda vs: vs[0],
    "last": lambda vs: vs[-1],
    "stdev": _stdev,
}
_NUMERIC_OUT = {"sum", "mean", "range", "stdev"}
_INT_OUT = {"count", "count_unique"}


class Reducer:
    """Group records by key column(s) and aggregate every other column
    with a per-column ReduceOp. Ref: `transform/reduce/Reducer.java`
    (Builder: keyColumns + sumColumns/meanColumns/.../countColumns;
    default op applies to unlisted columns)."""

    def __init__(self, schema: Schema, key_columns: Sequence[str],
                 ops: Dict[str, str], default_op: str = "first"):
        for k in key_columns:
            schema.index_of(k)
        for col, op in ops.items():
            schema.index_of(col)
            if op not in _REDUCE_OPS:
                raise ValueError(f"unknown reduce op {op!r} for {col!r}; "
                                 f"have {sorted(_REDUCE_OPS)}")
        if default_op not in _REDUCE_OPS:
            raise ValueError(f"unknown default op {default_op!r}")
        self.schema = schema
        self.key_columns = list(key_columns)
        self.ops = dict(ops)
        self.default_op = default_op

    class Builder:
        def __init__(self, schema: Schema):
            self._schema = schema
            self._keys: List[str] = []
            self._ops: Dict[str, str] = {}
            self._default = "first"

        def key_columns(self, *names):
            self._keys = list(names); return self

        def default_op(self, op):
            self._default = op; return self

        def __getattr__(self, name):
            # sum_columns / mean_columns / ... builder parity
            if name.endswith("_columns") and \
                    name[:-len("_columns")] in _REDUCE_OPS:
                op = name[:-len("_columns")]

                def setter(*cols):
                    for c in cols:
                        self._ops[c] = op
                    return self
                return setter
            raise AttributeError(name)

        def build(self) -> "Reducer":
            return Reducer(self._schema, self._keys, self._ops,
                           self._default)

    @staticmethod
    def builder(schema: Schema) -> "Reducer.Builder":
        return Reducer.Builder(schema)

    def output_schema(self) -> Schema:
        cols = []
        for c in self.schema.columns:
            if c.name in self.key_columns:
                cols.append(c)
                continue
            op = self.ops.get(c.name, self.default_op)
            if op in _INT_OUT:
                cols.append(ColumnMetaData(f"{op}({c.name})",
                                           ColumnType.LONG))
            elif op in _NUMERIC_OUT:
                cols.append(ColumnMetaData(f"{op}({c.name})",
                                           ColumnType.DOUBLE))
            else:
                cols.append(ColumnMetaData(f"{op}({c.name})", c.type,
                                           dict(c.state)))
        return Schema(cols)

    def execute(self, records: Sequence[list]) -> List[list]:
        key_pos = [self.schema.index_of(k) for k in self.key_columns]
        # per-column plan resolved once: either ("key", position-in-key)
        # or ("agg", reduce-fn) — no name scans inside the group loop
        plan = []
        for i, c in enumerate(self.schema.columns):
            if c.name in self.key_columns:
                plan.append(("key", self.key_columns.index(c.name)))
            else:
                plan.append(
                    ("agg", _REDUCE_OPS[self.ops.get(c.name,
                                                     self.default_op)]))
        groups: "OrderedDict[tuple, List[list]]" = OrderedDict()
        for r in records:
            groups.setdefault(tuple(r[i] for i in key_pos),
                              []).append(r)
        out = []
        for key, rows in groups.items():
            agg = []
            for i, (kind, v) in enumerate(plan):
                if kind == "key":
                    agg.append(key[v])
                else:
                    agg.append(v([r[i] for r in rows]))
            out.append(agg)
        return out


def convert_to_sequence(records: Sequence[list], schema: Schema,
                        key_column: str,
                        sort_column: Optional[str] = None
                        ) -> List[List[list]]:
    """Group flat records into per-key sequences, each sorted by
    `sort_column` (ascending; stable input order when None). Ref:
    `TransformProcess.convertToSequence(keyColumn, comparator)` with
    NumericalColumnComparator semantics."""
    ki = schema.index_of(key_column)
    si = None if sort_column is None else schema.index_of(sort_column)
    groups: "OrderedDict[object, List[list]]" = OrderedDict()
    for r in records:
        groups.setdefault(r[ki], []).append(list(r))
    out = []
    for _, rows in groups.items():
        if si is not None:
            rows = sorted(rows, key=lambda r: r[si])
        out.append(rows)
    return out


def sequence_offset(sequences: Sequence[List[list]], schema: Schema,
                    columns: Sequence[str], offset: int
                    ) -> List[List[list]]:
    """Shift the named columns by `offset` steps within each sequence,
    trimming steps whose shifted values fall outside (ref:
    `transform/sequence/SequenceOffsetTransform.java`, InBuilt trim
    mode). A positive offset pairs step t's other columns with the named
    columns' values from step t-offset (past values)."""
    idx = [schema.index_of(c) for c in columns]
    out = []
    for seq in sequences:
        n = len(seq)
        if n <= abs(offset):
            continue
        rows = []
        rng = range(offset, n) if offset >= 0 else range(0, n + offset)
        for t in rng:
            row = list(seq[t])
            for i in idx:
                row[i] = seq[t - offset][i]
            rows.append(row)
        out.append(rows)
    return out


def sequence_moving_window(sequences: Sequence[List[list]],
                           window: int, step: int = 1
                           ) -> List[List[list]]:
    """Split each sequence into overlapping windows of `window` steps
    taken every `step` steps (ref:
    `transform/sequence/window/OverlappingTimeWindowFunction.java`
    role, count-based). Sequences shorter than the window are dropped."""
    if window < 1 or step < 1:
        raise ValueError("window and step must be >= 1")
    out = []
    for seq in sequences:
        for start in range(0, len(seq) - window + 1, step):
            out.append([list(r) for r in seq[start:start + window]])
    return out
