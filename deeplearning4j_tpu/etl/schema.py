"""Schema: typed column metadata for record pipelines.

Ref: `datavec-api/.../transform/schema/Schema.java` (builder DSL with
addColumnInteger/Double/Categorical/String/Time/NDArray) — the anchor of
every TransformProcess: each transform maps an input schema to an output
schema, so pipelines are shape/type-checked before any data moves.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum
from typing import List, Optional, Sequence, Tuple


class ColumnType(Enum):
    INTEGER = "Integer"
    LONG = "Long"
    DOUBLE = "Double"
    FLOAT = "Float"
    CATEGORICAL = "Categorical"
    STRING = "String"
    TIME = "Time"
    NDARRAY = "NDArray"
    BOOLEAN = "Boolean"


@dataclass
class ColumnMetaData:
    name: str
    type: ColumnType
    state: dict = field(default_factory=dict)  # categories, shape, ranges

    def to_json(self):
        return {"name": self.name, "type": self.type.value,
                "state": self.state}

    @staticmethod
    def from_json(d):
        return ColumnMetaData(d["name"], ColumnType(d["type"]),
                              d.get("state", {}))


class Schema:
    """Immutable-ish column schema with the reference's builder DSL."""

    def __init__(self, columns: Sequence[ColumnMetaData]):
        self.columns = list(columns)
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names in {names}")

    # -- lookups -------------------------------------------------------
    def num_columns(self) -> int:
        return len(self.columns)

    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"no column {name!r}; have {self.column_names()}")

    def column(self, name: str) -> ColumnMetaData:
        return self.columns[self.index_of(name)]

    def column_type(self, name: str) -> ColumnType:
        return self.column(name).type

    def has_column(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    # -- serde (JSON round-trip like the reference's Jackson serde) ----
    def to_json(self) -> str:
        return json.dumps({"columns": [c.to_json() for c in self.columns]})

    @staticmethod
    def from_json(s: str) -> "Schema":
        d = json.loads(s)
        return Schema([ColumnMetaData.from_json(c) for c in d["columns"]])

    def __eq__(self, other):
        return isinstance(other, Schema) and self.to_json() == other.to_json()

    def __repr__(self):
        cols = ", ".join(f"{c.name}:{c.type.value}" for c in self.columns)
        return f"Schema({cols})"

    # -- builder (ref: Schema.Builder) ---------------------------------
    class Builder:
        def __init__(self):
            self._cols: List[ColumnMetaData] = []

        def _add(self, name, ctype, **state):
            self._cols.append(ColumnMetaData(name, ctype, dict(state)))
            return self

        def add_column_integer(self, name, min_value=None, max_value=None):
            return self._add(name, ColumnType.INTEGER,
                             min=min_value, max=max_value)

        def add_column_long(self, name):
            return self._add(name, ColumnType.LONG)

        def add_column_double(self, name, min_value=None, max_value=None):
            return self._add(name, ColumnType.DOUBLE,
                             min=min_value, max=max_value)

        def add_column_float(self, name):
            return self._add(name, ColumnType.FLOAT)

        def add_column_categorical(self, name, *categories):
            if len(categories) == 1 and isinstance(categories[0],
                                                   (list, tuple)):
                categories = tuple(categories[0])
            return self._add(name, ColumnType.CATEGORICAL,
                             categories=list(categories))

        def add_column_string(self, name):
            return self._add(name, ColumnType.STRING)

        def add_column_time(self, name):
            return self._add(name, ColumnType.TIME)

        def add_column_boolean(self, name):
            return self._add(name, ColumnType.BOOLEAN)

        def add_column_ndarray(self, name, shape: Tuple[int, ...]):
            return self._add(name, ColumnType.NDARRAY, shape=list(shape))

        def add_columns_double(self, *names):
            for n in names:
                self.add_column_double(n)
            return self

        def add_columns_integer(self, *names):
            for n in names:
                self.add_column_integer(n)
            return self

        def add_columns_string(self, *names):
            for n in names:
                self.add_column_string(n)
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()
