"""Reader -> DataSet bridge iterators.

Ref: `deeplearning4j-data` `RecordReaderDataSetIterator.java` and
`SequenceRecordReaderDataSetIterator.java` (alignment + masking), the
glue between DataVec readers and network `fit()`.

TPU-first: emits fixed-shape numpy batches (sequences padded to the
longest length in the DATASET, not per-batch, so every batch has one
static shape and XLA compiles the step exactly once).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..datasets import DataSet, DataSetIterator
from .records import RecordReader


def _one_hot(idx: int, n: int) -> np.ndarray:
    v = np.zeros(n, np.float32)
    v[int(idx)] = 1.0
    return v


class RecordReaderDataSetIterator(DataSetIterator):
    """Ref: RecordReaderDataSetIterator.java — batches records, splitting
    features/labels at `label_index` (one-hot for classification,
    passthrough for regression)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self._batch = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self._records: Optional[List[list]] = None
        self._matrix = None
        self._pos = 0

    def _load(self):
        if self._records is not None or self._matrix is not None:
            return
        # all-numeric fast path: slice batches out of one [rows, cols]
        # float32 matrix (native CSV parser) instead of per-row python
        m = getattr(self.reader, "matrix", None)
        self._matrix = m() if callable(m) else None
        if self._matrix is None:
            self._records = list(self.reader)

    def reset(self):
        self._pos = 0

    def batch_size(self):
        return self._batch

    def _n_rows(self):
        self._load()
        return len(self._records if self._matrix is None else self._matrix)

    def has_next(self):
        return self._pos < self._n_rows()

    def next(self):
        self._load()
        if self._matrix is not None:
            chunk = self._matrix[self._pos:self._pos + self._batch]
            self._pos += len(chunk)
            if self.label_index is None:
                # copy, not a view: in-place mutation of a returned batch
                # (normalization, augmentation) must not corrupt the
                # cached matrix for later epochs
                return np.array(chunk, np.float32, copy=True), None
            li = self.label_index % chunk.shape[1]  # negative idx parity
                                                    # with the row path
            feats = np.ascontiguousarray(
                np.delete(chunk, li, axis=1), np.float32)
            if self.regression:
                labels = chunk[:, li:li + 1].astype(np.float32)
            else:
                labels = np.eye(self.num_classes, dtype=np.float32)[
                    chunk[:, li].astype(np.int64)]
            return feats, labels
        chunk = self._records[self._pos:self._pos + self._batch]
        self._pos += len(chunk)
        if self.label_index is None:
            feats = np.asarray([[float(v) for v in r] for r in chunk],
                               np.float32)
            return feats, None
        li = self.label_index
        feats, labels = [], []
        for r in chunk:
            nli = li % len(r)  # normalize negatives so the label column
            f = [float(v) for i, v in enumerate(r) if i != nli]  # is
            # excluded from features on both the row and matrix paths
            feats.append(f)
            if self.regression:
                labels.append([float(r[li])])
            else:
                labels.append(_one_hot(int(r[li]), self.num_classes))
        return (np.asarray(feats, np.float32),
                np.asarray(labels, np.float32))

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        return self.next()


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Ref: SequenceRecordReaderDataSetIterator.java — sequence records
    to [B, T, F] batches. Variable-length sequences are padded to the
    dataset-wide max length with ALIGN_END semantics and a [B, T] mask
    (the reference's masking contract for RNNs, SURVEY.md §5.7)."""

    def __init__(self, reader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False, align_end: bool = False):
        self.reader = reader
        self._batch = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.align_end = align_end
        self._seqs: Optional[List[List[list]]] = None
        self._max_len = 0
        self._pos = 0

    def _load(self):
        if self._seqs is None:
            self._seqs = list(self.reader)
            self._max_len = max((len(s) for s in self._seqs), default=0)

    def reset(self):
        self._pos = 0

    def batch_size(self):
        return self._batch

    def has_next(self):
        self._load()
        return self._pos < len(self._seqs)

    def next(self):
        self._load()
        chunk = self._seqs[self._pos:self._pos + self._batch]
        self._pos += len(chunk)
        T = self._max_len
        li = self.label_index
        n_feat = len(chunk[0][0]) - (0 if li is None else 1)
        B = len(chunk)
        feats = np.zeros((B, T, n_feat), np.float32)
        mask = np.zeros((B, T), np.float32)
        if li is not None:
            ldim = 1 if self.regression else self.num_classes
            labels = np.zeros((B, T, ldim), np.float32)
        for b, seq in enumerate(chunk):
            L = len(seq)
            off = T - L if self.align_end else 0
            for t, rec in enumerate(seq):
                f = [float(v) for i, v in enumerate(rec) if i != li]
                feats[b, off + t] = f
                mask[b, off + t] = 1.0
                if li is not None:
                    if self.regression:
                        labels[b, off + t, 0] = float(rec[li])
                    else:
                        labels[b, off + t] = _one_hot(int(rec[li]),
                                                      self.num_classes)
        if li is None:
            return feats, None, mask
        return feats, labels, mask

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        return self.next()
