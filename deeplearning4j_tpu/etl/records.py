"""Record readers: streaming sources of (lists of) column values.

Ref: `datavec-api/.../records/reader/RecordReader.java:40` SPI and its
implementations (`impl/csv/CSVRecordReader.java`,
`impl/csv/CSVSequenceRecordReader.java`, `impl/LineRecordReader.java`,
`impl/collection/CollectionRecordReader.java`) plus the media readers
`datavec-data/datavec-data-image/.../NativeImageLoader.java` (JavaCPP
OpenCV there; PIL/numpy here) and
`datavec-data/datavec-data-audio/.../WavFileRecordReader.java` (stdlib
wave + numpy FFT here).

A "record" is a list of python/numpy values (the reference's
List<Writable>); a sequence record is a list of records. Readers are
restartable iterators (`reset()`), matching the SPI contract.
"""
from __future__ import annotations

import csv
import io
import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np


class RecordReader:
    """SPI (ref: RecordReader.java:40 — hasNext/next/reset)."""

    def __iter__(self) -> Iterator[list]:
        self.reset()
        while self.has_next():
            yield self.next()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> list:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError


def _parse_cell(s: str):
    """CSV cells come out typed like the reference's Writables: int if it
    parses, else float, else string."""
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s


class CSVRecordReader(RecordReader):
    """Ref: CSVRecordReader.java — skipNumLines + delimiter config."""

    def __init__(self, path: Optional[str] = None, skip_lines: int = 0,
                 delimiter: str = ",", text: Optional[str] = None,
                 parse: bool = True):
        if (path is None) == (text is None):
            raise ValueError("provide exactly one of path= or text=")
        self.path, self.text = path, text
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.parse = parse
        self._rows: Optional[List[list]] = None
        self._pos = 0

    def _raw_text(self) -> str:
        # no caching: matrix() and _load() each memoize their own parsed
        # product and run at most once, so holding the raw text for the
        # reader's lifetime would only triple steady-state memory
        if self.path is not None:
            with open(self.path, newline="") as f:
                return f.read()
        return self.text

    def matrix(self):
        """All-numeric fast path: the whole file parsed to one
        [rows, cols] float32 matrix via the native C parser (ref role:
        the reference's off-heap CSV vectorization). None when any cell
        is non-numeric — callers fall back to the row-wise reader, which
        keeps exact _parse_cell int/double semantics. skip_lines drops
        PHYSICAL lines here; a header whose quoted fields span lines
        leaves a non-numeric residue, so such files fall back (where
        record-wise skipping applies)."""
        if not self.parse:
            return None
        if not hasattr(self, "_matrix"):
            from ..runtime import csv_parse_floats
            src = self._raw_text()
            if self.skip_lines:
                src = "\n".join(src.splitlines()[self.skip_lines:])
            self._matrix = csv_parse_floats(src, self.delimiter)
        return self._matrix

    def _load(self):
        if self._rows is not None:
            return
        raw = list(csv.reader(io.StringIO(self._raw_text()),
                              delimiter=self.delimiter))
        raw = [r for r in raw[self.skip_lines:] if r]
        self._rows = [[_parse_cell(c) for c in r] if self.parse else r
                      for r in raw]

    def has_next(self) -> bool:
        self._load()
        return self._pos < len(self._rows)

    def next(self) -> list:
        self._load()
        row = self._rows[self._pos]
        self._pos += 1
        return list(row)

    def reset(self):
        self._pos = 0


class LineRecordReader(RecordReader):
    """Ref: LineRecordReader.java — one record per line, single string."""

    def __init__(self, path: Optional[str] = None,
                 text: Optional[str] = None):
        if (path is None) == (text is None):
            raise ValueError("provide exactly one of path= or text=")
        self.path, self.text = path, text
        self._lines: Optional[List[str]] = None
        self._pos = 0

    def _load(self):
        if self._lines is None:
            src = open(self.path).read() if self.path else self.text
            self._lines = src.splitlines()

    def has_next(self):
        self._load()
        return self._pos < len(self._lines)

    def next(self):
        self._load()
        line = self._lines[self._pos]
        self._pos += 1
        return [line]

    def reset(self):
        self._pos = 0


class CollectionRecordReader(RecordReader):
    """Ref: CollectionRecordReader.java — in-memory records."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = [list(r) for r in records]
        self._pos = 0

    def has_next(self):
        return self._pos < len(self.records)

    def next(self):
        r = self.records[self._pos]
        self._pos += 1
        return list(r)

    def reset(self):
        self._pos = 0


class NumpyRecordReader(RecordReader):
    """Rows of a feature matrix (+ optional label vector) as records —
    the nd4j RecordConverter.toRecords analogue."""

    def __init__(self, features: np.ndarray,
                 labels: Optional[np.ndarray] = None):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self._pos = 0

    def has_next(self):
        return self._pos < self.features.shape[0]

    def next(self):
        row = list(self.features[self._pos])
        if self.labels is not None:
            row.append(self.labels[self._pos])
        self._pos += 1
        return row

    def reset(self):
        self._pos = 0


class CSVSequenceRecordReader(RecordReader):
    """Ref: CSVSequenceRecordReader.java — one sequence per FILE (or per
    text blob); each line is one time step."""

    def __init__(self, paths: Optional[Sequence[str]] = None,
                 skip_lines: int = 0, delimiter: str = ",",
                 texts: Optional[Sequence[str]] = None):
        if (paths is None) == (texts is None):
            raise ValueError("provide exactly one of paths= or texts=")
        self.paths, self.texts = paths, texts
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._pos = 0

    def _n(self):
        return len(self.paths if self.paths is not None else self.texts)

    def has_next(self):
        return self._pos < self._n()

    def next(self) -> List[list]:
        if self.paths is not None:
            rr = CSVRecordReader(path=self.paths[self._pos],
                                 skip_lines=self.skip_lines,
                                 delimiter=self.delimiter)
        else:
            rr = CSVRecordReader(text=self.texts[self._pos],
                                 skip_lines=self.skip_lines,
                                 delimiter=self.delimiter)
        self._pos += 1
        return list(rr)

    def reset(self):
        self._pos = 0


class ImageRecordReader(RecordReader):
    """Ref: datavec-data-image `ImageRecordReader` + `NativeImageLoader` —
    reads image files to [H, W, C] float arrays with the label taken from
    the parent directory name (ParentPathLabelGenerator semantics).

    TPU-first: emits NHWC float32 (channels-last matches the conv stack's
    native layout) resized to a FIXED height x width so downstream batches
    are static-shaped for XLA."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 paths: Optional[Sequence[str]] = None,
                 root_dir: Optional[str] = None,
                 labels: Optional[Sequence[str]] = None):
        self.height, self.width, self.channels = height, width, channels
        if root_dir is not None:
            paths = sorted(
                os.path.join(dp, f)
                for dp, _, fs in os.walk(root_dir) for f in fs
                if f.lower().split(".")[-1] in
                ("png", "jpg", "jpeg", "bmp", "gif"))
        self.paths = list(paths or [])
        dirs = sorted({os.path.basename(os.path.dirname(p))
                       for p in self.paths})
        self.labels = list(labels) if labels is not None else dirs
        self._pos = 0

    def _load_image(self, path) -> np.ndarray:
        from PIL import Image
        img = Image.open(path)
        img = img.convert("L" if self.channels == 1 else "RGB")
        img = img.resize((self.width, self.height))
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr

    def has_next(self):
        return self._pos < len(self.paths)

    def next(self):
        path = self.paths[self._pos]
        self._pos += 1
        arr = self._load_image(path)
        label = os.path.basename(os.path.dirname(path))
        idx = self.labels.index(label) if label in self.labels else -1
        return [arr, idx]

    def reset(self):
        self._pos = 0


class WavFileRecordReader(RecordReader):
    """Ref: datavec-data-audio `WavFileRecordReader.java` (whole-file
    audio records) + the datavec audio processing tier (FFT features).
    Stdlib `wave` only — 8/16/32-bit PCM, channels mixed to mono,
    samples normalized to [-1, 1] float32; the label is the parent
    directory name (ParentPathLabelGenerator semantics, same as
    ImageRecordReader).

    Modes:
    - default: one record per file = [signal [n_samples], label_idx]
    - ``frame_length``/``frame_step`` set: overlapping windowed frames
      [n_frames, frame_length] — static-shaped per file for the
      transform pipeline
    - ``spectrogram=True`` (requires frame_length): per-frame magnitude
      of the real FFT -> [n_frames, frame_length // 2 + 1] (the
      Spectrogram feature of the reference's audio tier)
    """

    def __init__(self, paths: Optional[Sequence[str]] = None,
                 root_dir: Optional[str] = None,
                 labels: Optional[Sequence[str]] = None,
                 frame_length: Optional[int] = None,
                 frame_step: Optional[int] = None,
                 spectrogram: bool = False):
        if spectrogram and frame_length is None:
            raise ValueError("spectrogram=True requires frame_length")
        if frame_length is None and frame_step is not None:
            raise ValueError("frame_step requires frame_length (whole-"
                             "file records are unframed)")
        if root_dir is not None:
            paths = sorted(
                os.path.join(dp, f)
                for dp, _, fs in os.walk(root_dir) for f in fs
                if f.lower().endswith(".wav"))
        self.paths = list(paths or [])
        dirs = sorted({os.path.basename(os.path.dirname(p))
                       for p in self.paths})
        self.labels = list(labels) if labels is not None else dirs
        self.frame_length = frame_length
        self.frame_step = frame_step or frame_length
        self.spectrogram = spectrogram
        self.sample_rate: Optional[int] = None  # of the LAST read file
        self._pos = 0

    @staticmethod
    def _decode(path) -> Tuple[np.ndarray, int]:
        import wave
        with wave.open(path, "rb") as w:
            n = w.getnframes()
            width = w.getsampwidth()
            channels = w.getnchannels()
            rate = w.getframerate()
            raw = w.readframes(n)
        if width == 1:       # unsigned 8-bit PCM
            x = np.frombuffer(raw, np.uint8).astype(np.float32)
            x = (x - 128.0) / 128.0
        elif width == 2:     # signed 16-bit
            x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
        elif width == 4:     # signed 32-bit
            x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
        else:
            raise ValueError(f"unsupported PCM sample width {width}")
        if channels > 1:
            x = x.reshape(-1, channels).mean(axis=1)
        return x, rate

    def _features(self, x: np.ndarray) -> np.ndarray:
        if self.frame_length is None:
            return x
        fl, st = self.frame_length, self.frame_step
        n_frames = max(0, (len(x) - fl) // st + 1)
        idx = (np.arange(fl)[None, :] +
               st * np.arange(n_frames)[:, None])
        frames = x[idx] if n_frames else np.zeros((0, fl), np.float32)
        if not self.spectrogram:
            return frames.astype(np.float32)
        win = np.hanning(fl).astype(np.float32)
        return np.abs(np.fft.rfft(frames * win, axis=-1)
                      ).astype(np.float32)

    def has_next(self):
        return self._pos < len(self.paths)

    def next(self):
        path = self.paths[self._pos]
        self._pos += 1
        x, rate = self._decode(path)
        self.sample_rate = rate
        label = os.path.basename(os.path.dirname(path))
        idx = self.labels.index(label) if label in self.labels else -1
        return [self._features(x), idx]

    def reset(self):
        self._pos = 0
