"""Data analysis (ref: DataVec `datavec-local/.../AnalyzeLocal.java` +
`datavec-api/.../transform/analysis/DataAnalysis.java` and the
per-column `*AnalysisCounter` hierarchy: one pass over a record reader
producing per-column statistics — min/max/mean/stddev/zero and
positive/negative counts + histograms for numeric columns, unique value
counts for categorical/string, used to drive normalizers and data-
quality checks before training).

TPU-first: the analysis is host-side numpy (it feeds config decisions,
not the device hot path); accumulation is streaming (Welford), so the
reader never needs to fit in memory.
"""
from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .schema import ColumnType, Schema


class NumericalColumnAnalysis:
    """Ref: `analysis/columns/DoubleAnalysis.java` (+Integer/Long)."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.count_zero = 0
        self.count_positive = 0
        self.count_negative = 0
        self.count_nan = 0
        self.min: float = math.inf
        self.max: float = -math.inf
        self._mean = 0.0
        self._m2 = 0.0
        self._values: List[float] = []   # reservoir for the histogram

    _RESERVOIR = 100_000

    def add(self, v: float):
        v = float(v)
        if math.isnan(v):
            self.count_nan += 1
            return
        self.count += 1
        if v == 0:
            self.count_zero += 1
        elif v > 0:
            self.count_positive += 1
        else:
            self.count_negative += 1
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        d = v - self._mean                 # Welford streaming moments
        self._mean += d / self.count
        self._m2 += d * (v - self._mean)
        if len(self._values) < self._RESERVOIR:
            self._values.append(v)

    @property
    def mean(self) -> float:
        return self._mean if self.count else float("nan")

    @property
    def stddev(self) -> float:
        if self.count < 2:
            return 0.0
        return math.sqrt(self._m2 / (self.count - 1))

    def histogram(self, bins: int = 20):
        """(counts, bin_edges) over the sampled values (ref: the
        histogram buckets DataAnalysis renders)."""
        if not self._values:
            return np.zeros(bins), np.linspace(0, 1, bins + 1)
        return np.histogram(np.asarray(self._values), bins=bins)

    def to_dict(self) -> Dict[str, Any]:
        return {"type": "numerical", "count": self.count,
                "min": None if self.count == 0 else self.min,
                "max": None if self.count == 0 else self.max,
                "mean": None if self.count == 0 else self.mean,
                "stddev": self.stddev, "count_zero": self.count_zero,
                "count_positive": self.count_positive,
                "count_negative": self.count_negative,
                "count_nan": self.count_nan}


class CategoricalColumnAnalysis:
    """Ref: `analysis/columns/CategoricalAnalysis.java` — per-category
    counts (also used for string columns' unique accounting)."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.category_counts: Dict[str, int] = {}

    def add(self, v):
        self.count += 1
        key = str(v)
        self.category_counts[key] = self.category_counts.get(key, 0) + 1

    @property
    def unique_count(self) -> int:
        return len(self.category_counts)

    def to_dict(self) -> Dict[str, Any]:
        return {"type": "categorical", "count": self.count,
                "unique": self.unique_count,
                "category_counts": dict(sorted(
                    self.category_counts.items(),
                    key=lambda kv: -kv[1])[:50])}


class DataAnalysis:
    """Ref: `transform/analysis/DataAnalysis.java` — schema + per-column
    analyses, JSON-serializable for reports."""

    def __init__(self, schema: Schema, analyses: Dict[str, Any]):
        self.schema = schema
        self.analyses = analyses

    def column_analysis(self, name: str):
        return self.analyses[name]

    def to_json(self) -> str:
        return json.dumps({n: a.to_dict() for n, a in self.analyses.items()},
                          indent=2)

    def __repr__(self):
        rows = []
        for n, a in self.analyses.items():
            d = a.to_dict()
            if d["type"] == "numerical":
                rows.append(f"{n}: n={d['count']} min={d['min']} "
                            f"max={d['max']} mean={d['mean']:.4g} "
                            f"std={d['stddev']:.4g}")
            else:
                rows.append(f"{n}: n={d['count']} unique={d['unique']}")
        return "DataAnalysis(\n  " + "\n  ".join(rows) + "\n)"


_NUMERIC = {ColumnType.INTEGER, ColumnType.LONG, ColumnType.DOUBLE,
            ColumnType.FLOAT}


def analyze(schema: Schema, data) -> DataAnalysis:
    """One streaming pass over `data` (a RecordReader or iterable of
    rows) computing per-column statistics (ref:
    `AnalyzeLocal.analyze(schema, recordReader)`)."""
    analyses: Dict[str, Any] = {}
    for meta in schema.columns:
        if meta.type in _NUMERIC:
            analyses[meta.name] = NumericalColumnAnalysis(meta.name)
        else:
            analyses[meta.name] = CategoricalColumnAnalysis(meta.name)
    names = schema.column_names()

    rows = data if not hasattr(data, "has_next") else _reader_iter(data)
    for row in rows:
        if len(row) != len(names):
            raise ValueError(
                f"row width {len(row)} != schema width {len(names)}")
        for name, v in zip(names, row):
            a = analyses[name]
            if isinstance(a, NumericalColumnAnalysis):
                try:
                    a.add(float(v))
                except (TypeError, ValueError):
                    a.count_nan += 1
            else:
                a.add(v)
    return DataAnalysis(schema, analyses)


def _reader_iter(reader):
    reader.reset()
    while reader.has_next():
        yield reader.next()
