"""ETL pipeline — the DataVec-class layer (ref: L4, `datavec/`).

Re-implements the reference's record-oriented ETL surface
(`datavec-api/.../records/reader/RecordReader.java:40`, `Writable` types,
`transform/TransformProcess.java:86`, `Schema`, and
`datavec-local/.../LocalTransformExecutor.java`) the TPU-native way:
records flow as python/numpy values through lazy reader + transform
pipelines, and the terminal iterators emit FIXED-SHAPE numpy batches that
feed the device via the async double-buffered path
(`datasets.AsyncDataSetIterator`) — static shapes keep XLA from
recompiling, and ETL stays on host threads off the device critical path
(the reference's AsyncDataSetIterator philosophy, SURVEY.md §2.3 D8).
"""
from .schema import ColumnType, Schema
from .analysis import (CategoricalColumnAnalysis, DataAnalysis,
                       NumericalColumnAnalysis, analyze)
from .records import (CSVRecordReader, CSVSequenceRecordReader,
                      CollectionRecordReader, ImageRecordReader,
                      WavFileRecordReader,
                      LineRecordReader, NumpyRecordReader, RecordReader)
from .transform import (Condition, Filter, LocalTransformExecutor,
                        TransformProcess)
from .iterators import (RecordReaderDataSetIterator,
                        SequenceRecordReaderDataSetIterator)
from .normalize import (ImagePreProcessingScaler, NormalizerMinMaxScaler,
                        NormalizerStandardize)
from .relational import (Join, Reducer, convert_to_sequence,
                         sequence_moving_window, sequence_offset)

__all__ = [
    "Join", "Reducer", "convert_to_sequence", "sequence_offset",
    "sequence_moving_window",
    "Schema", "ColumnType", "RecordReader", "CSVRecordReader",
    "CSVSequenceRecordReader", "CollectionRecordReader", "LineRecordReader",
    "ImageRecordReader", "WavFileRecordReader", "NumpyRecordReader", "TransformProcess",
    "LocalTransformExecutor", "Filter", "Condition",
    "RecordReaderDataSetIterator", "SequenceRecordReaderDataSetIterator",
    "NormalizerStandardize", "NormalizerMinMaxScaler",
    "ImagePreProcessingScaler",
]
