"""TransformProcess: schema-checked record transformation pipelines.

Ref: `datavec-api/.../transform/TransformProcess.java:86` (builder DSL,
JSON serde), transform impls under `transform/transform/**` (categorical,
doublemath, string, condition, filter packages), and the single-machine
executor `datavec-local/.../LocalTransformExecutor.java`.

Each step maps (record, schema) -> record and declares its output schema,
so a pipeline is type-checked at BUILD time against the input schema —
before any data moves (same contract as the reference). JSON round-trip
of the whole process is preserved (the property the reference's Spark
executor and UI rely on).
"""
from __future__ import annotations

import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

from .schema import ColumnMetaData, ColumnType, Schema


# ---------------------------------------------------------------------------
# conditions (ref: transform/condition/** — column conditions + ops)
# ---------------------------------------------------------------------------
_COND_OPS = {
    "Equal": lambda v, t: v == t,
    "NotEqual": lambda v, t: v != t,
    "LessThan": lambda v, t: v < t,
    "LessOrEqual": lambda v, t: v <= t,
    "GreaterThan": lambda v, t: v > t,
    "GreaterOrEqual": lambda v, t: v >= t,
    "InSet": lambda v, t: v in t,
    "NotInSet": lambda v, t: v not in t,
}


class Condition:
    """Column-value condition (ref: `ColumnCondition` hierarchy)."""

    def __init__(self, column: str, op: str, value: Any):
        if op not in _COND_OPS:
            raise ValueError(f"unknown condition op {op!r}; "
                             f"have {sorted(_COND_OPS)}")
        self.column, self.op, self.value = column, op, value

    def matches(self, record: list, schema: Schema) -> bool:
        return _COND_OPS[self.op](record[schema.index_of(self.column)],
                                  self.value)

    def to_json(self):
        v = list(self.value) if isinstance(self.value, (set, tuple)) \
            else self.value
        return {"column": self.column, "op": self.op, "value": v}

    @staticmethod
    def from_json(d):
        v = d["value"]
        if d["op"] in ("InSet", "NotInSet") and isinstance(v, list):
            v = set(v)
        return Condition(d["column"], d["op"], v)


class Filter:
    """Record filter: DROP records matching the condition (ref:
    `transform/filter/ConditionFilter.java`)."""

    def __init__(self, condition: Condition):
        self.condition = condition

    def removes(self, record, schema) -> bool:
        return self.condition.matches(record, schema)


# ---------------------------------------------------------------------------
# step registry: name -> (apply(record, schema, spec) -> record,
#                         out_schema(schema, spec) -> schema)
# ---------------------------------------------------------------------------
_MATH_OPS = {
    "Add": lambda v, s: v + s, "Subtract": lambda v, s: v - s,
    "Multiply": lambda v, s: v * s, "Divide": lambda v, s: v / s,
    "ReverseSubtract": lambda v, s: s - v,
    "ReverseDivide": lambda v, s: s / v,
    "Modulus": lambda v, s: v % s, "ScalarMin": lambda v, s: min(v, s),
    "ScalarMax": lambda v, s: max(v, s), "Power": lambda v, s: v ** s,
}

_MATH_FNS = {
    "log": math.log, "log2": lambda v: math.log2(v), "log10": math.log10,
    "exp": math.exp, "sqrt": math.sqrt, "abs": abs, "sign":
    lambda v: (v > 0) - (v < 0), "floor": math.floor, "ceil": math.ceil,
    "sin": math.sin, "cos": math.cos, "tanh": math.tanh,
}


def _copy_schema_replace(schema, name, new_meta):
    cols = [new_meta if c.name == name else c for c in schema.columns]
    return Schema(cols)


class _Step:
    def __init__(self, kind: str, spec: dict):
        self.kind = kind
        self.spec = spec

    def to_json(self):
        return {"kind": self.kind, "spec": self.spec}


def _remove_columns(record, schema, spec):
    drop = {schema.index_of(n) for n in spec["columns"]}
    return [v for i, v in enumerate(record) if i not in drop]


def _remove_columns_schema(schema, spec):
    drop = set(spec["columns"])
    for n in drop:
        schema.index_of(n)  # validate
    return Schema([c for c in schema.columns if c.name not in drop])


def _keep_columns(record, schema, spec):
    keep = [schema.index_of(n) for n in spec["columns"]]
    return [record[i] for i in keep]


def _keep_columns_schema(schema, spec):
    return Schema([schema.column(n) for n in spec["columns"]])


def _rename(record, schema, spec):
    return record


def _rename_schema(schema, spec):
    old, new = spec["old"], spec["new"]
    c = schema.column(old)
    return _copy_schema_replace(schema, old,
                                ColumnMetaData(new, c.type, dict(c.state)))


def _reorder(record, schema, spec):
    order = [schema.index_of(n) for n in spec["columns"]]
    rest = [i for i in range(len(record)) if i not in order]
    return [record[i] for i in order + rest]


def _reorder_schema(schema, spec):
    named = [schema.column(n) for n in spec["columns"]]
    rest = [c for c in schema.columns if c.name not in spec["columns"]]
    return Schema(named + rest)


def _duplicate(record, schema, spec):
    i = schema.index_of(spec["column"])
    return record + [record[i]]


def _duplicate_schema(schema, spec):
    c = schema.column(spec["column"])
    return Schema(schema.columns +
                  [ColumnMetaData(spec["new_name"], c.type, dict(c.state))])


def _cat_to_int(record, schema, spec):
    i = schema.index_of(spec["column"])
    cats = schema.column(spec["column"]).state["categories"]
    out = list(record)
    out[i] = cats.index(out[i])
    return out


def _cat_to_int_schema(schema, spec):
    c = schema.column(spec["column"])
    if c.type != ColumnType.CATEGORICAL:
        raise ValueError(f"{spec['column']} is {c.type}, not CATEGORICAL")
    return _copy_schema_replace(
        schema, c.name, ColumnMetaData(c.name, ColumnType.INTEGER, {}))


def _cat_to_onehot(record, schema, spec):
    i = schema.index_of(spec["column"])
    cats = schema.column(spec["column"]).state["categories"]
    onehot = [1 if record[i] == c else 0 for c in cats]
    return record[:i] + onehot + record[i + 1:]


def _cat_to_onehot_schema(schema, spec):
    c = schema.column(spec["column"])
    if c.type != ColumnType.CATEGORICAL:
        raise ValueError(f"{spec['column']} is {c.type}, not CATEGORICAL")
    i = schema.index_of(c.name)
    new = [ColumnMetaData(f"{c.name}[{cat}]", ColumnType.INTEGER, {})
           for cat in c.state["categories"]]
    return Schema(schema.columns[:i] + new + schema.columns[i + 1:])


def _int_to_cat(record, schema, spec):
    i = schema.index_of(spec["column"])
    out = list(record)
    out[i] = spec["categories"][int(out[i])]
    return out


def _int_to_cat_schema(schema, spec):
    c = schema.column(spec["column"])
    return _copy_schema_replace(
        schema, c.name, ColumnMetaData(c.name, ColumnType.CATEGORICAL,
                                       {"categories": spec["categories"]}))


def _string_to_cat(record, schema, spec):
    return record


def _string_to_cat_schema(schema, spec):
    c = schema.column(spec["column"])
    if c.type != ColumnType.STRING:
        raise ValueError(f"{spec['column']} is {c.type}, not STRING")
    return _copy_schema_replace(
        schema, c.name, ColumnMetaData(c.name, ColumnType.CATEGORICAL,
                                       {"categories": spec["categories"]}))


def _math_op(record, schema, spec):
    i = schema.index_of(spec["column"])
    out = list(record)
    out[i] = _MATH_OPS[spec["op"]](out[i], spec["scalar"])
    return out


def _math_fn(record, schema, spec):
    i = schema.index_of(spec["column"])
    out = list(record)
    out[i] = _MATH_FNS[spec["fn"]](out[i])
    return out


def _same_schema(schema, spec):
    return schema


def _replace_string(record, schema, spec):
    i = schema.index_of(spec["column"])
    out = list(record)
    out[i] = out[i].replace(spec["find"], spec["replace"])
    return out


def _map_string(record, schema, spec):
    i = schema.index_of(spec["column"])
    out = list(record)
    out[i] = spec["mapping"].get(out[i], out[i])
    return out


def _append_string(record, schema, spec):
    i = schema.index_of(spec["column"])
    out = list(record)
    out[i] = str(out[i]) + spec["suffix"]
    return out


def _conditional_replace(record, schema, spec):
    cond = Condition.from_json(spec["condition"])
    if cond.matches(record, schema):
        i = schema.index_of(spec["column"])
        out = list(record)
        out[i] = spec["value"]
        return out
    return record


def _to_type(record, schema, spec):
    i = schema.index_of(spec["column"])
    out = list(record)
    caster = {"Integer": int, "Double": float, "String": str}[spec["to"]]
    out[i] = caster(out[i])
    return out


def _to_type_schema(schema, spec):
    c = schema.column(spec["column"])
    t = {"Integer": ColumnType.INTEGER, "Double": ColumnType.DOUBLE,
         "String": ColumnType.STRING}[spec["to"]]
    return _copy_schema_replace(schema, c.name,
                                ColumnMetaData(c.name, t, {}))


_STEPS: Dict[str, tuple] = {
    "RemoveColumns": (_remove_columns, _remove_columns_schema),
    "RemoveAllColumnsExceptFor": (_keep_columns, _keep_columns_schema),
    "RenameColumn": (_rename, _rename_schema),
    "ReorderColumns": (_reorder, _reorder_schema),
    "DuplicateColumn": (_duplicate, _duplicate_schema),
    "CategoricalToInteger": (_cat_to_int, _cat_to_int_schema),
    "CategoricalToOneHot": (_cat_to_onehot, _cat_to_onehot_schema),
    "IntegerToCategorical": (_int_to_cat, _int_to_cat_schema),
    "StringToCategorical": (_string_to_cat, _string_to_cat_schema),
    "MathOp": (_math_op, _same_schema),
    "MathFunction": (_math_fn, _same_schema),
    "ReplaceString": (_replace_string, _same_schema),
    "MapString": (_map_string, _same_schema),
    "AppendString": (_append_string, _same_schema),
    "ConditionalReplaceValue": (_conditional_replace, _same_schema),
    "ConvertType": (_to_type, _to_type_schema),
}


class TransformProcess:
    """An ordered list of schema-checked steps + filters.

    Ref: TransformProcess.java:86 — built with Builder, executed by
    LocalTransformExecutor (or Spark there; plain python here, with the
    heavy numeric batch work happening downstream on-device)."""

    def __init__(self, initial_schema: Schema, steps: List[_Step]):
        self.initial_schema = initial_schema
        self.steps = steps
        # validate: thread the schema through every step now
        s = initial_schema
        self._schemas = [s]
        for st in steps:
            if st.kind == "Filter":
                Condition.from_json(st.spec["condition"])
            else:
                s = _STEPS[st.kind][1](s, st.spec)
            self._schemas.append(s)
        self.final_schema = s

    def execute(self, record: list) -> Optional[list]:
        """Transform one record; None if a filter dropped it."""
        s_iter = iter(self._schemas)
        schema = next(s_iter)
        for st in self.steps:
            if st.kind == "Filter":
                cond = Condition.from_json(st.spec["condition"])
                if cond.matches(record, schema):
                    return None
                next(s_iter)
            else:
                record = _STEPS[st.kind][0](record, schema, st.spec)
                schema = next(s_iter)
        return record

    # -- serde ---------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "initialSchema": json.loads(self.initial_schema.to_json()),
            "steps": [s.to_json() for s in self.steps]})

    @staticmethod
    def from_json(s: str) -> "TransformProcess":
        d = json.loads(s)
        schema = Schema.from_json(json.dumps(d["initialSchema"]))
        steps = [_Step(sd["kind"], sd["spec"]) for sd in d["steps"]]
        return TransformProcess(schema, steps)

    # -- builder (ref: TransformProcess.Builder) -----------------------
    class Builder:
        def __init__(self, initial_schema: Schema):
            self._schema = initial_schema
            self._steps: List[_Step] = []

        def _add(self, kind, **spec):
            self._steps.append(_Step(kind, spec))
            return self

        def remove_columns(self, *names):
            return self._add("RemoveColumns", columns=list(names))

        def remove_all_columns_except_for(self, *names):
            return self._add("RemoveAllColumnsExceptFor",
                             columns=list(names))

        def rename_column(self, old, new):
            return self._add("RenameColumn", old=old, new=new)

        def reorder_columns(self, *names):
            return self._add("ReorderColumns", columns=list(names))

        def duplicate_column(self, column, new_name):
            return self._add("DuplicateColumn", column=column,
                             new_name=new_name)

        def categorical_to_integer(self, column):
            return self._add("CategoricalToInteger", column=column)

        def categorical_to_one_hot(self, column):
            return self._add("CategoricalToOneHot", column=column)

        def integer_to_categorical(self, column, categories):
            return self._add("IntegerToCategorical", column=column,
                             categories=list(categories))

        def string_to_categorical(self, column, categories):
            return self._add("StringToCategorical", column=column,
                             categories=list(categories))

        def double_math_op(self, column, op, scalar):
            return self._add("MathOp", column=column, op=op, scalar=scalar)

        integer_math_op = double_math_op

        def double_math_function(self, column, fn):
            return self._add("MathFunction", column=column, fn=fn)

        def replace_string(self, column, find, replace):
            return self._add("ReplaceString", column=column, find=find,
                             replace=replace)

        def map_string(self, column, mapping: Dict[str, str]):
            return self._add("MapString", column=column,
                             mapping=dict(mapping))

        def append_string(self, column, suffix):
            return self._add("AppendString", column=column, suffix=suffix)

        def conditional_replace_value(self, column, value,
                                      condition: Condition):
            return self._add("ConditionalReplaceValue", column=column,
                             value=value, condition=condition.to_json())

        def convert_to_integer(self, column):
            return self._add("ConvertType", column=column, to="Integer")

        def convert_to_double(self, column):
            return self._add("ConvertType", column=column, to="Double")

        def convert_to_string(self, column):
            return self._add("ConvertType", column=column, to="String")

        def filter(self, condition_or_filter):
            cond = (condition_or_filter.condition
                    if isinstance(condition_or_filter, Filter)
                    else condition_or_filter)
            return self._add("Filter", condition=cond.to_json())

        def build(self) -> "TransformProcess":
            return TransformProcess(self._schema, self._steps)

    @staticmethod
    def builder(initial_schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(initial_schema)


class LocalTransformExecutor:
    """Ref: `datavec-local/.../LocalTransformExecutor.java` — execute a
    TransformProcess over a collection of records in-process."""

    @staticmethod
    def execute(records: Sequence[list],
                tp: TransformProcess) -> List[list]:
        out = []
        for r in records:
            t = tp.execute(list(r))
            if t is not None:
                out.append(t)
        return out

    @staticmethod
    def execute_reader(reader, tp: TransformProcess) -> List[list]:
        return LocalTransformExecutor.execute(list(reader), tp)
