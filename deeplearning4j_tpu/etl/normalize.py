"""Data normalizers with fit/transform/revert + serialization.

Ref: nd4j `linalg/dataset/api/preprocessor/{NormalizerStandardize,
NormalizerMinMaxScaler,ImagePreProcessingScaler}.java` — the reference
persists the fitted normalizer inside the model zip
(`ModelSerializer.addNormalizerToModel`), and restores it with the model;
`save()/load()` here produce the npz payload the serializer embeds.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class _FittedNormalizer:
    _fields: tuple = ()

    def _stats_axes(self, x):
        # statistics per-feature over all leading axes (batch, time, ...)
        return tuple(range(x.ndim - 1))

    def fit(self, data):
        """`data`: array [N, ...features] or a DataSetIterator."""
        if hasattr(data, "reset") or hasattr(data, "has_next"):
            feats = []
            for batch in data:
                feats.append(np.asarray(
                    batch[0] if isinstance(batch, (tuple, list))
                    else batch.features))
            if hasattr(data, "reset"):
                data.reset()
            x = np.concatenate(feats, axis=0)
        else:
            x = np.asarray(data)
        self._fit_array(x)
        return self

    def transform(self, x):
        raise NotImplementedError

    def revert(self, x):
        raise NotImplementedError

    def pre_process(self, dataset):
        """In-place DataSet feature transform (ref: preProcess)."""
        dataset.features = self.transform(np.asarray(dataset.features))
        return dataset

    def save(self, path: str):
        np.savez(path, __class__=type(self).__name__,
                 **{f: getattr(self, f) for f in self._fields})

    @staticmethod
    def load(path: str):
        with np.load(path, allow_pickle=False) as z:
            cls_name = str(z["__class__"])
            cls = {c.__name__: c for c in
                   (NormalizerStandardize, NormalizerMinMaxScaler,
                    ImagePreProcessingScaler)}[cls_name]
            obj = cls.__new__(cls)
            for f in cls._fields:
                setattr(obj, f, z[f])
        return obj


class NormalizerStandardize(_FittedNormalizer):
    """Zero-mean unit-variance per feature (ref:
    NormalizerStandardize.java)."""

    _fields = ("mean", "std")

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def _fit_array(self, x):
        axes = self._stats_axes(x)
        self.mean = x.mean(axis=axes)
        self.std = x.std(axis=axes) + 1e-8

    def transform(self, x):
        return ((np.asarray(x) - self.mean) / self.std).astype(np.float32)

    def revert(self, x):
        return np.asarray(x) * self.std + self.mean


class NormalizerMinMaxScaler(_FittedNormalizer):
    """Scale features to [min_range, max_range] (ref:
    NormalizerMinMaxScaler.java)."""

    _fields = ("data_min", "data_max", "range")

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.range = np.asarray([min_range, max_range], np.float64)
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def _fit_array(self, x):
        axes = self._stats_axes(x)
        self.data_min = x.min(axis=axes)
        self.data_max = x.max(axis=axes)

    def transform(self, x):
        lo, hi = self.range
        denom = np.where(self.data_max > self.data_min,
                         self.data_max - self.data_min, 1.0)
        z = (np.asarray(x) - self.data_min) / denom
        return (z * (hi - lo) + lo).astype(np.float32)

    def revert(self, x):
        lo, hi = self.range
        z = (np.asarray(x) - lo) / (hi - lo)
        return z * (self.data_max - self.data_min) + self.data_min


class ImagePreProcessingScaler(_FittedNormalizer):
    """Pixel scaling [0, max_pixel] -> [min, max] with no fitting needed
    (ref: ImagePreProcessingScaler.java)."""

    _fields = ("lo", "hi", "max_pixel")

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.lo = np.float64(min_range)
        self.hi = np.float64(max_range)
        self.max_pixel = np.float64(max_pixel)

    def fit(self, data):
        return self  # stateless

    def _fit_array(self, x):
        pass

    def transform(self, x):
        z = np.asarray(x) / self.max_pixel
        return (z * (self.hi - self.lo) + self.lo).astype(np.float32)

    def revert(self, x):
        z = (np.asarray(x) - self.lo) / (self.hi - self.lo)
        return z * self.max_pixel
