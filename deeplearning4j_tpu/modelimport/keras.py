"""Keras h5 import.

Ref: `deeplearning4j-modelimport/.../keras/KerasModelImport.java`
(`importKerasSequentialModelAndWeights` :88 -> MultiLayerNetwork,
`importKerasModelAndWeights` :50 -> ComputationGraph), the per-layer
mappers under `keras/layers/**`, and `KerasModel`/`KerasSequentialModel`.

Reads the h5 directly (config JSON + weight groups) — no TF/Keras runtime
needed at import time, mirroring the reference's JavaCPP-hdf5 approach.
Weight layouts transfer verbatim: this framework is channels-last with
Keras-identical Dense [in,out], Conv [kh,kw,in,out], and LSTM gate order
(i,f,c,o), so import is a copy, not a transpose dance.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import h5py
import jax.numpy as jnp
import numpy as np

from ..nn import NeuralNetConfiguration
from ..nn.graph import (ComputationGraph, ElementWiseVertex, GraphBuilder,
                        MergeVertex)
from ..nn.layers import (ActivationLayer, BatchNormalization,
                         ConvolutionLayer, DenseLayer, DropoutLayer,
                         EmbeddingLayer, GlobalPoolingLayer, Layer,
                         OutputLayer, SubsamplingLayer, Upsampling2D,
                         ZeroPaddingLayer)
from ..nn.layers.convolutional import (Convolution1D, Convolution3D,
                                       Cropping1D, Cropping2D,
                                       Deconvolution2D,
                                       DepthwiseConvolution2D,
                                       SeparableConvolution2D,
                                       Subsampling1DLayer,
                                       Subsampling3DLayer, Upsampling1D,
                                       Upsampling3D, ZeroPadding1DLayer)
from ..nn.layers.recurrent import (GRU, LSTM, Bidirectional, LastTimeStep,
                                   SimpleRnn)
from ..nn.conf.dropout import (AlphaDropout, GaussianDropout, GaussianNoise,
                               SpatialDropout)
from ..nn.multilayer import MultiLayerNetwork

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "sigmoid": "sigmoid", "softmax": "softmax", "tanh": "tanh",
    "elu": "elu", "selu": "selu", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid",
    "swish": "swish", "silu": "swish", "gelu": "gelu",
    "leaky_relu": "leakyrelu", "mish": "mish", "exponential": "identity",
}


def _act(cfg, key: str = "activation", default: str = "linear") -> str:
    a = cfg.get(key, default)
    if isinstance(a, dict):  # serialized activation object
        a = a.get("class_name", default).lower()
    if a not in _ACTIVATIONS:
        raise ValueError(f"unsupported Keras {key} {a!r}")
    return _ACTIVATIONS[a]


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


class _Skip:
    """Marker for config-only Keras layers with no runtime op here
    (InputLayer, Flatten — dense auto-flattens)."""


_LOSS_BY_ACTIVATION = {"softmax": "mcxent", "sigmoid": "xent"}


def _as_output_layer(d: DenseLayer) -> OutputLayer:
    act = d.activation.to_json()
    act_name = act.get("@class", act) if isinstance(act, dict) else act
    loss = _LOSS_BY_ACTIVATION.get(act_name, "mse")
    return OutputLayer(n_out=d.n_out, loss=loss, activation=d.activation,
                       has_bias=d.has_bias, name=d.name)


def _check_masking_semantics_graph(layer_cfgs, mapped):
    """enforce_training_config guards for Masking semantics this import
    cannot reproduce exactly (without enforce these import with the
    documented divergences):

    - a merge vertex consuming a masked branch: keras ANDs masks at
      Concatenate, while the graph forward uses the DL4J MergeVertex OR
      rule (an unmasked sequence sibling clears the merged mask);
    - a sequence-shaped (per-timestep) OUTPUT downstream of Masking:
      keras excludes masked timesteps from the LOSS, but the derived
      mask here lives in the forward pass only — pass an explicit label
      mask to fit() instead."""
    from ..nn.layers import MaskingLayer
    masking_nodes = {nm for nm, l in mapped.items()
                     if isinstance(l, MaskingLayer)}
    if not masking_nodes:
        return
    # transitive downstream closure of the masking nodes
    downstream = set(masking_nodes)
    changed = True
    while changed:
        changed = False
        for lc in layer_cfgs:
            nm = lc["config"].get("name")
            if nm in downstream:
                continue
            if any(i in downstream for i in _inbound_names(lc)):
                downstream.add(nm)
                changed = True
    for lc in layer_cfgs:
        nm = lc["config"].get("name")
        if nm not in downstream or nm in masking_nodes:
            continue
        if lc["class_name"] in _MERGE_VERTICES and any(
                i in downstream for i in _inbound_names(lc)):
            # merging a masked branch with a possibly-unmasked one
            others = [i for i in _inbound_names(lc)
                      if i not in downstream]
            if others:
                raise ValueError(
                    "keras Masking feeding a merge vertex alongside an "
                    "unmasked branch is not mapped exactly (keras ANDs "
                    "masks; the DL4J MergeVertex OR rule applies here) "
                    "— import with enforce_training_config=False to "
                    "accept the divergence")
    # per-timestep outputs: the derived mask does not reach the loss —
    # but only outputs DOWNSTREAM of a Masking node see a derived mask;
    # unrelated unmasked branches are exact and must not be rejected
    out_like = [nm for nm, l in mapped.items()
                if nm in downstream
                and getattr(l, "kind", "") in ("rnnoutput", "rnnloss")]
    if out_like:
        raise ValueError(
            "keras Masking with a per-timestep output is not mapped "
            "exactly: the derived mask is forward-only and does not "
            "reach the loss — pass an explicit label mask to fit(), "
            "or import with enforce_training_config=False")


def _map_layer(class_name: str, cfg: dict) -> Optional[object]:
    """One Keras layer config -> framework Layer (or _Skip / None).
    Ref: the 60+ mappers under `keras/layers/**` — same dispatch shape."""
    name = cfg.get("name")
    if class_name == "InputLayer" or class_name == "Flatten":
        return _Skip()
    if class_name == "Dense":
        return DenseLayer(n_out=cfg["units"], activation=_act(cfg),
                          has_bias=cfg.get("use_bias", True), name=name)
    if class_name in ("Conv2D", "Convolution2D"):
        return ConvolutionLayer(
            n_out=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            padding=cfg.get("padding", "valid"),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True),
            name=name)
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        return SubsamplingLayer(
            kernel=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            padding=cfg.get("padding", "valid"),
            pooling="max" if class_name.startswith("Max") else "avg",
            name=name)
    if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
                      "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
        return GlobalPoolingLayer(
            pooling="max" if "Max" in class_name else "avg",
            keep_dims=bool(cfg.get("keepdims", False)), name=name)
    if class_name == "BatchNormalization":
        return BatchNormalization(decay=cfg.get("momentum", 0.99),
                                  eps=cfg.get("epsilon", 1e-3), name=name)
    if class_name == "Dropout":
        return DropoutLayer(dropout=cfg["rate"], name=name)
    if class_name == "Masking":
        from ..nn.layers import MaskingLayer
        return MaskingLayer(mask_value=cfg.get("mask_value", 0.0),
                            name=name)
    if class_name == "Activation":
        return ActivationLayer(activation=_act(cfg), name=name)
    if class_name == "ZeroPadding2D":
        p = cfg.get("padding", 1)
        return ZeroPaddingLayer(padding=p, name=name)
    if class_name == "UpSampling2D":
        return Upsampling2D(size=_pair(cfg.get("size", 2)), name=name)
    if class_name == "Embedding":
        return EmbeddingLayer(n_in=cfg["input_dim"], n_out=cfg["output_dim"],
                              name=name)
    if class_name == "LSTM":
        lstm = LSTM(n_out=cfg["units"], activation=_act(cfg),
                    gate_activation=_act(cfg, "recurrent_activation",
                                         "sigmoid"),
                    name=name)
        if not cfg.get("return_sequences", False):
            return LastTimeStep(lstm, name=name)
        return lstm
    if class_name == "SimpleRNN":
        rnn = SimpleRnn(n_out=cfg["units"], activation=_act(cfg), name=name)
        if not cfg.get("return_sequences", False):
            return LastTimeStep(rnn, name=name)
        return rnn
    if class_name == "GRU":
        gru = GRU(n_out=cfg["units"], activation=_act(cfg),
                  gate_activation=_act(cfg, "recurrent_activation",
                                       "sigmoid"),
                  reset_after=cfg.get("reset_after", True), name=name)
        if not cfg.get("return_sequences", False):
            return LastTimeStep(gru, name=name)
        return gru
    if class_name == "Bidirectional":
        inner_cfg = cfg["layer"]["config"]
        if not inner_cfg.get("return_sequences", False):
            raise ValueError(
                "Bidirectional(return_sequences=False) import is "
                "unsupported: Keras merges each direction's LAST output, "
                "which has no LastTimeStep equivalent here — re-export "
                "with return_sequences=True + a pooling layer")
        inner = _map_layer(cfg["layer"]["class_name"], inner_cfg)
        mode = {"concat": "concat", "sum": "add", "mul": "mul",
                "ave": "average"}.get(cfg.get("merge_mode", "concat"))
        if mode is None:
            raise ValueError(
                f"unsupported Bidirectional merge_mode "
                f"{cfg.get('merge_mode')!r}")
        return Bidirectional(layer=inner, mode=mode, name=name)
    if class_name in ("Conv1D", "Convolution1D"):
        k = cfg["kernel_size"]
        return Convolution1D(
            n_out=cfg["filters"], kernel=k[0] if isinstance(k, list) else k,
            stride=(cfg.get("strides", [1]) or [1])[0]
            if isinstance(cfg.get("strides"), list) else cfg.get("strides", 1),
            padding=cfg.get("padding", "valid"), activation=_act(cfg),
            has_bias=cfg.get("use_bias", True), name=name)
    if class_name in ("SeparableConv2D", "SeparableConvolution2D"):
        return SeparableConvolution2D(
            n_out=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            padding=cfg.get("padding", "valid"),
            depth_multiplier=cfg.get("depth_multiplier", 1),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True),
            name=name)
    if class_name == "DepthwiseConv2D":
        return DepthwiseConvolution2D(
            kernel=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            padding=cfg.get("padding", "valid"),
            depth_multiplier=cfg.get("depth_multiplier", 1),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True),
            name=name)
    if class_name in ("Conv2DTranspose", "Deconvolution2D"):
        return Deconvolution2D(
            n_out=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            padding=cfg.get("padding", "valid"), activation=_act(cfg),
            has_bias=cfg.get("use_bias", True), name=name)
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        pool = cfg.get("pool_size", 2)
        pool = pool[0] if isinstance(pool, list) else pool
        stride = cfg.get("strides") or pool
        stride = stride[0] if isinstance(stride, list) else stride
        return Subsampling1DLayer(
            kernel=pool, stride=stride, padding=cfg.get("padding", "valid"),
            pooling="max" if class_name.startswith("Max") else "avg",
            name=name)
    if class_name == "Cropping2D":
        c = cfg.get("cropping", 0)
        return Cropping2D(cropping=c, name=name)
    if class_name == "Conv3D":
        return Convolution3D(
            n_out=cfg["filters"], kernel=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1, 1))),
            padding=cfg.get("padding", "valid"),
            dilation=tuple(cfg.get("dilation_rate", (1, 1, 1))),
            activation=_act(cfg), has_bias=cfg.get("use_bias", True),
            name=name)
    if class_name in ("MaxPooling3D", "AveragePooling3D"):
        pool = tuple(cfg.get("pool_size", (2, 2, 2)))
        return Subsampling3DLayer(
            kernel=pool, stride=tuple(cfg.get("strides") or pool),
            padding=cfg.get("padding", "valid"),
            pooling="max" if class_name.startswith("Max") else "avg",
            name=name)
    if class_name == "UpSampling1D":
        return Upsampling1D(size=int(cfg.get("size", 2)), name=name)
    if class_name == "UpSampling3D":
        return Upsampling3D(size=tuple(cfg.get("size", (2, 2, 2))),
                            name=name)
    if class_name == "ZeroPadding1D":
        p = cfg.get("padding", 1)
        p = (p, p) if isinstance(p, int) else tuple(p)
        return ZeroPadding1DLayer(padding=p, name=name)
    if class_name == "Cropping1D":
        c = cfg.get("cropping", 0)
        c = (c, c) if isinstance(c, int) else tuple(c)
        return Cropping1D(cropping=c, name=name)
    if class_name == "Reshape":
        from ..nn.layers.misc import ReshapeLayer
        return ReshapeLayer(target_shape=tuple(cfg["target_shape"]),
                            name=name)
    if class_name == "ReLU":
        # keras.layers.ReLU(max_value, negative_slope, threshold) — the
        # max_value=6 form is MobileNet's ReLU6
        mv = cfg.get("max_value")
        ns = float(cfg.get("negative_slope", 0.0) or 0.0)
        th = float(cfg.get("threshold", 0.0) or 0.0)
        if ns == 0.0 and th == 0.0 and mv is None:
            return ActivationLayer(activation="relu", name=name)
        if ns == 0.0 and th == 0.0 and float(mv) == 6.0:
            return ActivationLayer(activation="relu6", name=name)
        if mv is None and th == 0.0:
            return ActivationLayer(
                activation={"@class": "leakyrelu", "alpha": ns},
                name=name)
        raise ValueError(f"unsupported ReLU config {cfg!r}")
    if class_name == "LeakyReLU":
        alpha = cfg.get("negative_slope", cfg.get("alpha", 0.3))
        return ActivationLayer(
            activation={"@class": "leakyrelu", "alpha": float(alpha)},
            name=name)
    if class_name == "ELU":
        return ActivationLayer(
            activation={"@class": "elu",
                        "alpha": float(cfg.get("alpha", 1.0))},
            name=name)
    if class_name == "GaussianNoise":
        return DropoutLayer(dropout=GaussianNoise(cfg.get("stddev", 0.1)),
                            name=name)
    if class_name == "GaussianDropout":
        return DropoutLayer(dropout=GaussianDropout(cfg.get("rate", 0.5)),
                            name=name)
    if class_name == "AlphaDropout":
        return DropoutLayer(dropout=AlphaDropout(cfg.get("rate", 0.05)),
                            name=name)
    if class_name == "SpatialDropout2D":
        return DropoutLayer(dropout=SpatialDropout(cfg.get("rate", 0.5)),
                            name=name)
    raise ValueError(f"unsupported Keras layer type {class_name!r} "
                     f"(layer {name!r})")


# merge layers -> graph vertices (functional models only)
_MERGE_VERTICES = {
    "Concatenate": lambda cfg: MergeVertex(),
    "Add": lambda cfg: ElementWiseVertex("add"),
    "Subtract": lambda cfg: ElementWiseVertex("subtract"),
    "Multiply": lambda cfg: ElementWiseVertex("product"),
    "Average": lambda cfg: ElementWiseVertex("average"),
    "Maximum": lambda cfg: ElementWiseVertex("max"),
}


# ---------------------------------------------------------------------------
# weights
# ---------------------------------------------------------------------------
def _layer_weights(f: h5py.File, layer_name: str) -> Dict[str, np.ndarray]:
    """Collect datasets under model_weights/<layer> keyed by basename
    (Keras 3 nests groups; Keras 2 uses weight_names attrs — walking the
    tree handles both)."""
    out: Dict[str, np.ndarray] = {}
    grp = f["model_weights"]
    if layer_name not in grp:
        return out

    def visit(name, obj):
        if isinstance(obj, h5py.Dataset):
            base = name.split("/")[-1].split(":")[0]
            out[base] = np.asarray(obj)
            # Bidirectional wrappers nest forward_*/backward_* groups
            # whose basenames collide; keep direction-prefixed copies
            if "forward" in name:
                out[f"forward:{base}"] = out[base]
            elif "backward" in name:
                out[f"backward:{base}"] = out[base]
    grp[layer_name].visititems(visit)
    return out


def _dw_kernel(w):
    """Keras depthwise kernel (kh, kw, C, mult) -> grouped-conv HWIO
    (kh, kw, 1, C*mult) with C-major output ordering (matches XLA's
    feature_group_count channel layout)."""
    kh, kw_, c, m = w.shape
    return w.reshape(kh, kw_, 1, c * m)


_PARAM_MAP = {
    # our param name -> keras dataset basename (optionally with a layout
    # transform), per layer kind
    "dense": {"W": "kernel", "b": "bias"},
    "output": {"W": "kernel", "b": "bias"},
    "conv2d": {"W": "kernel", "b": "bias"},
    "conv1d": {"W": "kernel", "b": "bias"},
    "conv3d": {"W": "kernel", "b": "bias"},
    "batchnorm": {"gamma": "gamma", "beta": "beta"},
    "embedding": {"W": "embeddings"},
    "lstm": {"W": "kernel", "U": "recurrent_kernel", "b": "bias"},
    "simplernn": {"W": "kernel", "U": "recurrent_kernel", "b": "bias"},
    # keras GRU with reset_after stores bias as (2, 3H): row 0 input
    # bias, row 1 recurrent bias
    "gru": {"W": "kernel", "U": "recurrent_kernel",
            "b": ("bias", lambda w: w[0] if w.ndim == 2 else w),
            "b_rec": ("bias", lambda w: w[1] if w.ndim == 2 else
                      np.zeros_like(w))},
    # keras Conv2DTranspose kernel is (kh, kw, out, in) applied with
    # transpose_kernel=True; our deconv2d runs lax.conv_transpose with a
    # plain HWIO kernel, so convert by flipping the spatial dims and
    # swapping in/out (verified equivalent vs real Keras)
    "deconv2d": {"W": ("kernel",
                       lambda w: np.transpose(w[::-1, ::-1], (0, 1, 3, 2))),
                 "b": "bias"},
    # Keras 2 names the depthwise kernel "depthwise_kernel"; Keras 3's
    # h5 export calls it plain "kernel" — accept either
    "depthwiseconv2d": {"W": (["depthwise_kernel", "kernel"], _dw_kernel),
                        "b": "bias"},
    "sepconv2d": {"dW": ("depthwise_kernel", _dw_kernel),
                  "pW": "pointwise_kernel", "b": "bias"},
}


def _translate_params(kind: str, ours: dict, keras_w: Dict[str, np.ndarray],
                      layer_name: str, layer=None) -> dict:
    if kind == "bidirectional":
        # split direction-prefixed datasets, translate each half with the
        # wrapped layer's own mapping, re-prefix to our f_/b_ params.
        # Unwrap MaskZeroLayer/LastTimeStep first: `layer` may be the
        # wrapper, and reading .layer.kind off the wrapper returns
        # "bidirectional" again (double-split drops every weight)
        bidir = _unwrap(layer) if layer is not None else None
        inner_kind = bidir.layer.kind if bidir is not None else "lstm"
        fwd = {k.split(":", 1)[1]: v for k, v in keras_w.items()
               if k.startswith("forward:")}
        bwd = {k.split(":", 1)[1]: v for k, v in keras_w.items()
               if k.startswith("backward:")}
        ours_f = {k[2:]: v for k, v in ours.items() if k.startswith("f_")}
        ours_b = {k[2:]: v for k, v in ours.items() if k.startswith("b_")}
        tf_ = _translate_params(inner_kind, ours_f, fwd, layer_name)
        tb_ = _translate_params(inner_kind, ours_b, bwd, layer_name)
        return {**{f"f_{k}": v for k, v in tf_.items()},
                **{f"b_{k}": v for k, v in tb_.items()}}
    mapping = _PARAM_MAP.get(kind)
    if mapping is None:
        if ours:
            raise ValueError(f"no weight mapping for layer kind {kind!r} "
                             f"({layer_name!r})")
        return ours
    new = {}
    for pname, template in ours.items():
        spec = mapping.get(pname)
        if isinstance(spec, tuple):
            kname, transform = spec
        else:
            kname, transform = spec, None
        if isinstance(kname, list):  # candidate names (Keras 2 vs 3)
            kname = next((k for k in kname if k in keras_w), None)
        if kname is None or kname not in keras_w:
            new[pname] = template  # keep init (e.g. missing bias)
            continue
        w = keras_w[kname]
        if transform is not None:
            w = transform(np.asarray(w))
        if tuple(w.shape) != tuple(np.shape(template)):
            raise ValueError(
                f"shape mismatch importing {layer_name!r}.{pname}: "
                f"keras {w.shape} vs model {np.shape(template)}")
        new[pname] = jnp.asarray(w)
    return new


def _bn_state(keras_w) -> Optional[dict]:
    if "moving_mean" in keras_w:
        return {"mean": jnp.asarray(keras_w["moving_mean"]),
                "var": jnp.asarray(keras_w["moving_variance"])}
    return None


def _unwrap(layer):
    """Peel recurrent wrappers (MaskZeroLayer(LastTimeStep(LSTM)) ...)."""
    from ..nn.layers.recurrent import MaskZeroLayer
    while isinstance(layer, (LastTimeStep, MaskZeroLayer)):
        layer = layer.layer
    return layer


def _wrapped_kind(layer) -> str:
    return _unwrap(layer).kind


def _input_type(list_builder, batch_shape):
    from ..nn.conf import InputType
    dims = [d for d in batch_shape[1:]]
    if len(dims) == 4:
        return list_builder.input_type(InputType.convolutional3d(*dims))
    if len(dims) == 3:
        return list_builder.input_type_convolutional(*dims)
    if len(dims) == 2:
        return list_builder.input_type_recurrent(dims[1], timesteps=dims[0])
    return list_builder.input_type_feed_forward(dims[0])


def _map_training_config(f, enforce: bool):
    """Map the h5 `training_config` attr (model.compile state) to
    (updater, loss_name). Ref: KerasModelImport's enforceTrainingConfig
    + KerasOptimizerUtils/KerasLossUtils — when `enforce` is False,
    unmappable pieces are skipped; when True they raise."""
    from .. import learning as U
    raw = f.attrs.get("training_config")
    if raw is None:
        if enforce:
            raise ValueError("model was saved without training_config "
                             "(not compiled) but enforce_training_config"
                             "=True")
        return None, None
    tc = json.loads(raw if isinstance(raw, str) else raw.decode())
    upd = None
    oc = tc.get("optimizer_config") or {}
    name = str(oc.get("class_name") or "").lower()
    ocfg = (oc.get("config") or {})
    # Keras 3 stores 'learning_rate'; Keras 2 h5 files store 'lr'
    lr = ocfg.get("learning_rate", ocfg.get("lr", 1e-3))
    if isinstance(lr, dict):  # lr schedule object
        if enforce:
            raise ValueError("keras learning-rate schedules are not "
                             "mapped; resolve to a constant lr first")
        lr = (lr.get("config") or {}).get("initial_learning_rate", 1e-3)
    lr = float(lr)
    if name == "adam":
        upd = U.Adam(lr, ocfg.get("beta_1", 0.9),
                     ocfg.get("beta_2", 0.999),
                     ocfg.get("epsilon", 1e-7))
    elif name == "sgd":
        mom = float(ocfg.get("momentum", 0.0) or 0.0)
        upd = U.Nesterovs(lr, mom) if mom else U.Sgd(lr)
    elif name == "rmsprop":
        upd = U.RmsProp(lr, ocfg.get("rho", 0.9),
                        ocfg.get("epsilon", 1e-7))
    elif name == "adagrad":
        upd = U.AdaGrad(lr)
    elif name == "adamax":
        upd = U.AdaMax(lr)
    elif name == "nadam":
        upd = U.Nadam(lr)
    elif name and enforce:
        raise ValueError(f"unsupported keras optimizer {name!r}")
    def _loss_str(sp):
        # a loss-object dict carries class_name/config.name; anything
        # else string-like passes through
        if isinstance(sp, dict):
            cfg_v = sp.get("config")
            name = cfg_v.get("name") if isinstance(cfg_v, dict) else None
            cls_v = sp.get("class_name")
            sp = name or (cls_v if isinstance(cls_v, str) else None)
        return sp if isinstance(sp, str) else None

    def _check_sparse(l):
        if l == "sparse_categorical_crossentropy":
            if enforce:
                raise ValueError(
                    "sparse_categorical_crossentropy is not mapped (the "
                    "mcxent loss expects one-hot labels; integer-label "
                    "sparse CE would silently optimize a wrong objective) "
                    "— one-hot the labels and recompile, or import with "
                    "enforce_training_config=False and set the loss")
            return None
        return l

    raw_loss = tc.get("loss")
    # a serialized loss OBJECT has a class_name string (keras serde
    # invariant); a per-output dict maps output-layer names to specs.
    # Checking the class_name TYPE keeps an output literally named
    # "config" or "class_name" from being misparsed as a loss object.
    if (isinstance(raw_loss, dict)
            and not isinstance(raw_loss.get("class_name"), str)):
        # keras multi-output per-output dict form {'out_name': spec}:
        # map each entry; the whole dict is unmappable only if some
        # ENTRY is (advisor r4: dropping a fully-mappable dict left
        # compiled functional models without restored losses)
        loss = {k: _check_sparse(_loss_str(v))
                for k, v in raw_loss.items()}
        if not loss or any(v is None for v in loss.values()):
            loss = None
    else:
        loss = _check_sparse(_loss_str(raw_loss)) \
            if raw_loss is not None else None
    if loss is None and raw_loss is not None and enforce:
        raise ValueError(f"unsupported keras loss spec {raw_loss!r}")
    return upd, loss


class KerasModelImport:
    """Ref: KerasModelImport.java:50 (functional) / :88 (sequential)."""

    # -- sequential -> MultiLayerNetwork -------------------------------
    @staticmethod
    def import_keras_sequential_model_and_weights(
            path: str, enforce_training_config: bool = False
    ) -> MultiLayerNetwork:
        with h5py.File(path, "r") as f:
            cfg = json.loads(f.attrs["model_config"])
            if cfg["class_name"] != "Sequential":
                raise ValueError(
                    f"{path} is a {cfg['class_name']} model; use "
                    "import_keras_model_and_weights")
            layer_cfgs = cfg["config"]["layers"]
            batch_shape = None
            mapped: List[Tuple[str, object]] = []
            for lc in layer_cfgs:
                c = lc["config"]
                if lc["class_name"] == "InputLayer":
                    batch_shape = c.get("batch_shape") or c.get(
                        "batch_input_shape")
                if batch_shape is None:
                    bs = c.get("batch_shape") or c.get("batch_input_shape")
                    if bs:
                        batch_shape = bs
                layer = _map_layer(lc["class_name"], c)
                if not isinstance(layer, _Skip):
                    mapped.append((c.get("name"), layer))
            if batch_shape is None:
                raise ValueError("could not determine model input shape")

            # make the head trainable: final Dense -> OutputLayer with the
            # loss implied by its activation (ref: KerasLoss mapping /
            # enforceTrainingConfig behavior)
            if mapped and type(mapped[-1][1]) is DenseLayer:
                nm, d = mapped[-1]
                mapped[-1] = (nm, _as_output_layer(d))
            if enforce_training_config:
                from ..nn.layers import MaskingLayer
                has_masking = any(isinstance(l, MaskingLayer)
                                  for _, l in mapped)
                if has_masking and mapped and getattr(
                        mapped[-1][1], "kind", "") in ("rnnoutput",
                                                       "rnnloss"):
                    raise ValueError(
                        "keras Masking with a per-timestep output is "
                        "not mapped exactly: the derived mask is "
                        "forward-only and does not reach the loss — "
                        "pass an explicit label mask to fit(), or "
                        "import with enforce_training_config=False")

            # restore the compile-time training config (optimizer + loss)
            # so an imported model fine-tunes with the same settings
            upd, loss_name = _map_training_config(
                f, enforce_training_config)
            b = NeuralNetConfiguration.builder()
            if upd is not None:
                b = b.updater(upd)
            lb = b.list()
            for _, layer in mapped:
                lb = lb.layer(layer)
            if isinstance(loss_name, dict):
                # per-output dict on a Sequential = one output
                loss_name = (next(iter(loss_name.values()))
                             if len(loss_name) == 1 else None)
            if loss_name is not None and mapped:
                if not hasattr(mapped[-1][1], "loss"):
                    if enforce_training_config:
                        raise ValueError(
                            "compiled loss cannot be attached: the "
                            "final imported layer "
                            f"({type(mapped[-1][1]).__name__}) is not "
                            "an output layer")
                else:
                    from .. import losses as _L
                    try:
                        mapped[-1][1].loss = _L.get(loss_name)
                    except Exception:
                        if enforce_training_config:
                            raise
            lb = _input_type(lb, batch_shape)
            net = MultiLayerNetwork(lb.build()).init()

            # copy weights
            for i, (kname, layer) in enumerate(mapped):
                key = net._layer_keys[i]
                keras_w = _layer_weights(f, kname)
                kind = _wrapped_kind(layer)
                if key in net._params:
                    net._params[key] = _translate_params(
                        kind, net._params[key], keras_w, kname,
                        layer=layer)
                if kind == "batchnorm":
                    st = _bn_state(keras_w)
                    if st is not None:
                        net._net_state[key] = st
        return net

    # -- functional -> ComputationGraph --------------------------------
    @staticmethod
    def import_keras_model_and_weights(
            path: str, enforce_training_config: bool = False
    ) -> ComputationGraph:
        with h5py.File(path, "r") as f:
            cfg = json.loads(f.attrs["model_config"])
            if cfg["class_name"] == "Sequential":
                raise ValueError(
                    f"{path} is Sequential; use "
                    "import_keras_sequential_model_and_weights")
            gcfg = cfg["config"]
            # restore compile-time optimizer (+ loss, attached below)
            upd, loss_name = _map_training_config(
                f, enforce_training_config)
            base = NeuralNetConfiguration.builder()
            if upd is not None:
                base = base.updater(upd)
            builder = GraphBuilder(base)
            input_names = []
            mapped: Dict[str, object] = {}
            shapes: Dict[str, list] = {}
            # positional references from DATA-path nodes only: the
            # aux mask subgraph (NotEqual -> Any) references itself
            # positionally, which must not veto dropping it
            _aux = {lc2["config"].get("name") for lc2 in gcfg["layers"]
                    if lc2["class_name"] in ("NotEqual", "Any")}
            _positional_refs = {n for lc2 in gcfg["layers"]
                                if lc2["config"].get("name") not in _aux
                                for n in _inbound_names(lc2)}
            for lc in gcfg["layers"]:
                c = lc["config"]
                nm = c["name"]
                inbound = _inbound_names(lc)
                if lc["class_name"] == "InputLayer":
                    input_names.append(nm)
                    shapes[nm] = c.get("batch_shape") or c.get(
                        "batch_input_shape")
                    continue
                if lc["class_name"] in ("NotEqual", "Any"):
                    # keras-3 functional serialization materializes the
                    # Masking mask computation as auxiliary NotEqual/Any
                    # nodes wired to consumers via kwargs only; our
                    # MaskingLayer derives the mask in-band, so these
                    # nodes have no data-path consumers — drop them.
                    # Safety: a model legitimately using
                    # keras.ops.not_equal/any IN the data path would be
                    # positionally referenced — refuse those clearly
                    if nm in _positional_refs:
                        raise ValueError(
                            f"unsupported Keras layer type "
                            f"{lc['class_name']!r} in the data path "
                            f"(layer {nm!r})")
                    continue
                if lc["class_name"] in _MERGE_VERTICES:
                    builder.add_vertex(nm, _MERGE_VERTICES[lc["class_name"]](c),
                                       *inbound)
                    continue
                layer = _map_layer(lc["class_name"], c)
                if isinstance(layer, _Skip):
                    # passthrough: alias by scale-1 vertex
                    from ..nn.graph import ScaleVertex
                    builder.add_vertex(nm, ScaleVertex(1.0), *inbound)
                    continue
                mapped[nm] = layer
                builder.add_layer(nm, layer, *inbound)
            if enforce_training_config:
                _check_masking_semantics_graph(gcfg["layers"], mapped)
            builder.add_inputs(*input_names)
            outs = gcfg["output_layers"]
            if (len(outs) >= 2 and isinstance(outs[0], str)
                    and isinstance(outs[1], int)):
                outs = [outs]  # single output stored flat: [name, 0, 0]
            out_names = [_node_name(o) for o in outs]
            builder.set_outputs(*out_names)
            # make output nodes trainable: final Dense -> OutputLayer
            # (same conversion the sequential path applies; without it
            # the imported graph has no loss head and cannot fit)
            for onm in out_names:
                ol = mapped.get(onm)
                if type(ol) is DenseLayer:
                    new = _as_output_layer(ol)
                    mapped[onm] = new
                    builder._nodes[onm].layer = new
            from ..nn.conf import InputType
            types = []
            for nm in input_names:
                dims = shapes[nm][1:]
                if len(dims) == 3:
                    types.append(InputType.convolutional(*dims))
                elif len(dims) == 2:
                    types.append(InputType.recurrent(dims[1], dims[0]))
                else:
                    types.append(InputType.feed_forward(dims[0]))
            builder.set_input_types(*types)
            if loss_name is not None:
                from .. import losses as _L
                for onm in out_names:
                    ol = mapped.get(onm)
                    # per-output dict form: each output gets ITS entry
                    this_loss = (loss_name.get(onm)
                                 if isinstance(loss_name, dict)
                                 else loss_name)
                    if this_loss is None:
                        if enforce_training_config:
                            raise ValueError(
                                "compiled per-output loss dict has no "
                                f"entry for output {onm!r}")
                        continue
                    if ol is None or not hasattr(ol, "loss"):
                        if enforce_training_config:
                            raise ValueError(
                                "compiled loss cannot be attached: "
                                f"output node {onm!r} is not an output "
                                "layer")
                        continue
                    try:
                        ol.loss = _L.get(this_loss)
                    except Exception:
                        if enforce_training_config:
                            raise
            graph = ComputationGraph(builder.build()).init()

            for nm, layer in mapped.items():
                keras_w = _layer_weights(f, nm)
                kind = _wrapped_kind(layer)
                if nm in graph._params:
                    graph._params[nm] = _translate_params(
                        kind, graph._params[nm], keras_w, nm,
                        layer=layer)
                if kind == "batchnorm":
                    st = _bn_state(keras_w)
                    if st is not None:
                        graph._net_state[nm] = st
        return graph

    # convenience dispatch (ref: importKerasModelAndWeights handles both)
    @staticmethod
    def import_model(path: str):
        with h5py.File(path, "r") as f:
            cls = json.loads(f.attrs["model_config"])["class_name"]
        if cls == "Sequential":
            return KerasModelImport.\
                import_keras_sequential_model_and_weights(path)
        return KerasModelImport.import_keras_model_and_weights(path)


def _node_name(entry) -> str:
    """output_layers entries: [name, node_idx, tensor_idx] (Keras 2/3)."""
    if isinstance(entry, (list, tuple)):
        return entry[0]
    return entry


def _inbound_names(layer_cfg: dict) -> List[str]:
    """Extract predecessor layer names from inbound_nodes — handles both
    Keras 2 nested lists and Keras 3 keras_history dicts."""
    names: List[str] = []

    def walk(obj):
        if isinstance(obj, dict):
            if "keras_history" in obj:
                names.append(obj["keras_history"][0])
            elif "args" in obj:
                # keras-3 node form {'args': [...], 'kwargs': {...}}:
                # only positional args are DATA inputs — kwargs carry
                # auxiliary wiring (e.g. the mask tensor from the
                # serialized Masking infrastructure's NotEqual/Any
                # nodes, which the importer drops)
                walk(obj["args"])
            else:
                for v in obj.values():
                    walk(v)
        elif isinstance(obj, (list, tuple)):
            if (len(obj) >= 3 and isinstance(obj[0], str)
                    and isinstance(obj[1], int)):
                names.append(obj[0])  # Keras 2: [name, node, tensor, {}]
            else:
                for v in obj:
                    walk(v)

    walk(layer_cfg.get("inbound_nodes", []))
    # dedupe preserving order (multi-arg merges list each input once)
    seen = set()
    out = []
    for n in names:
        if n not in seen:
            seen.add(n)
            out.append(n)
    return out
