"""TF GraphDef import -> SameDiff.

Ref: `nd4j-api/.../imports/graphmapper/tf/TFGraphMapper.java:59`
(protobuf GraphDef -> SameDiff; per-op import mappings), exercised in the
reference by the TFGraphs regression corpus and `BERTGraphTest.java:29`.

Self-contained: a minimal protobuf wire-format reader parses GraphDef /
NodeDef / AttrValue / TensorProto directly (the reference links libnd4j's
protobuf; importing the 2GB TF runtime just to read a graph would be the
opposite of that design). Each TF op maps to a catalog op recorded into a
SameDiff, so an imported graph executes through the same whole-graph-jit
path as a natively built one.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..autodiff import SameDiff

# ---------------------------------------------------------------------------
# minimal protobuf wire reader
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a protobuf message."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 1:  # 64-bit
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:  # 32-bit
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


# TF DataType enum values we support (types.proto: DT_FLOAT=1, DT_DOUBLE=2,
# DT_INT32=3, DT_UINT8=4, DT_INT8=6, DT_STRING=7, DT_INT64=9, DT_BOOL=10,
# DT_BFLOAT16=14, DT_HALF=19)
_TF_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
              6: np.int8, 7: str, 9: np.int64, 10: np.bool_,
              19: np.float16}
try:  # bfloat16 consts (rare in frozen graphs; jax ships ml_dtypes)
    import ml_dtypes as _mld
    _TF_DTYPES[14] = _mld.bfloat16
except ImportError:  # pragma: no cover
    pass


def _parse_shape(buf: bytes) -> List[int]:
    dims = []
    for f, _, v in _fields(buf):
        if f == 2:  # Dim
            size = 0
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    # zigzag not used; int64 varint (may be huge for -1)
                    size = v2 if v2 < (1 << 62) else v2 - (1 << 64)
            dims.append(size)
        elif f == 3:  # unknown_rank
            return []
    return dims


def _parse_tensor(buf: bytes) -> np.ndarray:
    # TensorProto fields (tensor.proto): 1=dtype 2=tensor_shape
    # 4=tensor_content 5=float_val 6=double_val 7=int_val 8=string_val
    # 10=int64_val 11=bool_val 13=half_val (bits of f16/bf16)
    dtype = np.float32
    shape: List[int] = []
    content = b""
    float_vals: List[float] = []
    int_vals: List[int] = []
    half_bits: List[int] = []
    for f, wt, v in _fields(buf):
        if f == 1:
            dtype = _TF_DTYPES.get(v, np.float32)
        elif f == 2:
            shape = _parse_shape(v)
        elif f == 4:
            content = v
        elif f == 5:  # float_val (wire: 32-bit, or packed)
            if wt == 2:  # packed
                float_vals.extend(struct.unpack(f"<{len(v)//4}f", v))
            else:
                float_vals.append(struct.unpack("<f", v)[0])
        elif f == 6:  # double_val (wire: 64-bit, or packed)
            if wt == 2:
                float_vals.extend(struct.unpack(f"<{len(v)//8}d", v))
            else:
                float_vals.append(struct.unpack("<d", v)[0])
        elif f in (7, 10, 11):  # int_val / int64_val / bool_val
            # sign-correct: varints encode negative ints as huge unsigned
            if wt == 2:
                pos = 0
                while pos < len(v):
                    iv, pos = _read_varint(v, pos)
                    int_vals.append(iv if iv < (1 << 62) else iv - (1 << 64))
            else:
                int_vals.append(v if v < (1 << 62) else v - (1 << 64))
        elif f == 13:  # half_val: raw f16/bf16 bit patterns as varints
            if wt == 2:
                pos = 0
                while pos < len(v):
                    iv, pos = _read_varint(v, pos)
                    half_bits.append(iv)
            else:
                half_bits.append(v)
        elif f == 8 and wt == 2:  # string_val — unsupported payload
            raise ValueError("string tensors not supported")
    size = int(np.prod(shape)) if shape else 1
    if content:
        arr = np.frombuffer(content, dtype=dtype)
    elif half_bits:
        arr = np.asarray(half_bits, "<u2").view(dtype)
        if arr.size == 1 and size > 1:
            arr = np.full(size, arr[0], dtype)
    elif float_vals:
        arr = np.asarray(float_vals, dtype)
        if arr.size == 1 and size > 1:
            arr = np.full(size, arr[0], dtype)
    elif int_vals:
        arr = np.asarray(int_vals, dtype)
        if arr.size == 1 and size > 1:
            arr = np.full(size, arr[0], dtype)
    else:
        arr = np.zeros(size, dtype)
    return arr.reshape(shape)


def _parse_attr(buf: bytes) -> Any:
    for f, wt, v in _fields(buf):
        if f == 2:  # s: bytes
            return v.decode("utf-8", "replace")
        if f == 3:  # i
            return v if v < (1 << 62) else v - (1 << 64)
        if f == 4:  # f
            return struct.unpack("<f", v)[0]
        if f == 5:  # b
            return bool(v)
        if f == 6:  # type
            return ("dtype", v)
        if f == 7:  # shape
            return _parse_shape(v)
        if f == 8:  # tensor
            return _parse_tensor(v)
        if f == 1:  # list
            items = []
            for f2, wt2, v2 in _fields(v):
                if f2 == 2:
                    items.append(v2.decode())
                elif f2 == 3:
                    if wt2 == 2:  # packed ints
                        pos = 0
                        while pos < len(v2):
                            iv, pos = _read_varint(v2, pos)
                            items.append(iv)
                    else:
                        items.append(v2)
                elif f2 == 4:
                    items.append(struct.unpack("<f", v2)[0]
                                 if wt2 == 5 else v2)
            return items
    return None


class _NodeDef:
    def __init__(self):
        self.name = ""
        self.op = ""
        self.inputs: List[str] = []
        self.attrs: Dict[str, Any] = {}


def parse_graph_def(data: bytes) -> List[_NodeDef]:
    nodes = []
    for f, _, v in _fields(data):
        if f == 1:  # NodeDef
            nd = _NodeDef()
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    nd.name = v2.decode()
                elif f2 == 2:
                    nd.op = v2.decode()
                elif f2 == 3:
                    nd.inputs.append(v2.decode())
                elif f2 == 5:  # attr map entry
                    key, val = None, None
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            key = v3.decode()
                        elif f3 == 2:
                            val = _parse_attr(v3)
                    if key is not None:
                        nd.attrs[key] = val
            nodes.append(nd)
    return nodes


# ---------------------------------------------------------------------------
# op mapping (ref: per-op import mappings on DifferentialFunction classes)
# ---------------------------------------------------------------------------


def _strides_hw(attrs) -> Tuple[int, int]:
    s = attrs.get("strides", [1, 1, 1, 1])
    return (int(s[1]), int(s[2]))  # NHWC


def _ksize_hw(attrs) -> Tuple[int, int]:
    k = attrs.get("ksize", [1, 2, 2, 1])
    return (int(k[1]), int(k[2]))


class TFGraphMapper:
    """Ref: TFGraphMapper.java:59 — importGraph(GraphDef) -> SameDiff."""

    @staticmethod
    def import_graph(source) -> SameDiff:
        """`source`: path to a frozen .pb, raw bytes, or a TF GraphDef
        object (anything with SerializeToString)."""
        if hasattr(source, "SerializeToString"):
            data = source.SerializeToString()
        elif isinstance(source, (bytes, bytearray)):
            data = bytes(source)
        else:
            with open(source, "rb") as f:
                data = f.read()
        nodes = parse_graph_def(data)
        sd = SameDiff.create()
        env: Dict[str, Any] = {}  # tf node name -> SDVariable

        def ref(inp: str):
            inp = inp.lstrip("^")
            if ":" in inp:
                base, idx = inp.rsplit(":", 1)
                if idx.isdigit() and int(idx) > 0:
                    key = f"{base}:{idx}"
                    if key in env:
                        return env[key]
                    # our multi-output vars are named base:k
                    return sd.get_variable(f"{env[base].name}:{idx}")
                inp = base
            return env[inp]

        for nd in nodes:
            TFGraphMapper._map_node(sd, nd, env, ref)
        # TF node name -> SameDiff variable name (pass-through nodes like
        # Identity don't create vars; outputs are routed through this map)
        sd.tf_name_map = {k: v.name for k, v in env.items()
                          if hasattr(v, "name")}
        return sd

    @staticmethod
    def _map_node(sd: SameDiff, nd: _NodeDef, env, ref):
        op = nd.op
        name = nd.name
        ins = [i for i in nd.inputs if not i.startswith("^")]
        a = nd.attrs
        safe = name.replace("/", "_")

        def rec(cat_op, *args, **kw):
            v = sd._record(cat_op, args, kw, name=safe)
            env[name] = v[0] if isinstance(v, tuple) else v
            if isinstance(v, tuple):
                for k in range(1, len(v)):
                    env[f"{name}:{k}"] = v[k]
            return env[name]

        if op == "Placeholder":
            shape = a.get("shape") or None
            if shape is not None:
                shape = [None if d < 0 else int(d) for d in shape]
            dt = a.get("dtype")
            np_dt = _TF_DTYPES.get(dt[1], np.float32) \
                if isinstance(dt, tuple) else np.float32
            env[name] = sd.placeholder(safe, shape, np_dt)
        elif op == "Const":
            env[name] = sd.constant(a["value"], name=safe)
        elif op in ("Identity", "StopGradient", "PreventGradient",
                    "CheckNumerics", "NoOp"):
            if ins:
                env[name] = ref(ins[0])
        elif op == "MatMul":
            rec("matmul", ref(ins[0]), ref(ins[1]),
                transpose_a=bool(a.get("transpose_a", False)),
                transpose_b=bool(a.get("transpose_b", False)))
        elif op in ("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3"):
            rec("matmul", ref(ins[0]), ref(ins[1]),
                transpose_a=bool(a.get("adj_x", False)),
                transpose_b=bool(a.get("adj_y", False)))
        elif op == "Einsum":
            rec("einsum", *[ref(i) for i in ins],
                equation=a.get("equation", ""))
        elif op == "AddN":
            rec("mergeadd", *[ref(i) for i in ins])
        elif op == "BiasAdd":
            rec("biasadd", ref(ins[0]), ref(ins[1]))
        elif op in ("Add", "AddV2"):
            rec("add", ref(ins[0]), ref(ins[1]))
        elif op == "Sub":
            rec("subtract", ref(ins[0]), ref(ins[1]))
        elif op == "Mul":
            rec("multiply", ref(ins[0]), ref(ins[1]))
        elif op in ("RealDiv", "Div"):
            rec("divide", ref(ins[0]), ref(ins[1]))
        elif op == "Maximum":
            rec("maximum", ref(ins[0]), ref(ins[1]))
        elif op == "Minimum":
            rec("minimum", ref(ins[0]), ref(ins[1]))
        elif op == "Pow":
            rec("pow", ref(ins[0]), ref(ins[1]))
        elif op == "SquaredDifference":
            rec("squaredsubtract", ref(ins[0]), ref(ins[1]))
        elif op in ("Relu", "Relu6", "Sigmoid", "Tanh", "Softplus", "Selu",
                    "Elu", "Softsign"):
            rec(op.lower(), ref(ins[0]))
        elif op == "LeakyRelu":
            rec("lrelu", ref(ins[0]), alpha=a.get("alpha", 0.2))
        elif op == "Softmax":
            rec("softmax", ref(ins[0]))
        elif op in ("Exp", "Log", "Sqrt", "Rsqrt", "Square", "Neg", "Abs",
                    "Floor", "Ceil", "Sin", "Cos", "Erf", "Erfc", "Sign",
                    "Round", "Expm1", "Log1p", "Tan", "Atan", "Sinh", "Cosh",
                    "Asin", "Acos", "Reciprocal", "Inv"):
            legacy = {"Abs": "abs", "Ceil": "ceil", "Round": "rint",
                      "Inv": "reciprocal"}
            rec("legacy." + legacy.get(op, op.lower()), ref(ins[0]))
        elif op in ("ZerosLike", "OnesLike"):
            rec("zeros_as" if op == "ZerosLike" else "ones_as", ref(ins[0]))
        elif op in ("Greater", "GreaterEqual", "Less", "LessEqual",
                    "Equal", "NotEqual"):
            cmp = {"Greater": "greater", "GreaterEqual": "greater_equal",
                   "Less": "less", "LessEqual": "less_equal",
                   "Equal": "equals", "NotEqual": "not_equals"}[op]
            rec(cmp, ref(ins[0]), ref(ins[1]))
        elif op in ("LogicalAnd", "LogicalOr", "LogicalNot"):
            b = {"LogicalAnd": "boolean_and", "LogicalOr": "boolean_or",
                 "LogicalNot": "boolean_not"}[op]
            rec(b, *[ref(i) for i in ins])
        elif op in ("Select", "SelectV2"):
            rec("select", ref(ins[0]), ref(ins[1]), ref(ins[2]))
        elif op in ("FloorDiv", "FloorMod", "Mod"):
            b = {"FloorDiv": "floordiv", "FloorMod": "floormod",
                 "Mod": "floormod"}[op]
            rec(b, ref(ins[0]), ref(ins[1]))
        elif op == "LogSoftmax":
            rec("log_softmax", ref(ins[0]))
        elif op == "ClipByValue":
            lo = float(np.asarray(ref(ins[1]).get_arr()))
            hi = float(np.asarray(ref(ins[2]).get_arr()))
            rec("clipbyvalue", ref(ins[0]), lo, hi)
        elif op == "OneHot":
            depth = int(np.asarray(ref(ins[1]).get_arr()))
            on = float(np.asarray(ref(ins[2]).get_arr()))
            off = float(np.asarray(ref(ins[3]).get_arr()))
            rec("onehot", ref(ins[0]), depth, on=on, off=off,
                axis=int(a.get("axis", -1)))
        elif op == "Fill":
            dims = tuple(int(x) for x in np.asarray(ref(ins[0]).get_arr()))
            value = np.asarray(ref(ins[1]).get_arr())
            env[name] = sd.constant(np.full(dims, value), name=safe)
        elif op == "Range":
            start, limit, delta = (np.asarray(ref(i).get_arr()) for i in ins)
            env[name] = sd.constant(np.arange(start, limit, delta), name=safe)
        elif op == "Shape":
            shp = ref(ins[0]).shape
            if shp is None or any(s is None for s in shp):
                raise ValueError(
                    f"Shape op {name!r} requires static input shapes "
                    "(freeze the graph with concrete dims)")
            env[name] = sd.constant(np.asarray(shp, np.int32), name=safe)
        elif op == "StridedSlice":
            begin = np.asarray(ref(ins[1]).get_arr()).tolist()
            end = np.asarray(ref(ins[2]).get_arr()).tolist()
            strides = np.asarray(ref(ins[3]).get_arr()).tolist()
            bm = int(a.get("begin_mask", 0))
            em = int(a.get("end_mask", 0))
            elm = int(a.get("ellipsis_mask", 0))
            nam = int(a.get("new_axis_mask", 0))
            sam = int(a.get("shrink_axis_mask", 0))
            spec = []
            for i in range(len(begin)):
                if elm & (1 << i):
                    spec.append(("e",))
                elif nam & (1 << i):
                    spec.append(("n",))
                elif sam & (1 << i):
                    spec.append(("i", int(begin[i])))
                else:
                    spec.append((
                        "s",
                        None if bm & (1 << i) else int(begin[i]),
                        None if em & (1 << i) else int(end[i]),
                        int(strides[i])))
            rec("numpy_slice", ref(ins[0]), spec=tuple(spec))
        elif op == "Slice":
            begin = tuple(int(x) for x in np.asarray(ref(ins[1]).get_arr()))
            size = np.asarray(ref(ins[2]).get_arr()).tolist()
            x = ref(ins[0])
            if any(s < 0 for s in size):  # -1 = "to the end"
                shp = x.shape
                size = [int(shp[i] - begin[i]) if s < 0 else int(s)
                        for i, s in enumerate(size)]
            rec("slice", x, begin, tuple(int(s) for s in size))
        elif op in ("Split", "SplitV"):
            if op == "Split":  # inputs: axis, value
                axis = int(np.asarray(ref(ins[0]).get_arr()))
                rec("split", ref(ins[1]), int(a.get("num_split", 1)),
                    axis=axis)
            else:  # inputs: value, size_splits, axis
                sizes = tuple(int(x)
                              for x in np.asarray(ref(ins[1]).get_arr()))
                axis = int(np.asarray(ref(ins[2]).get_arr()))
                rec("split_v", ref(ins[0]), sizes, axis=axis)
        elif op == "Unpack":
            rec("unstack", ref(ins[0]), axis=int(a.get("axis", 0)))
        elif op in ("Mean", "Sum", "Max", "Min", "Prod"):
            axes_v = ref(ins[1]).get_arr()
            axes = tuple(int(x) for x in np.atleast_1d(np.asarray(axes_v)))
            red = {"Mean": "reduce_mean", "Sum": "reduce_sum",
                   "Max": "reduce_max", "Min": "reduce_min",
                   "Prod": "reduce_prod"}[op]
            rec(red, ref(ins[0]), axes=axes,
                keep_dims=bool(a.get("keep_dims", False)))
        elif op == "Reshape":
            shape_v = ref(ins[1]).get_arr()
            rec("reshape", ref(ins[0]),
                shape=tuple(int(x) for x in np.asarray(shape_v)))
        elif op == "Transpose":
            perm = ref(ins[1]).get_arr()
            rec("permute", ref(ins[0]),
                axes=tuple(int(x) for x in np.asarray(perm)))
        elif op == "ExpandDims":
            axis = int(np.asarray(ref(ins[1]).get_arr()))
            rec("expand_dims", ref(ins[0]), axis=axis)
        elif op == "Squeeze":
            dims = a.get("squeeze_dims") or None
            rec("squeeze", ref(ins[0]),
                axis=tuple(dims) if dims else None)
        elif op == "ConcatV2":
            axis = int(np.asarray(ref(ins[-1]).get_arr()))
            rec("concat", *[ref(i) for i in ins[:-1]], axis=axis)
        elif op == "Pack":
            rec("stack", *[ref(i) for i in ins],
                axis=int(a.get("axis", 0)))
        elif op == "Conv2D":
            rec("conv2d", ref(ins[0]), ref(ins[1]),
                stride=_strides_hw(a),
                padding=a.get("padding", "SAME").lower())
        elif op == "DepthwiseConv2dNative":
            rec("depthwise_conv2d", ref(ins[0]), ref(ins[1]),
                stride=_strides_hw(a),
                padding=a.get("padding", "SAME").lower())
        elif op == "MaxPool":
            rec("maxpool2d", ref(ins[0]), kernel=_ksize_hw(a),
                stride=_strides_hw(a),
                padding=a.get("padding", "VALID").lower())
        elif op == "AvgPool":
            rec("avgpool2d", ref(ins[0]), kernel=_ksize_hw(a),
                stride=_strides_hw(a),
                padding=a.get("padding", "VALID").lower())
        elif op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            # inference form: (x - mean)/sqrt(var+eps) * gamma + beta
            rec("batchnorm", ref(ins[0]), ref(ins[3]), ref(ins[4]),
                ref(ins[1]), ref(ins[2]), eps=a.get("epsilon", 1e-3))
        elif op == "ArgMax":
            axis = int(np.asarray(ref(ins[1]).get_arr()))
            rec("argmax", ref(ins[0]), axis=axis)
        elif op == "Cast":
            dt = a.get("DstT")
            np_dt = _TF_DTYPES.get(dt[1], np.float32) \
                if isinstance(dt, tuple) else np.float32
            rec("cast", ref(ins[0]), dtype=np_dt)
        elif op == "Pad":
            pads = np.asarray(ref(ins[1]).get_arr())
            rec("pad", ref(ins[0]),
                paddings=tuple(tuple(int(x) for x in r) for r in pads))
        elif op == "Tile":
            reps = np.asarray(ref(ins[1]).get_arr())
            rec("tile", ref(ins[0]), reps=tuple(int(x) for x in reps))
        elif op == "GatherV2":
            rec("gather", ref(ins[0]), ref(ins[1]),
                axis=int(np.asarray(ref(ins[2]).get_arr())))
        else:
            raise ValueError(
                f"unsupported TF op {op!r} (node {name!r}); "
                "extend TFGraphMapper._map_node")
