"""Model import — the deeplearning4j-modelimport / nd4j-imports layer.

Ref: `deeplearning4j-modelimport/.../keras/KerasModelImport.java:50,88`
(h5 -> MultiLayerNetwork / ComputationGraph, 60+ layer mappers) and
`nd4j-api/.../imports/graphmapper/tf/TFGraphMapper.java:59`
(TF GraphDef -> SameDiff).
"""
from .keras import KerasModelImport
from .tf import TFGraphMapper

__all__ = ["KerasModelImport", "TFGraphMapper"]
