"""ONNX import -> SameDiff.

Ref: `nd4j-api/.../imports/graphmapper/onnx/OnnxGraphMapper.java` —
protobuf ModelProto -> SameDiff with per-op mappings.

Like the TF path (`modelimport.tf`), the protobuf wire format is parsed
directly (ModelProto/GraphProto/NodeProto/TensorProto) — no onnx
package needed. Covered op set targets the standard
torch/keras-exported MLP/CNN surface; unsupported ops raise with the op
name so coverage can grow incrementally.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..autodiff import SameDiff
from .tf import _fields, _read_varint  # shared wire-format reader

# ONNX TensorProto.DataType
_ONNX_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
                7: np.int64, 9: np.bool_, 11: np.float64}


def _parse_tensor(buf: bytes) -> np.ndarray:
    dims: List[int] = []
    dtype = np.float32
    raw = b""
    floats: List[float] = []
    ints: List[int] = []
    for f, wt, v in _fields(buf):
        if f == 1:  # dims (repeated int64)
            dims.append(v if v < (1 << 62) else v - (1 << 64))
        elif f == 2:  # data_type
            dtype = _ONNX_DTYPES.get(v, np.float32)
        elif f == 4 and wt == 2:  # float_data packed
            floats.extend(struct.unpack(f"<{len(v)//4}f", v))
        elif f == 4:
            floats.append(struct.unpack("<f", v)[0])
        elif f == 7:  # int64_data
            if wt == 2:
                pos = 0
                while pos < len(v):
                    iv, pos = _read_varint(v, pos)
                    ints.append(iv if iv < (1 << 62) else iv - (1 << 64))
            else:
                ints.append(v)
        elif f == 9:  # raw_data
            raw = v
    if raw:
        arr = np.frombuffer(raw, dtype=dtype)
    elif floats:
        arr = np.asarray(floats, dtype)
    elif ints:
        arr = np.asarray(ints, dtype)
    else:
        arr = np.zeros(int(np.prod(dims)) if dims else 0, dtype)
    return arr.reshape(dims) if dims else arr


def _parse_attr(buf: bytes) -> Tuple[str, Any]:
    name = ""
    val: Any = None
    ints: List[int] = []
    floats: List[float] = []
    for f, wt, v in _fields(buf):
        if f == 1:
            name = v.decode()
        elif f == 2:  # f
            val = struct.unpack("<f", v)[0]
        elif f == 3:  # i
            val = v if v < (1 << 62) else v - (1 << 64)
        elif f == 4:  # s
            val = v.decode("utf-8", "replace")
        elif f == 5:  # t (tensor)
            val = _parse_tensor(v)
        elif f == 8:  # ints (repeated) — AttributeProto field 8
            if wt == 2:
                pos = 0
                while pos < len(v):
                    iv, pos = _read_varint(v, pos)
                    ints.append(iv if iv < (1 << 62) else iv - (1 << 64))
            else:
                ints.append(v if v < (1 << 62) else v - (1 << 64))
        elif f == 7:  # floats (repeated) — AttributeProto field 7
            if wt == 2:
                floats.extend(struct.unpack(f"<{len(v)//4}f", v))
            else:
                floats.append(struct.unpack("<f", v)[0])
    if ints:
        val = ints
    elif floats and val is None:
        val = floats
    return name, val


class _OnnxNode:
    def __init__(self):
        self.op = ""
        self.name = ""
        self.inputs: List[str] = []
        self.outputs: List[str] = []
        self.attrs: Dict[str, Any] = {}


def _parse_value_info(buf: bytes) -> Tuple[str, Optional[List[int]]]:
    """ValueInfoProto -> (name, shape dims or None); 0/unknown dims map
    to None entries."""
    name = ""
    shape = None
    for f, _, v in _fields(buf):
        if f == 1:
            name = v.decode()
        elif f == 2:  # TypeProto
            for f2, _, v2 in _fields(v):
                if f2 == 1:  # tensor_type
                    for f3, _, v3 in _fields(v2):
                        if f3 == 2:  # TensorShapeProto
                            shape = []
                            for f4, _, v4 in _fields(v3):
                                if f4 == 1:  # Dimension
                                    dim = None
                                    for f5, wt5, v5 in _fields(v4):
                                        if f5 == 1:  # dim_value
                                            dim = v5
                                    shape.append(dim)
    return name, shape


def parse_model(data: bytes):
    """ModelProto -> (nodes, initializers, inputs, outputs)."""
    graph_buf = None
    for f, _, v in _fields(data):
        if f == 7:  # graph
            graph_buf = v
    if graph_buf is None:
        raise ValueError("no GraphProto in ONNX model")
    nodes: List[_OnnxNode] = []
    initializers: Dict[str, np.ndarray] = {}
    inputs: List[Tuple[str, Optional[List[int]]]] = []
    outputs: List[str] = []
    for f, _, v in _fields(graph_buf):
        if f == 1:  # node
            n = _OnnxNode()
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    n.inputs.append(v2.decode())
                elif f2 == 2:
                    n.outputs.append(v2.decode())
                elif f2 == 3:
                    n.name = v2.decode()
                elif f2 == 4:
                    n.op = v2.decode()
                elif f2 == 5:
                    k, val = _parse_attr(v2)
                    n.attrs[k] = val
            nodes.append(n)
        elif f == 5:  # initializer (TensorProto with name field 8)
            tname = ""
            for f2, _, v2 in _fields(v):
                if f2 == 8:
                    tname = v2.decode()
            initializers[tname] = _parse_tensor(v)
        elif f == 11:  # input
            inputs.append(_parse_value_info(v))
        elif f == 12:  # output
            name, _ = _parse_value_info(v)
            outputs.append(name)
    return nodes, initializers, inputs, outputs


class OnnxGraphMapper:
    """Ref: OnnxGraphMapper.java — importGraph(ModelProto) -> SameDiff."""

    @staticmethod
    def import_graph(source) -> SameDiff:
        if isinstance(source, (bytes, bytearray)):
            data = bytes(source)
        else:
            with open(source, "rb") as f:
                data = f.read()
        nodes, inits, inputs, outputs = parse_model(data)
        sd = SameDiff.create()
        env: Dict[str, Any] = {}
        # raw numpy side-table: jnp constants truncate int64 to int32,
        # which destroys ONNX's INT64_MIN/MAX open-slice sentinels —
        # const_of() prefers these originals
        env["__raw__"] = dict(inits)
        for name, arr in inits.items():
            env[name] = sd.constant(arr, name=name.replace("/", "_")
                                    .replace(".", "_"))
        graph_inputs = []
        for name, shape in inputs:
            if name in env:
                continue  # initializer doubling as graph input
            shape = None if shape is None else [
                None if (d is None or d == 0) else int(d) for d in shape]
            env[name] = sd.placeholder(name.replace("/", "_"), shape)
            graph_inputs.append(env[name].name)
        for n in nodes:
            OnnxGraphMapper._map_node(sd, n, env)
        # positional input/output names for callers feeding by order
        # (mirrors TFGraphMapper's tf_name_map contract)
        sd._onnx_inputs = graph_inputs
        sd._onnx_outputs = [env[o].name for o in outputs]
        return sd

    @staticmethod
    def _map_node(sd: SameDiff, n: _OnnxNode, env: Dict[str, Any]):
        op = n.op
        a = n.attrs
        ins = n.inputs
        safe = (n.name or n.outputs[0]).replace("/", "_").replace(".", "_")

        def rec(cat_op, *args, **kw):
            v = sd._record(cat_op, args, kw, name=safe)
            first = v[0] if isinstance(v, tuple) else v
            env[n.outputs[0]] = first
            if isinstance(v, tuple):
                for i in range(1, min(len(v), len(n.outputs))):
                    env[n.outputs[i]] = v[i]
            return first

        def const_of(name, int_exact=False):
            """Materialize a compile-time-constant input. Prefers the raw
            int64 numpy original (jnp truncates to int32, destroying
            sentinel values); torch's exporter also COMPUTES shape/pad/
            slice arguments through chains of Constant/Cast/Reshape/Add
            nodes — those chains are folded in the raw numpy int64
            domain by _fold_raw, so they land here too. When no raw
            entry exists, fold the (closed, placeholder-free) subgraph
            in jnp — but with ``int_exact=True`` (Slice/Pad bounds,
            where an already-int32-truncated INT64 sentinel would slip
            past the sentinel guard and slice wrongly) an integer result
            from that lossy path is refused instead of trusted."""
            raw = env.get("__raw__", {})
            if name in raw:
                return np.asarray(raw[name])
            v = sd.get_variable(env[name].name)
            arr = v.get_arr()
            if arr is None:
                arr = sd.output({}, [v.name])[v.name]
            arr = np.asarray(arr)
            if int_exact and np.issubdtype(arr.dtype, np.integer):
                raise ValueError(
                    f"constant input {name!r} resolves through the jnp "
                    "fold path, which truncates int64 to int32 — an "
                    "ONNX INT64 open-slice sentinel would be silently "
                    "corrupted. The producing op chain is not raw-"
                    "foldable; extend _fold_raw to cover it.")
            return arr

        if op == "Constant":
            # value arrives as a TensorProto attribute (value / value_float
            # / value_int variants; torch emits `value`)
            val = a.get("value")
            if val is None:
                val = np.asarray(a.get("value_float",
                                       a.get("value_int", 0.0)))
            env.setdefault("__raw__", {})[n.outputs[0]] = np.asarray(val)
            env[n.outputs[0]] = sd.constant(np.asarray(val), name=safe)
        elif op == "Shape":
            shape = env[ins[0]].shape
            if shape is None or any(s is None for s in shape):
                raise ValueError("Shape op on dynamic input unsupported")
            env.setdefault("__raw__", {})[n.outputs[0]] = np.asarray(
                shape, np.int64)
            env[n.outputs[0]] = sd.constant(
                np.asarray(shape, np.int64), name=safe)
        elif op in ("Cast", "CastLike"):
            to = {1: "float32", 6: "int32", 7: "int64", 9: "bool",
                  11: "float64"}.get(a.get("to", 1), "float32")
            rec("cast", env[ins[0]], dtype=to)
        elif op == "Gemm":
            alpha = a.get("alpha", 1.0)
            beta = a.get("beta", 1.0)
            x, w = env[ins[0]], env[ins[1]]
            y = sd._record("matmul", (x, w), {
                "transpose_a": bool(a.get("transA", 0)),
                "transpose_b": bool(a.get("transB", 0))})
            if alpha != 1.0:
                y = y * float(alpha)
            if len(ins) > 2:
                b = env[ins[2]]
                y = y + (b * float(beta) if beta != 1.0 else b)
            y.rename(safe)
            env[n.outputs[0]] = y
        elif op == "MatMul":
            rec("matmul", env[ins[0]], env[ins[1]])
        elif op == "Add":
            rec("add", env[ins[0]], env[ins[1]])
        elif op == "Sub":
            rec("subtract", env[ins[0]], env[ins[1]])
        elif op == "Mul":
            rec("multiply", env[ins[0]], env[ins[1]])
        elif op == "Div":
            rec("divide", env[ins[0]], env[ins[1]])
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Selu", "Elu",
                    "Softsign"):
            rec(op.lower(), env[ins[0]])
        elif op == "LeakyRelu":
            rec("lrelu", env[ins[0]], alpha=a.get("alpha", 0.01))
        elif op == "Softmax":
            rec("softmax", env[ins[0]], axis=a.get("axis", -1))
        elif op in ("Exp", "Log", "Sqrt", "Neg", "Abs", "Floor", "Ceil",
                    "Sin", "Cos", "Erf", "Sign", "Round"):
            legacy = {"Abs": "abs", "Ceil": "ceil", "Round": "rint"}
            rec("legacy." + legacy.get(op, op.lower()), env[ins[0]])
        elif op == "Identity":
            env[n.outputs[0]] = env[ins[0]]
        elif op == "Flatten":
            axis = a.get("axis", 1)
            if axis != 1:
                raise ValueError("Flatten axis != 1 unsupported")
            x = env[ins[0]]
            rec("reshape", x, shape=(-1, int(np.prod(x.shape[1:]))
                                     if x.shape else -1))
        elif op == "Reshape":
            shape = tuple(int(s) for s in const_of(ins[1]))
            rec("reshape", env[ins[0]], shape=shape)
        elif op == "Transpose":
            rec("permute", env[ins[0]], axes=tuple(a.get("perm", [])))
        elif op == "Concat":
            rec("concat", *[env[i] for i in ins], axis=a.get("axis", 0))
        elif op == "Conv":
            # ONNX NCHW -> framework NHWC
            strides = tuple(a.get("strides", [1, 1]))
            pads = a.get("pads", [0, 0, 0, 0])
            dil = tuple(a.get("dilations", [1, 1]))
            x = env[ins[0]]
            x_nhwc = sd._record("permute", (x,), {"axes": (0, 2, 3, 1)})
            w = const_of(ins[1])  # [O, I, kH, kW] -> [kH, kW, I, O]
            w_hwio = sd.constant(np.transpose(w, (2, 3, 1, 0)))
            padding = "valid" if not any(pads) else \
                ((pads[0], pads[2]), (pads[1], pads[3]))
            y = sd._record("conv2d", (x_nhwc, w_hwio), {
                "stride": strides, "padding": padding, "dilation": dil})
            if len(ins) > 2:
                y = y + env[ins[2]]
            y = sd._record("permute", (y,), {"axes": (0, 3, 1, 2)})
            y.rename(safe)
            env[n.outputs[0]] = y
        elif op in ("MaxPool", "AveragePool"):
            kernel = tuple(a.get("kernel_shape", [2, 2]))
            strides = tuple(a.get("strides", kernel))
            x_nhwc = sd._record("permute", (env[ins[0]],),
                                {"axes": (0, 2, 3, 1)})
            cat = "maxpool2d" if op == "MaxPool" else "avgpool2d"
            y = sd._record(cat, (x_nhwc,), {"kernel": kernel,
                                            "stride": strides,
                                            "padding": "valid"})
            y = sd._record("permute", (y,), {"axes": (0, 3, 1, 2)})
            y.rename(safe)
            env[n.outputs[0]] = y
        elif op == "GlobalAveragePool":
            rec("reduce_mean", env[ins[0]], axes=(2, 3), keep_dims=True)
        elif op == "BatchNormalization":
            # inference form over NCHW channel axis 1
            x = env[ins[0]]
            g, b = const_of(ins[1]), const_of(ins[2])
            mean, var = const_of(ins[3]), const_of(ins[4])
            eps = a.get("epsilon", 1e-5)
            shape = (1, -1) + (1,) * (len(x.shape) - 2 if x.shape else 0)
            scale = sd.constant((g / np.sqrt(var + eps)).reshape(shape))
            shift = sd.constant((b - mean * g
                                 / np.sqrt(var + eps)).reshape(shape))
            y = x * scale + shift
            y.rename(safe)
            env[n.outputs[0]] = y
        elif op in ("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin"):
            # opset 18 moved `axes` from attribute to a (constant) input
            if len(ins) > 1 and ins[1]:
                axes = tuple(int(i) for i in const_of(ins[1]).ravel())
            else:
                axes = tuple(a.get("axes", [])) or None
            cat = {"ReduceMean": "reduce_mean", "ReduceSum": "reduce_sum",
                   "ReduceMax": "reduce_max", "ReduceMin": "reduce_min"}
            rec(cat[op], env[ins[0]], axes=axes,
                keep_dims=bool(a.get("keepdims", 1)))
        elif op == "Clip":
            # opset 11+ carries min/max as optional (constant) inputs
            lo = float(const_of(ins[1]).ravel()[0]) \
                if len(ins) > 1 and ins[1] else a.get("min", -np.inf)
            hi = float(const_of(ins[2]).ravel()[0]) \
                if len(ins) > 2 and ins[2] else a.get("max", np.inf)
            rec("clipbyvalue", env[ins[0]], lo, hi)
        elif op == "Unsqueeze":
            if len(ins) > 1 and ins[1]:
                axes = [int(i) for i in const_of(ins[1]).ravel()]
            else:
                axes = list(a.get("axes", []))
            x = env[ins[0]]
            shape = list(x.shape)
            for ax in sorted(axes):
                shape.insert(ax if ax >= 0 else ax + len(shape) + 1, 1)
            rec("reshape", x, shape=tuple(int(s) for s in shape))
        elif op == "Squeeze":
            if len(ins) > 1 and ins[1]:
                axes = [int(i) for i in const_of(ins[1]).ravel()]
            else:
                axes = list(a.get("axes", []))
            x = env[ins[0]]
            shape = [s for i, s in enumerate(x.shape)
                     if not (i in axes or i - len(x.shape) in axes)]
            rec("reshape", x, shape=tuple(int(s) for s in shape))
        elif op == "Gather":
            rec("gather", env[ins[0]], env[ins[1]],
                axis=a.get("axis", 0))
        elif op == "Pow":
            rec("pow", env[ins[0]], env[ins[1]])
        elif op in ("Min", "Max"):
            cat = "minimum" if op == "Min" else "maximum"
            if len(ins) == 1:  # variadic with one input = identity; do
                env[n.outputs[0]] = env[ins[0]]  # NOT rename upstream
            else:
                y = env[ins[0]]
                for i in ins[1:]:
                    y = sd._record(cat, (y, env[i]), {})
                y.rename(safe)
                env[n.outputs[0]] = y
        elif op == "Where":
            rec("select", env[ins[0]], env[ins[1]], env[ins[2]])
        elif op in ("Equal", "Greater", "Less", "GreaterOrEqual",
                    "LessOrEqual"):
            cat = {"Equal": "equals", "Greater": "greater", "Less": "less",
                   "GreaterOrEqual": "greater_equal",
                   "LessOrEqual": "less_equal"}[op]
            rec(cat, env[ins[0]], env[ins[1]])
        elif op == "Dropout":
            env[n.outputs[0]] = env[ins[0]]  # inference graph: identity
        elif op == "Gelu":
            approx = a.get("approximate", "none")
            approx = approx.decode() if isinstance(approx, bytes) \
                else str(approx)
            if approx == "tanh":
                rec("legacy.gelu", env[ins[0]])  # jax.nn.gelu tanh form
            else:
                # exact erf form (torch's default)
                x = env[ins[0]]
                e = sd._record("legacy.erf", (x * 0.7071067811865476,), {})
                y = x * 0.5 * (e + 1.0)
                y.rename(safe)
                env[n.outputs[0]] = y
        elif op == "PRelu":
            rec("prelu", env[ins[0]], env[ins[1]])
        elif op == "Pad":
            # opset 11+: pads arrive as a constant input in
            # [begin_0..begin_k, end_0..end_k] layout; mode is an attr
            if len(ins) > 1 and ins[1]:
                pads = const_of(ins[1], int_exact=True).ravel()
            else:
                pads = np.asarray(a.get("pads", []), np.int64)
            k = len(pads) // 2
            paddings = tuple((int(pads[i]), int(pads[i + k]))
                             for i in range(k))
            mode = a.get("mode", "constant")
            mode = mode.decode() if isinstance(mode, bytes) else str(mode)
            if mode not in ("constant", "reflect", "symmetric"):
                raise ValueError(f"Pad mode {mode!r} unsupported")
            if len(ins) > 3 and ins[3]:
                raise ValueError("Pad with an `axes` input (opset 18 "
                                 "subset-axes form) unsupported")
            cval = 0.0
            if len(ins) > 2 and ins[2]:
                cval = float(const_of(ins[2]).ravel()[0])
            rec("pad", env[ins[0]], paddings=paddings, mode=mode,
                constant_values=cval)
        elif op == "Slice":
            # opset 10+: starts/ends/axes/steps as constant inputs
            starts = [int(v) for v in const_of(ins[1], int_exact=True)
                      .ravel()]
            ends = [int(v) for v in const_of(ins[2], int_exact=True)
                    .ravel()]
            x = env[ins[0]]
            if x.shape is None:
                raise ValueError("Slice on an input of unknown rank "
                                 "unsupported")
            rank = len(x.shape)
            axes = [int(v) for v in const_of(ins[3], int_exact=True)
                    .ravel()] \
                if len(ins) > 3 and ins[3] else list(range(len(starts)))
            steps = [int(v) for v in const_of(ins[4], int_exact=True)
                     .ravel()] \
                if len(ins) > 4 and ins[4] else [1] * len(starts)
            spec = [["s", None, None, 1] for _ in range(rank)]
            for ax, s, e, st in zip(axes, starts, ends, steps):
                ax = ax + rank if ax < 0 else ax
                # ONNX clamps out-of-range bounds to the dim ends;
                # INT64_MIN/MAX-magnitude bounds are open-slice sentinels
                # (INT64_MIN with step -1 = "reverse through index 0")
                begin = None if (s == 0 and st > 0) else int(s)
                dim = x.shape[ax] if x.shape else None
                end = None if (abs(e) >= (1 << 31) - 1 or
                               (st > 0 and dim and e >= dim)) else int(e)
                spec[ax] = ["s", begin, end, int(st)]
            rec("numpy_slice", x, spec=tuple(tuple(s) for s in spec))
        elif op == "Split":
            axis = a.get("axis", 0)
            if len(ins) > 1 and ins[1]:
                sizes = tuple(int(v) for v in const_of(ins[1]).ravel())
                v = sd._record("split_v", (env[ins[0]], sizes),
                               {"axis": axis})
            elif "split" in a:
                sizes = tuple(int(s) for s in a["split"])
                v = sd._record("split_v", (env[ins[0]], sizes),
                               {"axis": axis})
            else:
                num = a.get("num_outputs", len(n.outputs))
                v = sd._record("split", (env[ins[0]], int(num)),
                               {"axis": axis})
            for i, out_name in enumerate(n.outputs):
                env[out_name] = v[i]
        elif op == "Expand":
            # ONNX Expand is BIDIRECTIONAL broadcast: a target entry of 1
            # keeps the input dim, and the input may have more dims than
            # the target — resolve the final shape statically
            shape = [int(s) for s in const_of(ins[1]).ravel()]
            x = env[ins[0]]
            if x.shape is None or any(s is None for s in x.shape):
                raise ValueError("Expand on dynamic input unsupported")
            xs = list(x.shape)
            rank = max(len(xs), len(shape))
            xs = [1] * (rank - len(xs)) + xs
            shape = [1] * (rank - len(shape)) + shape
            out = []
            for xd, td in zip(xs, shape):
                if xd != td and 1 not in (xd, td):
                    raise ValueError(f"Expand: cannot broadcast {xs} "
                                     f"to {shape}")
                out.append(max(xd, td))
            rec("tile_to_shape", x, shape=tuple(out))
        elif op == "ConstantOfShape":
            shape = tuple(int(s) for s in const_of(ins[0]).ravel())
            val = a.get("value", np.zeros(1, np.float32))
            arr = np.full(shape, np.asarray(val).ravel()[0])
            # raw side-table too: integer fills (e.g. int64 index seeds)
            # must keep exact dtype for downstream const_of readers
            env.setdefault("__raw__", {})[n.outputs[0]] = arr
            env[n.outputs[0]] = sd.constant(arr, name=safe)
        elif op == "ConvTranspose":
            strides = tuple(a.get("strides", [1, 1]))
            pads = a.get("pads", [0, 0, 0, 0])
            unsupported = []
            if a.get("group", 1) != 1:
                unsupported.append("group != 1")
            if any(d != 1 for d in a.get("dilations", [1, 1])):
                unsupported.append("dilations != 1")
            if any(a.get("output_padding", [0, 0])):
                unsupported.append("output_padding != 0")
            ap = a.get("auto_pad", "NOTSET")
            ap = ap.decode() if isinstance(ap, bytes) else str(ap)
            if ap not in ("NOTSET", ""):
                unsupported.append(f"auto_pad={ap}")
            if unsupported:
                raise ValueError("ConvTranspose with "
                                 f"{', '.join(unsupported)} unsupported")
            x_nhwc = sd._record("permute", (env[ins[0]],),
                                {"axes": (0, 2, 3, 1)})
            # ONNX [I, O, kH, kW] -> [kH, kW, I, O] with the spatial taps
            # flipped: torch's ConvTranspose is the conv GRADIENT, while
            # deconv2d lowers to lax.conv_transpose without kernel
            # mirroring (same conversion as the Keras Conv2DTranspose
            # mapper — modelimport/keras.py)
            w = const_of(ins[1])
            w_hwio = sd.constant(
                np.transpose(w, (2, 3, 0, 1))[::-1, ::-1])
            if any(pads):
                padding = ((pads[0], pads[2]), (pads[1], pads[3]))
                raise ValueError("ConvTranspose with explicit pads "
                                 f"{padding} unsupported (use pads=0)")
            y = sd._record("deconv2d", (x_nhwc, w_hwio),
                           {"stride": strides, "padding": "valid"})
            if len(ins) > 2 and ins[2]:
                y = y + env[ins[2]]
            y = sd._record("permute", (y,), {"axes": (0, 3, 1, 2)})
            y.rename(safe)
            env[n.outputs[0]] = y
        elif op == "LayerNormalization":
            axis = a.get("axis", -1)
            eps = a.get("epsilon", 1e-5)
            x, g = env[ins[0]], env[ins[1]]
            if x.shape is None:
                raise ValueError("LayerNormalization on an input of "
                                 "unknown rank unsupported")
            rank = len(x.shape)
            # ONNX normalizes over ALL trailing axes [axis, rank)
            axes = tuple(range(axis + rank if axis < 0 else axis, rank))
            mean = sd._record("reduce_mean", (x,), {"axes": axes,
                                                    "keep_dims": True})
            d = x - mean
            var = sd._record("reduce_mean", (d * d,),
                             {"axes": axes, "keep_dims": True})
            yn = d / ((var + float(eps)) ** 0.5)
            y = yn * g
            if len(ins) > 2 and ins[2]:
                y = y + env[ins[2]]
            y.rename(safe)
            env[n.outputs[0]] = y
        else:
            raise ValueError(f"unsupported ONNX op {op!r} (node "
                             f"{n.name!r}); extend OnnxGraphMapper")
        # raw-domain constant-chain folding: keep int64 exactness through
        # the computed-constant chains torch's exporter emits
        # (Constant -> Cast/Add/Reshape/Concat/... -> Slice bounds) so
        # const_of(int_exact=True) never falls back to the lossy jnp fold
        OnnxGraphMapper._fold_raw(n, a, env)

    _RAW_FOLD_OPS = ("Cast", "Add", "Sub", "Mul", "Div", "Neg", "Reshape",
                     "Concat", "Squeeze", "Unsqueeze", "Gather", "Range",
                     "Slice", "Transpose", "Min", "Max", "Abs", "Mod",
                     "Where", "Equal", "Greater", "Less")

    @staticmethod
    def _fold_raw(n: "_OnnxNode", a: Dict[str, Any], env: Dict[str, Any]):
        """If every input of a foldable node is a known raw numpy
        constant, evaluate the node in numpy (int64-exact) and record the
        result in the ``__raw__`` side-table. jnp-domain truncation never
        touches these values, so INT64 open-slice sentinels survive
        Cast/Add/... chains (the advisor's round-4 finding)."""
        op = n.op
        raw = env.setdefault("__raw__", {})
        if op not in OnnxGraphMapper._RAW_FOLD_OPS or n.outputs[0] in raw:
            return
        # keep optional-input POSITIONS: ONNX omits an optional input as
        # an empty name (e.g. Slice [data, starts, ends, "", steps]) —
        # compacting would fold steps as axes
        if not n.inputs or not all((not i) or i in raw for i in n.inputs):
            return
        vals = [np.asarray(raw[i]) if i else None for i in n.inputs]
        while vals and vals[-1] is None:
            vals.pop()
        if not vals or vals[0] is None:
            return
        try:
            if op == "Cast":
                np_dtype = {1: np.float32, 6: np.int32, 7: np.int64,
                            9: np.bool_, 11: np.float64}.get(
                                a.get("to", 1))
                if np_dtype is None:
                    return  # unmapped dtype code: decline, don't guess
                out = vals[0].astype(np_dtype)
            elif op == "Add":
                out = vals[0] + vals[1]
            elif op == "Sub":
                out = vals[0] - vals[1]
            elif op == "Mul":
                out = vals[0] * vals[1]
            elif op == "Neg":
                out = -vals[0]
            elif op == "Div":
                if np.issubdtype(vals[0].dtype, np.integer):
                    # ONNX integer Div truncates toward zero (C
                    # semantics); numpy // floors, so go via magnitudes
                    s = np.sign(vals[0]) * np.sign(vals[1])
                    out = (s * (np.abs(vals[0]) // np.abs(vals[1]))
                           ).astype(vals[0].dtype)
                else:
                    out = vals[0] / vals[1]
            elif op == "Reshape":
                target = [int(t) for t in vals[1].ravel()]
                src = vals[0].shape
                target = [src[i] if t == 0 else t
                          for i, t in enumerate(target)]
                out = vals[0].reshape(target)
            elif op == "Concat":
                out = np.concatenate(vals, axis=int(a.get("axis", 0)))
            elif op in ("Squeeze", "Unsqueeze"):
                if len(vals) > 1 and vals[1] is not None:
                    axes = [int(v) for v in vals[1].ravel()]
                else:
                    axes = [int(v) for v in a.get("axes", [])]
                if op == "Squeeze":
                    out = (np.squeeze(vals[0], axis=tuple(axes))
                           if axes else np.squeeze(vals[0]))
                else:
                    out = vals[0]
                    for ax in sorted(axes):
                        out = np.expand_dims(out, ax)
            elif op == "Gather":
                out = np.take(vals[0], vals[1].astype(np.int64),
                              axis=int(a.get("axis", 0)))
            elif op == "Range":
                out = np.arange(vals[0].ravel()[0], vals[1].ravel()[0],
                                vals[2].ravel()[0])
            elif op == "Slice":
                data = vals[0]
                starts, ends = vals[1].ravel(), vals[2].ravel()
                axes = (vals[3].ravel()
                        if len(vals) > 3 and vals[3] is not None
                        else np.arange(len(starts)))
                steps = (vals[4].ravel()
                         if len(vals) > 4 and vals[4] is not None
                         else np.ones(len(starts), np.int64))
                sl = [slice(None)] * data.ndim
                for ax, s, e, st in zip(axes, starts, ends, steps):
                    # python slicing clamps out-of-range bounds exactly
                    # like ONNX (incl. the INT64 open-slice sentinels)
                    sl[int(ax)] = slice(int(s), int(e), int(st))
                out = data[tuple(sl)]
            elif op == "Transpose":
                perm = a.get("perm")
                out = np.transpose(vals[0],
                                   [int(p) for p in perm] if perm
                                   else None)
            elif op == "Min":
                out = vals[0]
                for v in vals[1:]:
                    out = np.minimum(out, v)
            elif op == "Max":
                out = vals[0]
                for v in vals[1:]:
                    out = np.maximum(out, v)
            elif op == "Abs":
                out = np.abs(vals[0])
            elif op == "Mod":
                # fmod=1 -> C fmod (truncated); default integer Mod is
                # python-style (floored), matching numpy
                out = (np.fmod(vals[0], vals[1]) if a.get("fmod")
                       else np.mod(vals[0], vals[1]))
            elif op == "Where":
                out = np.where(vals[0], vals[1], vals[2])
            elif op == "Equal":
                out = vals[0] == vals[1]
            elif op == "Greater":
                out = vals[0] > vals[1]
            elif op == "Less":
                out = vals[0] < vals[1]
            else:
                return
        except Exception:
            return  # fold is best-effort; the jnp graph stays correct
        raw[n.outputs[0]] = np.asarray(out)
