"""Serving metrics: queue depth, batch-size histogram, latency
percentiles, compile-cache hits/misses.

Built on the profiler's section machinery (`OpProfiler.record` names
``serving.*`` sections) plus the :class:`Reservoir` /
:class:`CountHistogram` aggregates it exposes; `GET /stats` on the
server returns :meth:`ServingMetrics.snapshot` per model.
"""
from __future__ import annotations

import threading
from typing import Dict, List

from ..profiler import CountHistogram, OpProfiler, Reservoir


class ServingMetrics:
    """Always-on counters for one served model (the reference's
    PerformanceListener role, serving-side). Scalar counters are
    mutated from many HTTP handler threads — use :meth:`inc`, not
    ``+=`` (attribute += is load/add/store and loses updates under
    preemption)."""

    def __init__(self, latency_window: int = 8192):
        self._lock = threading.Lock()
        self.requests = 0          # accepted into the queue/engine
        self.responses = 0         # successful results returned
        self.client_errors = 0     # 4xx-class failures
        self.server_errors = 0     # 5xx-class failures
        self.shed = 0              # rejected, queue full (503)
        self.timeouts = 0          # request deadline exceeded (504)
        self.batches = 0           # device calls issued
        self.batch_hist = CountHistogram()   # rows per device call
        self.bucket_hist = CountHistogram()  # padded bucket per call
        self.latency_ms = Reservoir(latency_window)    # request e2e
        self.device_ms = Reservoir(latency_window)     # device call
        self.queue_depth = 0       # gauge, updated by the batcher
        self.queue_max = 0
        # engine compile cache
        self.compiles = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self.warmed_buckets: List[int] = []

    def inc(self, field: str, n: int = 1):
        """Thread-safe counter increment."""
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def mean_batch(self) -> float:
        """Mean number of real rows per device call — the coalescing
        factor (1.0 means the batcher never merged anything)."""
        return self.batch_hist.mean()

    def snapshot(self) -> Dict:
        return {
            "requests": self.requests,
            "responses": self.responses,
            "client_errors": self.client_errors,
            "server_errors": self.server_errors,
            "shed": self.shed,
            "timeouts": self.timeouts,
            "queue_depth": self.queue_depth,
            "queue_max": self.queue_max,
            "batches": self.batches,
            "mean_batch": round(self.mean_batch(), 3),
            "batch_hist": self.batch_hist.snapshot(),
            "bucket_hist": self.bucket_hist.snapshot(),
            "latency_ms": {k: round(v, 3) for k, v in
                           self.latency_ms.snapshot().items()},
            "device_ms": {k: round(v, 3) for k, v in
                          self.device_ms.snapshot().items()},
            "compile_cache": {
                "compiles": self.compiles,
                "hits": self.cache_hits,
                "misses": self.cache_misses,
                "evictions": self.cache_evictions,
                "warmed_buckets": list(self.warmed_buckets),
            },
        }


def profiler_sections() -> Dict:
    """The profiler's own `serving.*` section timings (populated when
    ProfilingMode is OPERATIONS/ALL), merged into `GET /stats`."""
    return {name: stats for name, stats in
            OpProfiler.get_instance().timings().items()
            if name.startswith("serving.")}
