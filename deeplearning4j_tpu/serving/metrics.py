"""Serving metrics: queue depth, batch-size histogram, latency
percentiles, compile-cache hits/misses.

Built on the profiler's section machinery (`OpProfiler.record` names
``serving.*`` sections) plus the :class:`Reservoir` /
:class:`CountHistogram` aggregates it exposes; `GET /stats` on the
server returns :meth:`ServingMetrics.snapshot` per model.
"""
from __future__ import annotations

import threading
from typing import Dict, List

from ..profiler import CountHistogram, OpProfiler, RateMeter, Reservoir


class ServingMetrics:
    """Always-on counters for one served model (the reference's
    PerformanceListener role, serving-side). Scalar counters are
    mutated from many HTTP handler threads — use :meth:`inc`, not
    ``+=`` (attribute += is load/add/store and loses updates under
    preemption)."""

    def __init__(self, latency_window: int = 8192):
        self._lock = threading.Lock()
        self.requests = 0          # accepted into the queue/engine
        self.responses = 0         # successful results returned
        self.client_errors = 0     # 4xx-class failures
        self.server_errors = 0     # 5xx-class failures
        self.shed = 0              # rejected, queue full (503)
        self.shed_batch = 0        # batch-priority work shed first (503)
        self.shed_deadline = 0     # deadline budget blown before the
        #                            device call: rejected at dequeue-
        #                            admission, zero device work spent
        self.timeouts = 0          # request deadline exceeded (504)
        # fault-tolerance counters (serving/faults.py)
        self.retries = 0           # transient step failures retried
        self.recoveries = 0        # state rebuilds (n/a for batcher)
        self.quarantined = 0       # poison requests failed alone
        self.drains = 0            # graceful drains initiated
        self.batches = 0           # device calls issued
        self.batch_hist = CountHistogram()   # rows per device call
        self.bucket_hist = CountHistogram()  # padded bucket per call
        self.latency_ms = Reservoir(latency_window)    # request e2e
        self.device_ms = Reservoir(latency_window)     # device call
        self.queue_depth = 0       # gauge, updated by the batcher
        self.queue_max = 0
        self.inflight = 0          # gauge: rows in the device call NOW
        # engine compile cache
        self.compiles = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self.warmed_buckets: List[int] = []

    def inc(self, field: str, n: int = 1):
        """Thread-safe counter increment."""
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def mean_batch(self) -> float:
        """Mean number of real rows per device call — the coalescing
        factor (1.0 means the batcher never merged anything)."""
        return self.batch_hist.mean()

    def snapshot(self) -> Dict:
        return {
            "requests": self.requests,
            "responses": self.responses,
            "client_errors": self.client_errors,
            "server_errors": self.server_errors,
            "shed": self.shed,
            "shed_batch": self.shed_batch,
            "shed_deadline": self.shed_deadline,
            "timeouts": self.timeouts,
            "faults": {
                "retries": self.retries,
                "recoveries": self.recoveries,
                "quarantined": self.quarantined,
                "drains": self.drains,
            },
            "queue_depth": self.queue_depth,
            "queue_max": self.queue_max,
            "inflight": self.inflight,
            "batches": self.batches,
            "mean_batch": round(self.mean_batch(), 3),
            "batch_hist": self.batch_hist.snapshot(),
            "bucket_hist": self.bucket_hist.snapshot(),
            "latency_ms": {k: round(v, 3) for k, v in
                           self.latency_ms.snapshot().items()},
            "device_ms": {k: round(v, 3) for k, v in
                          self.device_ms.snapshot().items()},
            "compile_cache": {
                "compiles": self.compiles,
                "hits": self.cache_hits,
                "misses": self.cache_misses,
                "evictions": self.cache_evictions,
                "warmed_buckets": list(self.warmed_buckets),
            },
        }


class GenerationMetrics:
    """Always-on counters for one continuous-batching generation
    engine. Same threading discipline as :class:`ServingMetrics`
    (scalar counters via :meth:`inc`, never ``+=``): the HTTP handler
    threads and the scheduler thread both write here."""

    def __init__(self, latency_window: int = 8192,
                 rate_window_s: float = 30.0):
        self._lock = threading.Lock()
        self.requests = 0          # accepted into the queue
        self.responses = 0         # finished generations returned
        self.client_errors = 0     # 4xx-class failures
        self.server_errors = 0     # 5xx-class failures
        self.shed = 0              # rejected, queue full (503)
        self.shed_batch = 0        # batch-priority work shed first (503)
        self.shed_deadline = 0     # deadline budget blown before any
        #                            prefill/decode step: rejected at
        #                            admission, zero device work spent
        self.timeouts = 0          # deadline exceeded (504)
        # fault-tolerance counters (serving/faults.py): transient step
        # retries, recompute-recoveries (every in-flight request
        # re-prefilled from prompt + emitted tokens), poison requests
        # quarantined (non-finite logits -> 500, batchmates unharmed),
        # graceful drains
        self.retries = 0
        self.recoveries = 0
        self.quarantined = 0
        self.drains = 0
        self.prefills = 0          # prefill device calls
        self.decode_steps = 0      # decode device calls (all slots)
        self.tokens = RateMeter(rate_window_s)   # generated tokens
        self.occupancy_hist = CountHistogram()   # active slots per step
        self.prompt_bucket_hist = CountHistogram()  # padded prefill len
        self.ttft_ms = Reservoir(latency_window)    # submit -> 1st token
        self.itl_ms = Reservoir(latency_window)     # inter-token gap
        self.prefill_ms = Reservoir(latency_window)
        self.decode_step_ms = Reservoir(latency_window)
        self.queue_depth = 0       # gauge, updated by the scheduler
        self.queue_max = 0
        self.active_slots = 0      # gauge
        self.num_slots = 0
        self.cache_bytes = 0
        # paged-cache gauges/counters (serving/paging.py; all zero
        # when the engine runs the dense slot backend)
        self.cache_backend = "slots"
        self.block_size = 0
        self.blocks_total = 0          # allocatable blocks (excl. null)
        self.blocks_free = 0           # gauge
        self.blocks_peak_used = 0      # high-water mark
        self.prefill_chunks = 0        # chunk device calls
        self.chunked_prefills = 0      # prompts that spanned >1 chunk
        self.kv_tokens_live = 0        # written positions, live seqs
        self.kv_tokens_allocated = 0   # blocks_used * block_size
        # compile cache: decode + one prefill executable per bucket
        self.compiles = 0
        self.warmed_buckets: List[int] = []

    def inc(self, field: str, n: int = 1):
        """Thread-safe counter increment."""
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> Dict:
        occ = self.occupancy_hist
        steps = occ.total()
        paged = None
        if self.cache_backend == "paged":
            used = self.blocks_total - self.blocks_free
            alloc = self.kv_tokens_allocated
            paged = {
                "block_size": self.block_size,
                "blocks_total": self.blocks_total,
                "blocks_free": self.blocks_free,
                "blocks_used": used,
                "blocks_peak_used": self.blocks_peak_used,
                "utilization": round(used / self.blocks_total, 4)
                if self.blocks_total else 0.0,
                # internal fragmentation: the share of ALLOCATED token
                # capacity not (yet) holding live K/V — bounded by
                # block_size-1 tokens per sequence, vs up to
                # max_seq_len-1 per slot on the dense backend
                "fragmentation": round(
                    1.0 - self.kv_tokens_live / alloc, 4)
                if alloc else 0.0,
                "kv_tokens_live": self.kv_tokens_live,
                "kv_tokens_allocated": alloc,
                "prefill_chunks": self.prefill_chunks,
                "chunked_prefills": self.chunked_prefills,
            }
        return {
            "cache_backend": self.cache_backend,
            "paged": paged,
            "requests": self.requests,
            "responses": self.responses,
            "client_errors": self.client_errors,
            "server_errors": self.server_errors,
            "shed": self.shed,
            "shed_batch": self.shed_batch,
            "shed_deadline": self.shed_deadline,
            "timeouts": self.timeouts,
            "faults": {
                "retries": self.retries,
                "recoveries": self.recoveries,
                "quarantined": self.quarantined,
                "drains": self.drains,
            },
            "queue_depth": self.queue_depth,
            "queue_max": self.queue_max,
            "prefills": self.prefills,
            "decode_steps": self.decode_steps,
            "tokens_generated": self.tokens.total(),
            "tokens_per_sec": round(self.tokens.rate(), 3),
            "slots": {
                "num_slots": self.num_slots,
                "active": self.active_slots,
                "mean_occupancy": round(occ.mean(), 3),
                "utilization": round(
                    occ.mean() / self.num_slots, 4) if (
                        self.num_slots and steps) else 0.0,
                "occupancy_hist": occ.snapshot(),
            },
            "prompt_bucket_hist": self.prompt_bucket_hist.snapshot(),
            "ttft_ms": {k: round(v, 3) for k, v in
                        self.ttft_ms.snapshot().items()},
            "itl_ms": {k: round(v, 3) for k, v in
                       self.itl_ms.snapshot().items()},
            "prefill_ms": {k: round(v, 3) for k, v in
                           self.prefill_ms.snapshot().items()},
            "decode_step_ms": {k: round(v, 3) for k, v in
                               self.decode_step_ms.snapshot().items()},
            "kv_cache_bytes": self.cache_bytes,
            "compile_cache": {
                "compiles": self.compiles,
                "warmed_buckets": list(self.warmed_buckets),
            },
        }


def profiler_sections() -> Dict:
    """The profiler's own `serving.*` section timings (populated when
    ProfilingMode is OPERATIONS/ALL), merged into `GET /stats`."""
    return {name: stats for name, stats in
            OpProfiler.get_instance().timings().items()
            if name.startswith("serving.")}
