"""Serving metrics: queue depth, batch-size histogram, latency
percentiles, compile-cache hits/misses.

Built on the profiler's section machinery (`OpProfiler.record` names
``serving.*`` sections) plus the :class:`Reservoir` /
:class:`CountHistogram` aggregates it exposes; `GET /stats` on the
server returns :meth:`ServingMetrics.snapshot` per model.
"""
from __future__ import annotations

import re
import threading
from typing import Dict, List

from ..profiler import (RESERVOIR_SNAPSHOT_KEYS, CountHistogram,
                        OpProfiler, RateMeter, Reservoir)


class ServingMetrics:
    """Always-on counters for one served model (the reference's
    PerformanceListener role, serving-side). Scalar counters are
    mutated from many HTTP handler threads — use :meth:`inc`, not
    ``+=`` (attribute += is load/add/store and loses updates under
    preemption)."""

    def __init__(self, latency_window: int = 8192):
        self._lock = threading.Lock()
        self.requests = 0          # accepted into the queue/engine
        self.responses = 0         # successful results returned
        self.client_errors = 0     # 4xx-class failures
        self.server_errors = 0     # 5xx-class failures
        self.shed = 0              # rejected, queue full (503)
        self.shed_batch = 0        # batch-priority work shed first (503)
        self.shed_deadline = 0     # deadline budget blown before the
        #                            device call: rejected at dequeue-
        #                            admission, zero device work spent
        self.timeouts = 0          # request deadline exceeded (504)
        # fault-tolerance counters (serving/faults.py)
        self.retries = 0           # transient step failures retried
        self.recoveries = 0        # state rebuilds (n/a for batcher)
        self.quarantined = 0       # poison requests failed alone
        self.drains = 0            # graceful drains initiated
        self.batches = 0           # device calls issued
        self.batch_hist = CountHistogram()   # rows per device call
        self.bucket_hist = CountHistogram()  # padded bucket per call
        self.latency_ms = Reservoir(latency_window)    # request e2e
        self.device_ms = Reservoir(latency_window)     # device call
        self.queue_depth = 0       # gauge, updated by the batcher
        self.queue_max = 0
        self.inflight = 0          # gauge: rows in the device call NOW
        # engine compile cache
        self.compiles = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self.warmed_buckets: List[int] = []

    def inc(self, field: str, n: int = 1):
        """Thread-safe counter increment."""
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def mean_batch(self) -> float:
        """Mean number of real rows per device call — the coalescing
        factor (1.0 means the batcher never merged anything)."""
        return self.batch_hist.mean()

    def snapshot(self) -> Dict:
        return {
            "requests": self.requests,
            "responses": self.responses,
            "client_errors": self.client_errors,
            "server_errors": self.server_errors,
            "shed": self.shed,
            "shed_batch": self.shed_batch,
            "shed_deadline": self.shed_deadline,
            "timeouts": self.timeouts,
            "faults": {
                "retries": self.retries,
                "recoveries": self.recoveries,
                "quarantined": self.quarantined,
                "drains": self.drains,
            },
            "queue_depth": self.queue_depth,
            "queue_max": self.queue_max,
            "inflight": self.inflight,
            "batches": self.batches,
            "mean_batch": round(self.mean_batch(), 3),
            "batch_hist": self.batch_hist.snapshot(),
            "bucket_hist": self.bucket_hist.snapshot(),
            "latency_ms": {k: round(v, 3) for k, v in
                           self.latency_ms.snapshot().items()},
            "device_ms": {k: round(v, 3) for k, v in
                          self.device_ms.snapshot().items()},
            "compile_cache": {
                "compiles": self.compiles,
                "hits": self.cache_hits,
                "misses": self.cache_misses,
                "evictions": self.cache_evictions,
                "warmed_buckets": list(self.warmed_buckets),
            },
        }


class GenerationMetrics:
    """Always-on counters for one continuous-batching generation
    engine. Same threading discipline as :class:`ServingMetrics`
    (scalar counters via :meth:`inc`, never ``+=``): the HTTP handler
    threads and the scheduler thread both write here."""

    def __init__(self, latency_window: int = 8192,
                 rate_window_s: float = 30.0):
        self._lock = threading.Lock()
        self.requests = 0          # accepted into the queue
        self.responses = 0         # finished generations returned
        self.client_errors = 0     # 4xx-class failures
        self.server_errors = 0     # 5xx-class failures
        self.shed = 0              # rejected, queue full (503)
        self.shed_batch = 0        # batch-priority work shed first (503)
        self.shed_deadline = 0     # deadline budget blown before any
        #                            prefill/decode step: rejected at
        #                            admission, zero device work spent
        self.timeouts = 0          # deadline exceeded (504)
        # fault-tolerance counters (serving/faults.py): transient step
        # retries, recompute-recoveries (every in-flight request
        # re-prefilled from prompt + emitted tokens), poison requests
        # quarantined (non-finite logits -> 500, batchmates unharmed),
        # graceful drains
        self.retries = 0
        self.recoveries = 0
        self.quarantined = 0
        self.drains = 0
        self.prefills = 0          # prefill device calls
        self.decode_steps = 0      # decode device calls (all slots)
        self.tokens = RateMeter(rate_window_s)   # generated tokens
        self.occupancy_hist = CountHistogram()   # active slots per step
        self.prompt_bucket_hist = CountHistogram()  # padded prefill len
        self.ttft_ms = Reservoir(latency_window)    # submit -> 1st token
        self.itl_ms = Reservoir(latency_window)     # inter-token gap
        self.prefill_ms = Reservoir(latency_window)
        self.decode_step_ms = Reservoir(latency_window)
        # pipelined decode (ISSUE 14): how long the scheduler actually
        # BLOCKED at the step-t sync after dispatching step t+1 — near
        # zero when host bookkeeping fully overlaps device compute,
        # approaching decode_step_ms when the device is the bottleneck
        self.decode_sync_wait_ms = Reservoir(latency_window)
        self.queue_depth = 0       # gauge, updated by the scheduler
        self.queue_max = 0
        self.active_slots = 0      # gauge
        self.num_slots = 0
        self.cache_bytes = 0
        # quantized KV pool (ISSUE 15; kernels/kv_quant.py). kv_dtype
        # is a STRING (exposition walker skips strings — identity in
        # labels), so kv_bits carries the precision into /metrics as a
        # numeric gauge (32 / 16 / 8)
        self.kv_dtype = "f32"
        self.kv_bits = 32
        self.kv_bytes_per_token = 0    # K+V bytes per position, all
        #                                layers, sidecar included
        self.quant_blocks_quantized = 0  # gauge: allocated int8 blocks
        self.quant_scale_bytes = 0       # f32 sidecar bytes (0 unless
        #                                  int8)
        # paged-cache gauges/counters (serving/paging.py; all zero
        # when the engine runs the dense slot backend)
        self.cache_backend = "slots"
        self.block_size = 0
        self.blocks_total = 0          # allocatable blocks (excl. null)
        self.blocks_free = 0           # gauge
        self.blocks_peak_used = 0      # high-water mark
        self.prefill_chunks = 0        # chunk device calls
        self.chunked_prefills = 0      # prompts that spanned >1 chunk
        self.kv_tokens_live = 0        # written positions, live seqs
        self.kv_tokens_allocated = 0   # blocks_used * block_size
        # prefix sharing + persistent sessions (paged backend only;
        # docs/generation.md "Prefix sharing")
        self.prefix_sharing = False    # config flag
        self.prefix_hits = 0           # admissions that matched a prefix
        self.session_hits = 0          # ...matched via the session store
        self.session_misses = 0        # session_id sent, nothing pinned
        self.prefix_tokens_matched = 0  # prompt tokens served from cache
        self.prefill_tokens = 0        # prompt tokens actually computed
        self.cow_copies = 0            # copy-on-write block duplications
        self.prefix_evictions = 0      # index entries evicted
        self.session_evictions = 0     # sessions evicted (LRU/pressure)
        self.shared_blocks = 0         # gauge: blocks with refcount > 1
        self.prefix_blocks = 0         # gauge: blocks the index pins
        self.sessions_live = 0         # gauge
        # hierarchical KV tier (PR 16; serving/offload.py): demote-on-
        # evict to host RAM (+ optional disk ring), restore-on-resume.
        # All zero unless offload_host_bytes > 0
        self.offload_enabled = False   # config flag
        self.offload_demotions = 0     # device->host block-run copies
        self.offload_restores = 0      # host->device restores (each one
        #                                is a re-prefill avoided)
        self.offload_prefetch_hits = 0  # restores served from staged
        #                                 prefetch (overlapped IO)
        self.offload_demote_failures = 0   # torn demotions -> discard
        self.offload_restore_failures = 0  # torn restores -> re-prefill
        self.offload_spills = 0        # gauge: RAM -> disk-ring spills
        self.offload_drops = 0         # gauge: runs lost off the bottom
        self.offload_host_runs = 0     # gauge: runs in host RAM
        self.offload_host_blocks = 0   # gauge: blocks in host RAM
        self.offload_host_bytes = 0    # gauge
        self.offload_disk_blocks = 0   # gauge: blocks in the disk ring
        self.offload_disk_bytes = 0    # gauge
        self.offload_restore_ms = Reservoir(latency_window)  # host->
        #                              device restore wall time
        self.offload_demote_ms = Reservoir(latency_window)
        # speculative decoding (serving/speculative.py; both backends;
        # all zero with speculation_k=0)
        self.speculation_k = 0            # config knob (0 = off)
        self.spec_draft_tokens_proposed = 0  # k per verify round
        self.spec_draft_tokens_accepted = 0  # target-matched prefix
        self.spec_verify_batches = 0      # verify device calls
        self.spec_rollbacks = 0           # rounds with a rejected tail
        self.spec_draft_fallbacks = 0     # draft failures -> plain
        #                                   decode (lane never failed)
        # compile cache: decode + one prefill executable per bucket
        self.compiles = 0
        self.warmed_buckets: List[int] = []

    def inc(self, field: str, n: int = 1):
        """Thread-safe counter increment."""
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> Dict:
        occ = self.occupancy_hist
        steps = occ.total()
        paged = None
        if self.cache_backend == "paged":
            used = self.blocks_total - self.blocks_free
            alloc = self.kv_tokens_allocated
            paged = {
                "block_size": self.block_size,
                "blocks_total": self.blocks_total,
                "blocks_free": self.blocks_free,
                "blocks_used": used,
                "blocks_peak_used": self.blocks_peak_used,
                "utilization": round(used / self.blocks_total, 4)
                if self.blocks_total else 0.0,
                # internal fragmentation: the share of ALLOCATED token
                # capacity not (yet) holding live K/V — bounded by
                # block_size-1 tokens per sequence, vs up to
                # max_seq_len-1 per slot on the dense backend
                "fragmentation": round(
                    1.0 - self.kv_tokens_live / alloc, 4)
                if alloc else 0.0,
                "kv_tokens_live": self.kv_tokens_live,
                "kv_tokens_allocated": alloc,
                "prefill_chunks": self.prefill_chunks,
                "chunked_prefills": self.chunked_prefills,
                "prefix_cache": {
                    "enabled": self.prefix_sharing,
                    "prefix_hits": self.prefix_hits,
                    "session_hits": self.session_hits,
                    "session_misses": self.session_misses,
                    "prefix_tokens_matched": self.prefix_tokens_matched,
                    "prefill_tokens": self.prefill_tokens,
                    "cow_copies": self.cow_copies,
                    "prefix_evictions": self.prefix_evictions,
                    "session_evictions": self.session_evictions,
                    "shared_blocks": self.shared_blocks,
                    "prefix_blocks": self.prefix_blocks,
                    "sessions_live": self.sessions_live,
                },
                "offload": {
                    "enabled": self.offload_enabled,
                    "demotions": self.offload_demotions,
                    "restores": self.offload_restores,
                    "prefetch_hits": self.offload_prefetch_hits,
                    "demote_failures": self.offload_demote_failures,
                    "restore_failures": self.offload_restore_failures,
                    "spills": self.offload_spills,
                    "drops": self.offload_drops,
                    "host_runs": self.offload_host_runs,
                    "host_blocks": self.offload_host_blocks,
                    "host_bytes": self.offload_host_bytes,
                    "disk_blocks": self.offload_disk_blocks,
                    "disk_bytes": self.offload_disk_bytes,
                    "restore_ms": {
                        k: round(v, 3) for k, v in
                        self.offload_restore_ms.snapshot().items()},
                    "demote_ms": {
                        k: round(v, 3) for k, v in
                        self.offload_demote_ms.snapshot().items()},
                },
            }
        return {
            "cache_backend": self.cache_backend,
            "paged": paged,
            "requests": self.requests,
            "responses": self.responses,
            "client_errors": self.client_errors,
            "server_errors": self.server_errors,
            "shed": self.shed,
            "shed_batch": self.shed_batch,
            "shed_deadline": self.shed_deadline,
            "timeouts": self.timeouts,
            "faults": {
                "retries": self.retries,
                "recoveries": self.recoveries,
                "quarantined": self.quarantined,
                "drains": self.drains,
            },
            "queue_depth": self.queue_depth,
            "queue_max": self.queue_max,
            "prefills": self.prefills,
            "decode_steps": self.decode_steps,
            "tokens_generated": self.tokens.total(),
            "tokens_per_sec": round(self.tokens.rate(), 3),
            "slots": {
                "num_slots": self.num_slots,
                "active": self.active_slots,
                "mean_occupancy": round(occ.mean(), 3),
                "utilization": round(
                    occ.mean() / self.num_slots, 4) if (
                        self.num_slots and steps) else 0.0,
                "occupancy_hist": occ.snapshot(),
            },
            "spec": {
                "enabled": self.speculation_k > 0,
                "speculation_k": self.speculation_k,
                "draft_tokens_proposed": self.spec_draft_tokens_proposed,
                "draft_tokens_accepted": self.spec_draft_tokens_accepted,
                "accept_rate": round(
                    self.spec_draft_tokens_accepted
                    / self.spec_draft_tokens_proposed, 4)
                if self.spec_draft_tokens_proposed else 0.0,
                "verify_batches": self.spec_verify_batches,
                "rollbacks": self.spec_rollbacks,
                "draft_fallbacks": self.spec_draft_fallbacks,
            },
            "prompt_bucket_hist": self.prompt_bucket_hist.snapshot(),
            "ttft_ms": {k: round(v, 3) for k, v in
                        self.ttft_ms.snapshot().items()},
            "itl_ms": {k: round(v, 3) for k, v in
                       self.itl_ms.snapshot().items()},
            "prefill_ms": {k: round(v, 3) for k, v in
                           self.prefill_ms.snapshot().items()},
            "decode_step_ms": {k: round(v, 3) for k, v in
                               self.decode_step_ms.snapshot().items()},
            "decode_sync_wait_ms": {
                k: round(v, 3) for k, v in
                self.decode_sync_wait_ms.snapshot().items()},
            "kv_cache_bytes": self.cache_bytes,
            "kv_dtype": self.kv_dtype,
            "kv_bits": self.kv_bits,
            "kv_bytes_per_token": self.kv_bytes_per_token,
            "quant": {
                "blocks_quantized": self.quant_blocks_quantized,
                "scale_bytes": self.quant_scale_bytes,
            },
            "compile_cache": {
                "compiles": self.compiles,
                "warmed_buckets": list(self.warmed_buckets),
            },
        }


def profiler_sections() -> Dict:
    """The profiler's own `serving.*` section timings (populated when
    ProfilingMode is OPERATIONS/ALL), merged into `GET /stats`."""
    return {name: stats for name, stats in
            OpProfiler.get_instance().timings().items()
            if name.startswith("serving.")}


# -- Prometheus text exposition ----------------------------------------
# `GET /metrics` on both the replica server and the fleet front-end is
# generated here from the SAME snapshot dicts `GET /stats` serves, so
# the two views cannot drift: one source of truth, two encodings.
# Output follows the text exposition format version 0.0.4 (`# TYPE`
# lines, label escaping, one family per metric name).

#: monotonically increasing snapshot fields -> emitted as counters with
#: the conventional ``_total`` suffix; every other numeric leaf is a
#: gauge. Keyed by the LEAF name, so ``faults.retries`` matches
#: ``retries`` here.
_PROM_COUNTERS = frozenset({
    "requests", "responses", "client_errors", "server_errors",
    "shed", "shed_batch", "shed_deadline", "timeouts",
    "retries", "recoveries", "quarantined", "drains",
    "batches", "prefills", "decode_steps", "tokens_generated",
    "prefill_chunks", "chunked_prefills",
    "prefix_hits", "session_hits", "session_misses",
    "prefix_tokens_matched", "prefill_tokens", "cow_copies",
    "prefix_evictions", "session_evictions",
    # speculative decoding (the `spec` snapshot block; leaf names —
    # `spec_verify_batches` also matches the `batches` rule, the rest
    # are matched here)
    "draft_tokens_proposed", "draft_tokens_accepted", "verify_batches",
    "rollbacks", "draft_fallbacks",
    # hierarchical KV tier (the `paged.offload` snapshot block)
    "demotions", "restores", "prefetch_hits", "demote_failures",
    "restore_failures",
    "compiles", "hits", "misses", "evictions",
    "client_disconnects",
    # fleet-side counters
    "routed", "hedges", "hedges_won", "hedge_budget_denied",
    "requests_lost", "ejections", "readmissions", "restarts",
    "streams", "sheds", "cooldowns", "breaker_trips",
    "breaker_probes", "breaker_recoveries", "fleet_shed",
    "session_affinity_hits",
    # training-side counters (supervisor / async writer / per-worker
    # fleet telemetry / event-timeline rollups / stats router)
    "anomalies_skipped", "async_checkpoints", "sync_checkpoints",
    "sharded_checkpoints", "preemptions", "preempts_broadcast",
    "preempts_received", "writes", "steps", "preempts",
    "anomaly_skips", "dropped",
    "preempt_broadcast", "preempt_received", "anomaly_skip",
    "rollback", "checkpoint_commit", "re_mesh", "resume",
})

_RESERVOIR_KEYS = frozenset(RESERVOIR_SNAPSHOT_KEYS)


def _prom_name(*parts: str) -> str:
    name = "_".join(p for p in parts if p)
    name = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_escape(value) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _prom_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class _PromWriter:
    """Accumulates samples grouped per metric family (the exposition
    format requires all lines of one name to be contiguous, with the
    `# TYPE` line first)."""

    def __init__(self):
        self._families: "Dict[str, Dict]" = {}

    def sample(self, name: str, mtype: str, labels: Dict, value):
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = {"type": mtype, "lines": []}
        lab = ",".join(f'{k}="{_prom_escape(v)}"'
                       for k, v in labels.items() if v is not None)
        fam["lines"].append(
            f"{name}{{{lab}}} {_prom_value(value)}" if lab
            else f"{name} {_prom_value(value)}")

    def render(self) -> str:
        out = []
        for name, fam in self._families.items():
            out.append(f"# TYPE {name} {fam['type']}")
            out.extend(fam["lines"])
        return "\n".join(out) + "\n" if out else "\n"


def _walk(w: _PromWriter, base: str, labels: Dict, obj) -> None:
    """Recursively flatten a stats snapshot into exposition samples.
    Reservoir-shaped dicts become summaries (quantile-labelled, plus
    `_count`); integer-keyed dicts (CountHistograms) become one
    labelled series; strings are skipped (identity lives in labels)."""
    if isinstance(obj, bool) or isinstance(obj, (int, float)):
        if any(base.endswith("_" + c) or base == c
               for c in _PROM_COUNTERS):
            w.sample(base + "_total", "counter", labels, obj)
        else:
            w.sample(base, "gauge", labels, obj)
        return
    if isinstance(obj, dict):
        if obj and set(obj) == _RESERVOIR_KEYS:
            for q, key in (("0.5", "p50"), ("0.9", "p90"),
                           ("0.99", "p99")):
                w.sample(base, "summary",
                         {**labels, "quantile": q}, obj[key])
            w.sample(base + "_count", "summary", labels, obj["count"])
            w.sample(base + "_mean", "gauge", labels, obj["mean"])
            w.sample(base + "_max", "gauge", labels, obj["max"])
            return
        if obj and all(_is_int_key(k) for k in obj) and \
                all(isinstance(v, (int, float)) for v in obj.values()):
            # CountHistogram shape: int keys, numeric values -> one
            # bucket-labelled series. Int-keyed dicts of DICTS (e.g.
            # per-worker fleet telemetry) fall through to nested paths
            for k, v in obj.items():
                w.sample(base, "gauge", {**labels, "bucket": k}, v)
            return
        for k, v in obj.items():
            _walk(w, _prom_name(base, str(k)), labels, v)
        return
    if isinstance(obj, (list, tuple)):
        w.sample(base + "_count", "gauge", labels, len(obj))
        return
    # strings / None: identity belongs in labels, not sample values


def _is_int_key(k) -> bool:
    try:
        int(k)
        return True
    except (TypeError, ValueError):
        return False


def prometheus_text(stats: Dict, prefix: str = "dl4j") -> str:
    """Render a `/stats`-shaped snapshot (replica server or fleet
    router) as Prometheus text exposition. Replica server snapshots
    (``{"summary", "models", "profiler"}``) emit per-model families
    labelled ``{model=...}``; fleet snapshots (``{"fleet": ...}``)
    emit fleet counters plus per-replica gauges labelled
    ``{replica=...}``."""
    w = _PromWriter()
    if "models" in stats:
        summary = dict(stats.get("summary") or {})
        summary.pop("models", None)      # covered by the models block
        _walk(w, _prom_name(prefix, "server"), {}, summary)
        for mname, snap in (stats.get("models") or {}).items():
            _walk(w, _prom_name(prefix, "model"), {"model": mname}, snap)
        for section, timing in (stats.get("profiler") or {}).items():
            _walk(w, _prom_name(prefix, "profiler"),
                  {"section": section}, timing)
    elif "fleet" in stats:
        fl = dict(stats["fleet"])
        replicas = fl.pop("replicas", [])
        _walk(w, _prom_name(prefix, "fleet"), {}, fl)
        for rep in replicas:
            rid = rep.get("id") if isinstance(rep, dict) else None
            _walk(w, _prom_name(prefix, "replica"),
                  {"replica": rid}, rep)
    else:
        _walk(w, prefix, {}, stats)
    return w.render()
