"""Inference engine: bucketed batching + bounded compiled-executable cache.

Ref role: `libnd4j/server/GraphServer.cpp` caches the compiled graph
across requests; TensorFlow Serving's BatchingSession pads requests to
allowed batch sizes so one compiled program serves many request shapes.

TPU-native shape: every novel input shape costs an XLA compile, so the
engine pads each request batch up to the next power-of-two BUCKET and
keeps a bounded LRU of ahead-of-time compiled executables keyed by
(bucket, row signature, outputs). Steady-state traffic therefore runs
entirely out of the cache; `warmup(buckets=...)` pre-compiles the hot
buckets before the server takes traffic.
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from ..profiler import OpProfiler
from .metrics import ServingMetrics


# -- process-level XLA executable memo ---------------------------------
# ``jax.jit(fn).lower(...).compile()`` bypasses jax's jit cache (every
# engine builds fresh closures), so two engines serving the same
# architecture at the same shapes each pay the full XLA compile — which
# dominates multi-engine processes (replica-per-model servers, test
# suites). The memo is keyed by the lowered program's own text:
# identical HLO is identical compute, so there is no config
# fingerprint to get wrong. Backend and donation spec are in the key
# because they live in compile options, not (reliably) in the text.
# Tracing/lowering still runs per engine (cheap); only the XLA compile
# is shared. Executables are stateless and reentrant, so cross-engine
# sharing — donated buffers included — is safe.
_EXE_MEMO: "OrderedDict[Tuple, Any]" = OrderedDict()
_EXE_MEMO_LOCK = threading.Lock()
_EXE_MEMO_CAP = 64


def compile_memoized(fn, args, donate_argnums=()):
    """``jit(fn, donate).lower(*args).compile()`` with a bounded
    process-level LRU keyed by (backend, donation, sha256(HLO))."""
    donate = tuple(donate_argnums)
    lowered = jax.jit(fn, donate_argnums=donate).lower(*args)
    key = (jax.default_backend(), donate,
           hashlib.sha256(lowered.as_text().encode()).hexdigest())
    with _EXE_MEMO_LOCK:
        exe = _EXE_MEMO.get(key)
        if exe is not None:
            _EXE_MEMO.move_to_end(key)
            return exe
    exe = lowered.compile()
    with _EXE_MEMO_LOCK:
        prior = _EXE_MEMO.get(key)
        if prior is not None:
            return prior          # lost a benign compile race
        _EXE_MEMO[key] = exe
        while len(_EXE_MEMO) > _EXE_MEMO_CAP:
            _EXE_MEMO.popitem(last=False)
    return exe


class ServingError(RuntimeError):
    """Base class for serving-layer failures (maps to HTTP 5xx)."""


class ClientError(ValueError):
    """Malformed request — the caller's fault (maps to HTTP 400)."""


def next_bucket(n: int, min_bucket: int = 1, max_bucket: int = 1 << 30) -> int:
    """Smallest power-of-two >= n, clamped to [min_bucket, max_bucket]."""
    if n <= 0:
        raise ClientError("empty batch")
    b = max(int(min_bucket), 1)
    while b < n:
        b <<= 1
    return min(b, int(max_bucket))


def _pad_rows(a: np.ndarray, bucket: int) -> np.ndarray:
    n = a.shape[0]
    if n == bucket:
        return a
    pad = np.zeros((bucket - n,) + a.shape[1:], a.dtype)
    return np.concatenate([a, pad], axis=0)


class InferenceEngine:
    """Wraps any model exposing ``output(...)`` behind a bucketed,
    compile-cached forward pass.

    Supported natively (params passed as executable arguments, so the
    weights are NOT baked into each compiled program):
    - :class:`~deeplearning4j_tpu.nn.MultiLayerNetwork`
    - :class:`~deeplearning4j_tpu.nn.graph.ComputationGraph`
    - :class:`~deeplearning4j_tpu.autodiff.SameDiff` (named feeds;
      ``default_outputs`` or per-request ``outputs`` select heads)

    Anything else with an ``output(x)`` method falls back to calling it
    per batch (still bucket-padded, so the model's own jit cache keys
    stay bounded), without the AOT executable cache.
    """

    def __init__(self, model, default_outputs: Optional[Sequence[str]] = None,
                 max_batch_size: int = 64, min_bucket: int = 1,
                 cache_size: int = 16,
                 metrics: Optional[ServingMetrics] = None,
                 fault_injector=None):
        self.model = model
        # serving/faults.py FaultInjector (or None — the default; the
        # hot path then pays exactly one attribute load per call)
        self._faults = fault_injector
        self.default_outputs = list(default_outputs or [])
        self.max_batch_size = int(max_batch_size)
        self.min_bucket = int(min_bucket)
        self.metrics = metrics or ServingMetrics()
        self._cache: "OrderedDict[tuple, Any]" = OrderedDict()
        self._cache_size = max(1, int(cache_size))
        self._lock = threading.Lock()
        self._compiling: Dict[tuple, threading.Event] = {}
        self._profiler = OpProfiler.get_instance()
        self._kind, self._fn_for = self._adapt(model)

    # -- model adapters ------------------------------------------------
    def _adapt(self, model):
        """Returns (kind, fn_for(outputs) -> f(state, inputs)). Weights
        flow through ``state`` (see :meth:`_state_for`), never as
        closure constants, so executables serve the model's LIVE
        parameters — a fit() or checkpoint restore after registration
        is picked up on the next request."""
        from ..autodiff.samediff import SameDiff
        if isinstance(model, SameDiff):
            def fn_for(outputs):
                if not outputs:
                    raise ClientError("SameDiff serving needs 'outputs'")
                gfn = model._build(tuple(outputs))
                needed = set(gfn.needed)

                def f(state, feed):
                    vals = {k: v for k, v in {**state[0], **feed}.items()
                            if k in needed}
                    return gfn(vals, state[1])
                f.needed = gfn.needed
                return f
            return "samediff", fn_for
        cls = type(model).__name__
        if hasattr(model, "_forward") and hasattr(model, "conf") and \
                hasattr(model.conf, "graph_inputs"):
            if getattr(model, "_params", None) is None:
                model.init()

            def fn_for(outputs):
                def f(state, inputs):
                    acts, _ = model._forward(state[0], state[1], inputs,
                                             False, None)
                    return [acts[n]
                            for n in (outputs or model.conf.graph_outputs)]
                return f
            return "graph", fn_for
        if hasattr(model, "_forward") and hasattr(model, "_reshape_input"):
            if getattr(model, "_params", None) is None:
                model.init()

            def fn_for(outputs):
                def f(state, x):
                    act, _, _ = model._forward(state[0], state[1],
                                               model._reshape_input(x),
                                               False, None)
                    return act
                return f
            return "mln", fn_for
        if not hasattr(model, "output"):
            raise ServingError(
                f"{cls} has no output(...) method — cannot serve it")
        return "duck", None

    def _state_for(self, fn):
        """Executable arguments holding the weights, read LIVE from the
        model at every call (SameDiff resolves per output-head: only
        the values that head needs)."""
        if self._kind != "samediff":
            return (self.model._params, self.model._net_state)
        from ..autodiff.samediff import VariableType
        model = self.model
        vals = {k: v for k, v in model._values.items()
                if k in set(fn.needed)
                and model._vars[k].vtype != VariableType.PLACEHOLDER}
        return (vals, jax.random.PRNGKey(model.seed))

    # -- request normalization -----------------------------------------
    def normalize(self, inputs, outputs=None):
        """Parse a request payload into (feed, n_rows, signature).

        Arrays for MLN/ComputationGraph-style models; name->array dicts
        for SameDiff / multi-input graphs. Raises :class:`ClientError`
        on malformed payloads."""
        outs = tuple(outputs or self.default_outputs)
        if self._kind == "samediff":
            if not isinstance(inputs, dict):
                raise ClientError(
                    "SameDiff serving takes {'inputs': {name: array}}")
            if not outs:
                raise ClientError("SameDiff serving needs 'outputs'")
            from ..autodiff.samediff import VariableType
            unknown = [o for o in outs if o not in self.model._vars]
            if unknown:
                raise ClientError(f"unknown outputs {unknown}")
            feed = {}
            for k, v in inputs.items():
                var = self.model._vars.get(k)
                if var is None:
                    raise ClientError(f"unknown input {k!r}")
                dtype = getattr(var, "dtype", None) or np.float32
                try:
                    feed[k] = np.asarray(v, dtype)
                except (TypeError, ValueError) as e:
                    raise ClientError(f"input {k!r} is not a tensor: {e}")
            if not feed:
                raise ClientError("empty inputs")
            for k, a in feed.items():
                if a.ndim == 0:
                    raise ClientError(
                        f"input {k!r} must be at least 1-D (a batch)")
            fn = self.model._build(outs)
            missing = [nm for nm in fn.needed if nm not in feed
                       and self.model._vars[nm].vtype
                       == VariableType.PLACEHOLDER]
            if missing:
                raise ClientError(f"missing inputs for placeholders "
                                  f"{missing}")
            ns = {a.shape[0] for a in feed.values()}
            if len(ns) != 1:
                raise ClientError(f"inconsistent batch sizes: {sorted(ns)}")
            n = ns.pop()
            sig = ("sd", outs, tuple(sorted(
                (k, a.shape[1:], str(a.dtype)) for k, a in feed.items())))
            return feed, n, sig
        if self._kind == "graph" and outs:
            unknown = [o for o in outs
                       if o not in self.model.conf.graph_outputs]
            if unknown:
                raise ClientError(
                    f"unknown outputs {unknown} (graph outputs: "
                    f"{self.model.conf.graph_outputs})")
        elif outs and list(outs) != list(self.default_outputs):
            # MLN/duck models have one unnamed output head; silently
            # returning it under the client's requested name would be
            # a lie
            raise ClientError(
                "this model has a single unnamed output — omit 'outputs'")
        if isinstance(inputs, dict):
            if self._kind != "graph":
                raise ClientError("this model takes a plain array input")
            feed = {}
            for k, v in inputs.items():
                if k not in self.model.conf.graph_inputs:
                    raise ClientError(f"unknown input {k!r} (graph inputs: "
                                      f"{self.model.conf.graph_inputs})")
                try:
                    feed[k] = np.asarray(v, np.float32)
                except (TypeError, ValueError) as e:
                    raise ClientError(f"input {k!r} is not a tensor: {e}")
            if set(feed) != set(self.model.conf.graph_inputs):
                raise ClientError(
                    f"graph needs inputs {self.model.conf.graph_inputs}")
            for k, a in feed.items():
                if a.ndim == 0:
                    raise ClientError(
                        f"input {k!r} must be at least 1-D (a batch)")
            ns = {a.shape[0] for a in feed.values()}
            if len(ns) != 1:
                raise ClientError(f"inconsistent batch sizes: {sorted(ns)}")
            n = ns.pop()
            sig = ("graph", outs, tuple(sorted(
                (k, a.shape[1:]) for k, a in feed.items())))
            return feed, n, sig
        try:
            x = np.asarray(inputs, np.float32)
        except (TypeError, ValueError) as e:
            raise ClientError(f"inputs is not a tensor: {e}")
        if x.ndim == 0:
            raise ClientError("inputs must be at least 1-D (a batch)")
        if self._kind == "graph":
            gin = self.model.conf.graph_inputs
            if len(gin) > 1:
                raise ClientError(
                    "multi-input graph needs {'inputs': {name: array}}")
            feed = {gin[0]: x}
            return feed, x.shape[0], ("graph", outs,
                                      ((gin[0], x.shape[1:]),))
        return x, x.shape[0], (self._kind, outs, x.shape[1:])

    # -- compile cache -------------------------------------------------
    def _compiled(self, sig, bucket, feed):
        key = (sig, bucket)
        while True:
            with self._lock:
                hit = self._cache.get(key)
                if hit is not None:
                    self._cache.move_to_end(key)
                    self.metrics.cache_hits += 1
                    return hit
                ev = self._compiling.get(key)
                if ev is None:
                    # claim the compile; do it OUTSIDE the lock so
                    # cache hits for other buckets never wait on a
                    # multi-second XLA compile
                    ev = threading.Event()
                    self._compiling[key] = ev
                    self.metrics.cache_misses += 1
                    break
            ev.wait()  # another thread is compiling this key — reuse it
        try:
            fn = self._fn_for(sig[1])
            state = self._state_for(fn)
            with self._profiler.record("serving.compile"):
                exe = compile_memoized(fn, (state, feed))
            with self._lock:
                self.metrics.compiles += 1
                # cache the executable WITH its fn: weights are re-read
                # live via _state_for at every call, never frozen in
                self._cache[key] = (exe, fn)
                while len(self._cache) > self._cache_size:
                    self._cache.popitem(last=False)
                    self.metrics.cache_evictions += 1
                return self._cache[key]
        finally:
            with self._lock:
                self._compiling.pop(key, None)
            ev.set()

    def warmup(self, buckets: Sequence[int], example=None,
               outputs: Optional[Sequence[str]] = None):
        """Pre-compile executables for the given batch buckets so the
        server never compiles under traffic. ``example`` is one request
        payload (any batch size — row 0 is replicated); SameDiff models
        with fully-known placeholder shapes can omit it."""
        if example is None:
            example = self._infer_example(outputs)
        feed, _, sig = self.normalize(example, outputs)
        warmed = []
        for b in sorted(set(int(x) for x in buckets)):
            if b < 1 or b > self.max_batch_size:
                raise ValueError(f"bucket {b} outside [1, max_batch_size="
                                 f"{self.max_batch_size}]")
            padded = (jax.tree_util.tree_map(lambda a: _pad_rows(a[:1], b),
                                             feed)
                      if isinstance(feed, dict) else _pad_rows(feed[:1], b))
            self._compiled(sig, b, padded)
            warmed.append(b)
        self.metrics.warmed_buckets = sorted(
            set(self.metrics.warmed_buckets) | set(warmed))
        return warmed

    def _infer_example(self, outputs):
        if self._kind == "samediff":
            from ..autodiff.samediff import VariableType
            outs = tuple(outputs or self.default_outputs)
            fn = self._fn_for(outs)
            feed = {}
            for nm in fn.needed:
                var = self.model._vars[nm]
                if var.vtype != VariableType.PLACEHOLDER:
                    continue
                shape = var.shape
                if shape is None or any(d is None for d in shape[1:]):
                    raise ValueError(
                        f"placeholder {nm!r} has unknown non-batch dims — "
                        "pass example= to warmup()")
                feed[nm] = np.zeros((1,) + tuple(shape[1:]),
                                    var.dtype or np.float32)
            return feed
        shape = getattr(self.model, "_input_shape", None)
        kind = getattr(self.model, "_input_kind", None)
        if shape:
            if kind == "cnnflat":
                h, w, c = shape
                return np.zeros((1, h * w * c), np.float32)
            return np.zeros((1,) + tuple(shape), np.float32)
        raise ValueError("cannot infer the input shape for this model — "
                         "pass example= to warmup()")

    # -- execution -----------------------------------------------------
    def predict(self, inputs, outputs: Optional[Sequence[str]] = None,
                trace=None):
        """Run one (possibly multi-request) batch. Batches larger than
        ``max_batch_size`` are chunked. Returns numpy results shaped
        like the model's own ``output(...)``. ``trace`` (a
        :class:`~..tracing.Trace`, or ``None``) records the device call
        as one retroactive span — the unbatched direct path's analog of
        the batcher's per-request device span."""
        feed, n, sig = self.normalize(inputs, outputs)
        if trace is None:
            return self.predict_normalized(feed, n, sig)
        t0 = time.perf_counter()
        res = self.predict_normalized(feed, n, sig)
        trace.span("device", t_start=t0, t_end=time.perf_counter(),
                   rows=n, bucket=next_bucket(
                       min(n, self.max_batch_size), self.min_bucket,
                       self.max_batch_size))
        return res

    def predict_normalized(self, feed, n, sig):
        """Hot-path entry for callers that already hold a normalized
        (feed, n_rows, signature) triple — the batcher's device call
        goes through here so the scheduler thread never re-validates
        rows every submit() already validated."""
        if n > self.max_batch_size:
            parts = []
            for i in range(0, n, self.max_batch_size):
                part = _slice(feed, i, i + self.max_batch_size)
                parts.append(self.predict_normalized(
                    part, min(self.max_batch_size, n - i), sig))
            return _concat_results(parts)
        bucket = next_bucket(n, self.min_bucket, self.max_batch_size)
        self.metrics.bucket_hist.record(bucket)
        padded = (jax.tree_util.tree_map(lambda a: _pad_rows(a, bucket), feed)
                  if isinstance(feed, dict) else _pad_rows(feed, bucket))
        if self._faults is not None:
            # injection seam: fires BEFORE the device call, so a
            # transient fault leaves no partial state and the batcher
            # above can retry the whole call
            self._faults.fire("device_step")
        if self._kind == "duck":
            # fallback: the model's own output() (its internal jit cache
            # still benefits from the bounded bucket shapes)
            with self._profiler.record("serving.device_call"):
                res = self.model.output(padded)
            return _trim(res, n, bucket, sig[1])
        exe, fn = self._compiled(sig, bucket, padded)
        with self._profiler.record("serving.device_call"):
            res = exe(self._state_for(fn), padded)
        return _trim(res, n, bucket, sig[1])


def _slice(tree, lo, hi):
    """Row-slice a feed or result (dict / list-of-heads / array)."""
    if isinstance(tree, dict):
        return {k: v[lo:hi] for k, v in tree.items()}
    if isinstance(tree, list):
        return [v[lo:hi] for v in tree]
    return tree[lo:hi]


def _row_aligned(v, bucket):
    """Padding and coalescing are only sound for outputs with one row
    per input row. A batch-REDUCING head (e.g. a mean over the batch)
    would silently fold the zero padding rows — and other requests'
    rows — into every answer, so fail loudly instead."""
    a = np.asarray(v)
    if a.ndim == 0 or a.shape[0] != bucket:
        raise ServingError(
            f"model output shape {a.shape} is not row-aligned with the "
            f"batch (expected leading dim {bucket}); batch-reducing "
            "outputs cannot be served through the dynamic batcher — "
            "compute them client-side or serve via model.output directly")
    return a


def _trim(res, n, bucket, outs):
    """Strip padding rows and convert to numpy."""
    if isinstance(res, dict):
        return {k: _row_aligned(v, bucket)[:n] for k, v in res.items()}
    if isinstance(res, (list, tuple)):
        trimmed = [_row_aligned(v, bucket)[:n] for v in res]
        if outs and len(outs) == len(trimmed):
            return dict(zip(outs, trimmed))
        return trimmed[0] if len(trimmed) == 1 else trimmed
    return _row_aligned(res, bucket)[:n]


def _concat_results(parts):
    first = parts[0]
    if isinstance(first, dict):
        return {k: np.concatenate([p[k] for p in parts]) for k in first}
    if isinstance(first, list):
        return [np.concatenate([p[i] for p in parts])
                for i in range(len(first))]
    return np.concatenate(parts)
