"""Continuous-batching autoregressive generation runtime.

Iteration-level scheduling (Orca, OSDI '22) over a slot-managed
static-shape KV cache (the vLLM/PagedAttention regime at slot
granularity, PAPERS.md): instead of batching whole generate() calls —
where the fastest request waits for the slowest — the scheduler
re-forms the device batch EVERY DECODE STEP. Each iteration it

1. admits queued requests into free cache slots (one compiled prefill
   per admission, at the request's power-of-two prompt bucket),
2. decodes ONE token for every active slot in a single device call
   (the same compiled executable every step — shapes never change),
3. samples per-slot (greedy / temperature / top-k, per-request seeded
   PRNG folded with the step index, so results are reproducible
   regardless of which slot or step a request lands on), and
4. retires sequences on EOS or ``max_tokens``, freeing their slots for
   the next admission — a finishing request never blocks on its batch.

Exactly TWO executable kinds exist: single-token decode over the full
slot batch, and prefill per prompt bucket (a handful of power-of-two
lengths). ``warmup()`` AOT-compiles all of them, so steady-state
traffic — any mix of prompt lengths, generation lengths, and sampling
params — runs with ZERO recompiles.

Overload semantics match the micro-batcher: bounded queue sheds
(:class:`~.batcher.QueueFullError` → 503), per-request deadlines
(:class:`~.batcher.DeadlineExceededError` → 504) are enforced both in
the queue and mid-generation.

Admission control (docs/serving.md "Overload and admission control"):
requests carry a priority class (``interactive`` default, ``batch``
shed first — batch work only gets the front ``batch_queue_fraction``
of the queue), and admission is cost-aware: the engine keeps measured
EWMAs of per-token prefill time and per-step decode time, rejects a
request up front when its estimated prefill + ``max_tokens`` decode
cost cannot fit its deadline budget (504 — no replica can serve it),
and sheds a queued request at dequeue-admission once its queue wait
has eaten the budget needed to produce even a first token — zero
prefill/decode steps are ever spent on a request that cannot finish.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from ..profiler import OpProfiler
from .batcher import (PRIORITIES, DeadlineExceededError, DrainingError,
                      QueueFullError)
from .engine import ClientError, ServingError, compile_memoized
from .faults import (CorruptedStateFault, PoisonRequestError,
                     TransientFault, poll_until_idle)
from ..kernels.kv_quant import (canonical_kv_dtype, kv_bytes_per_token,
                                kv_copy_row, kv_pack_host,
                                kv_unpack_host, kv_update_slice,
                                kv_zeros)
from .kvcache import KVCache, SlotTable
from .metrics import GenerationMetrics
from .offload import (DiskRing, HostBlockStore, HostRun,
                      OffloadPrefetcher)
from .paging import (NULL_BLOCK, BlockAllocator, BlockTable, PagedKVCache,
                     PrefixIndex, SessionStore, blocks_for, chain_hashes,
                     export_block_run, import_block_run, pow2_bucket)
from .speculative import (make_prime_fn, make_propose_fn,
                          make_verify_paged_fn, make_verify_slots_fn,
                          verify_bucket)

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# sampling (pure, jit-traced inside the executables)
# ---------------------------------------------------------------------------
#: static cap on per-request top_k: the filter thresholds via
#: ``lax.top_k(logits, cap)`` — a full per-row sort costs ~10x more on
#: CPU and the cap keeps the executable shape static. Requests asking
#: for top_k >= vocab get exact no-filter sampling.
TOP_K_CAP = 128


def _sample_from_logits(logits, temps, top_ks, us):
    """Greedy (temp <= 0) / temperature / top-k sampling, vectorized
    over rows; ``us`` is one pre-drawn uniform per row and ``top_ks <=
    0`` disables the filter per row. The single shared sampling core —
    prefill and decode both route through it, so the first token and
    every later token come from bit-identical math.

    Two deliberate cost choices, both measured against the decode-step
    budget: the top-k threshold comes from a static-cap ``lax.top_k``
    (not a full sort), and sampling is inverse-CDF with ONE uniform per
    sequence rather than categorical-via-Gumbel (Gumbel needs V
    independent draws per slot per step; the threefry bits for
    [num_slots, V] dominate small-model steps)."""
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    cap = min(TOP_K_CAP, vocab)
    desc = jax.lax.top_k(logits, cap)[0]                   # [S, cap]
    kth = jnp.take_along_axis(
        desc, jnp.clip(top_ks - 1, 0, cap - 1)[:, None], axis=1)
    filt = jnp.where((top_ks[:, None] > 0)
                     & (top_ks[:, None] < vocab)
                     & (logits < kth), _NEG_INF, logits)
    p = jax.nn.softmax(filt / jnp.maximum(temps, 1e-6)[:, None],
                       axis=-1)
    c = jnp.cumsum(p, axis=-1)
    sampled = jnp.argmax(c > (us * c[:, -1])[:, None],  # c[-1]: drift
                         axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


def _sample_batch(logits, temps, top_ks, seeds, steps):
    """Decode-step sampling over the slot batch. The per-request PRNG
    stream is fold_in(PRNGKey(seed), step) — slot- and
    schedule-independent, so results are reproducible under any
    admission order."""
    keys = jax.vmap(
        lambda s, t: jax.random.fold_in(jax.random.PRNGKey(s), t))(
        seeds, steps)
    us = jax.vmap(lambda k: jax.random.uniform(k, ()))(keys)
    return _sample_from_logits(logits, temps, top_ks, us)


def _sample_one(logits, temp, top_k, key):
    """Single-row sampling (prefill). ``key`` is the request's step-0
    fold; the math is the shared core, one-row batched."""
    u = jax.random.uniform(key, ())
    return _sample_from_logits(
        logits[None], jnp.asarray(temp, jnp.float32)[None],
        jnp.asarray(top_k, jnp.int32)[None], u[None])[0]


# ---------------------------------------------------------------------------
# request
# ---------------------------------------------------------------------------
def _recovery_seq(req: "_GenRequest") -> np.ndarray:
    """The K/V prefix a (possibly recovered) request must hold before
    its next decode step: the prompt, plus — after recompute-recovery —
    the already-emitted tokens minus the last one, whose K/V the next
    decode step writes at ``pos`` exactly like a fresh admission's
    first sampled token. Shared by both cache backends so the resume
    math can never diverge between them."""
    if req.tokens:
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens[:-1], np.int32)])
    return req.prompt


class _GenRequest:
    __slots__ = ("prompt", "max_tokens", "temperature", "top_k", "seed",
                 "eos_id", "deadline", "priority", "session_id", "event",
                 "tokens", "error", "finish_reason", "stream_q",
                 "stream_notify",
                 "t_submit", "t_first", "t_last", "abandoned",
                 "recoveries", "_lock", "_timeout_counted", "trace",
                 "qspan", "spec_rounds", "spec_proposed",
                 "spec_accepted", "spec_emitted", "spec_dt0", "spec_dt1",
                 "spec_vt0", "spec_vt1", "pipe_d0", "pipe_w0")

    def __init__(self, prompt, max_tokens, temperature, top_k, seed,
                 eos_id, deadline, stream: bool,
                 priority: str = "interactive",
                 session_id: Optional[str] = None):
        self.prompt = prompt
        self.session_id = session_id
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.eos_id = eos_id
        self.deadline = deadline
        self.priority = priority
        self.event = threading.Event()
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.finish_reason: Optional[str] = None
        # unbounded on purpose: admission is already bounded by the
        # request queue + slot count; the scheduler must never block on
        # a slow streaming consumer (head-of-line for every other slot)
        self.stream_q: Optional["queue.Queue"] = (
            queue.Queue() if stream else None)
        # optional post-put hook for event-loop consumers: lets an
        # async front-end park on an asyncio.Event instead of holding
        # a blocking-get thread per open stream. Must never raise into
        # the scheduler, so pushes go through _stream_push.
        self.stream_notify: Optional[Callable[[], None]] = None
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        self.abandoned = False  # submitter gave up: skip, don't recount
        self.recoveries = 0     # recompute-recovery re-admissions
        self._lock = threading.Lock()
        self._timeout_counted = False
        self.trace = None   # tracing.Trace when the request is traced
        self.qspan = None   # its open queue-wait span
        # speculative-decoding participation, aggregated per request so
        # the terminal trace can rebuild draft/verify spans
        # retroactively (zero cost in the hot loop beyond 8 stores)
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self.spec_dt0: Optional[float] = None
        self.spec_dt1: Optional[float] = None
        self.spec_vt0: Optional[float] = None
        self.spec_vt1: Optional[float] = None
        # engine-cumulative pipeline counters snapshotted at decode
        # entry; the terminal span reports the deltas over this
        # request's decode lifetime (engine-wide, not per-lane — the
        # sync is shared by the whole batch). None = never decoded on
        # a pipelining engine
        self.pipe_d0: Optional[float] = None
        self.pipe_w0: Optional[float] = None

    def _stream_push(self, item) -> None:
        self.stream_q.put(item)
        cb = self.stream_notify
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — consumer bug, not ours
                pass

    def count_timeout_once(self, metrics) -> None:
        """The waiter and the scheduler can both observe this request's
        deadline expiring at the same instant — the timeouts counter
        must move exactly once per request, so the decision is a CAS
        under the request's own lock."""
        with self._lock:
            if self._timeout_counted:
                return
            self._timeout_counted = True
        metrics.inc("timeouts")

    def result(self) -> Dict[str, Any]:
        return {"tokens": list(self.tokens),
                "prompt_tokens": len(self.prompt),
                "finish_reason": self.finish_reason}


class _TokenStream:
    """Iterator over one streaming generation. ``close()`` — invoked
    explicitly by the HTTP layer on disconnect, and by GC as a
    backstop — abandons an unfinished request so the scheduler frees
    its slot, EVEN if the consumer never started iterating (a plain
    generator's ``finally`` would not run in that case)."""

    def __init__(self, engine: "GenerationEngine", req: _GenRequest):
        self._engine = engine
        self._req = req
        self._i = 0
        self._done = False

    def __iter__(self) -> "Iterator[Dict]":
        return self

    def __next__(self) -> Dict:
        if self._done:
            raise StopIteration
        req = self._req
        budget = req.deadline - time.perf_counter() + 1.0
        try:
            kind, payload = req.stream_q.get(timeout=max(budget, 0.001))
        except queue.Empty:
            self._done = True
            req.abandoned = True
            req.count_timeout_once(self._engine.metrics)
            raise DeadlineExceededError("stream stalled past the "
                                        "deadline")
        if kind == "token":
            i = self._i
            self._i += 1
            return {"token": int(payload), "index": i}
        self._done = True
        if kind == "done":
            self._engine.metrics.inc("responses")
            final = req.result()
            final["done"] = True
            return final
        raise payload  # "error"

    def close(self):
        if not self._done and self._req.finish_reason is None \
                and self._req.error is None:
            self._req.abandoned = True  # scheduler frees the slot
        self._done = True

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — never raise from GC
            pass


class _ChunkState:
    """One request mid-prefill on the paged backend: its slot, its
    block table, and the chunk plan with a cursor. The scheduler
    processes ONE chunk per loop iteration, interleaved with decode
    steps, so a long prompt's prefill never stalls the decode loop for
    longer than one chunk (Sarathi-Serve, PAPERS.md).

    ``seq`` is the token prefix the chunks run over: the prompt for a
    fresh admission, or prompt + already-emitted tokens (minus the
    last, whose K/V the next decode step writes) when re-admitted by
    recompute-recovery. ``start`` is the prefix-cache match length —
    positions below it hold valid K/V from shared/copied blocks, so
    the plan's first chunk begins there and ``done_tokens`` counts
    them as live from the moment of admission."""

    __slots__ = ("req", "slot", "table", "tbl_bucket", "plan", "idx",
                 "seq", "start")

    def __init__(self, req: "_GenRequest", slot: int, table: BlockTable,
                 tbl_bucket: int, plan: List[Tuple[int, int, int]],
                 seq: np.ndarray, start: int = 0):
        self.req = req
        self.slot = slot
        self.table = table
        self.tbl_bucket = tbl_bucket
        self.plan = plan                  # [(p0, chunk_bucket, len)]
        self.idx = 0
        self.seq = seq
        self.start = start

    @property
    def done_tokens(self) -> int:
        return self.plan[self.idx - 1][0] + self.plan[self.idx - 1][2] \
            if self.idx else self.start


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------
class GenerationEngine:
    """Slot-based continuous-batching decode engine over a
    :class:`~deeplearning4j_tpu.zoo.transformer_lm.CausalTransformerLM`
    (or any model exposing the same ``forward_prefill`` /
    ``forward_decode`` / ``cache_shapes`` surface).

    ``num_slots`` bounds concurrent in-flight sequences (the device
    batch of every decode step); ``max_seq_len`` bounds prompt +
    generated tokens per sequence and sizes the KV cache. Both are
    STATIC — admission control handles everything dynamic.

    Two cache backends (``cache=``):

    - ``"slots"`` (default) — dense per-slot panels
      ``[num_slots, H, max_seq_len, Dh]``: memory scales with the
      WORST-CASE sequence length per slot.
    - ``"paged"`` — a shared block pool
      ``[num_blocks, H, block_size, Dh]`` (`serving/paging.py`): a
      request claims ``ceil((prompt + max_tokens) / block_size)``
      blocks at admission (all-or-nothing — when blocks run out the
      request WAITS at the queue head instead of over-committing), so
      at equal pool bytes the engine holds as many more concurrent
      sequences as real lengths are shorter than ``max_seq_len``.
      Prefill runs in CHUNKS of at most ``prefill_chunk_tokens``
      interleaved with decode steps, so a long prompt admitted
      mid-stream cannot stall every other request's inter-token
      latency for more than one chunk. Token outputs are identical to
      the slot backend (test-asserted). With ``enable_prefix_sharing``
      (default on), admission matches the prompt against an LRU index
      of chained-content-hashed full prompt blocks and against
      ``session_id``-pinned conversation state: matched blocks join
      the request's table by refcount (skipping their prefill
      entirely, copy-on-write isolating any mid-block tail), so a
      fleet-wide system prompt is prefilled once and a chat turn
      re-prefills only its new suffix (docs/generation.md "Prefix
      sharing").
    """

    def __init__(self, model, num_slots: int = 8,
                 max_seq_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 min_prompt_bucket: int = 8,
                 max_queue: int = 256,
                 default_timeout_ms: float = 60_000.0,
                 decode_impl: str = "auto",
                 cache: str = "slots",
                 block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 enable_prefix_sharing: bool = True,
                 prefix_index_capacity: int = 1024,
                 session_capacity: int = 64,
                 metrics: Optional[GenerationMetrics] = None,
                 fault_injector=None,
                 max_step_retries: int = 3,
                 retry_backoff_ms: float = 1.0,
                 retry_backoff_max_ms: float = 50.0,
                 max_recoveries_per_request: int = 3,
                 stall_timeout_s: float = 30.0,
                 batch_queue_fraction: float = 0.5,
                 speculation_k: int = 0,
                 draft_model=None,
                 decode_pipeline: bool = True,
                 kv_dtype: str = "f32",
                 offload_host_bytes: int = 0,
                 offload_disk_bytes: int = 0,
                 offload_dir: Optional[str] = None,
                 offload_prefetch: bool = True):
        if getattr(model, "_params", None) is None:
            model.init()
        self.model = model
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.max_seq_len = int(max_seq_len or model.max_seq_len)
        if self.max_seq_len < 2:
            raise ValueError("max_seq_len must be >= 2 (one prompt "
                             "token + one generated token)")
        if self.max_seq_len > model.max_seq_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"position table ({model.max_seq_len})")
        # speculative decoding (serving/speculative.py): k = 0 is OFF
        # (the default — no draft model, no extra executables, the
        # decode loop is byte-for-byte the non-speculative one)
        self.speculation_k = int(speculation_k)
        if self.speculation_k < 0:
            raise ValueError(f"speculation_k must be >= 0, "
                             f"got {speculation_k}")
        if self.speculation_k and \
                self.speculation_k + 1 >= self.max_seq_len:
            raise ValueError(
                f"speculation_k {self.speculation_k} leaves no room "
                f"under max_seq_len {self.max_seq_len}")
        self._vbucket = (verify_bucket(self.speculation_k)
                         if self.speculation_k else 0)
        self.decode_impl = decode_impl
        # quantized serving plane (ISSUE 15): storage precision of the
        # KV pool — "f32" (exact, default), "bf16" (half the bytes),
        # "int8" (quarter; per-row f32 scale sidecars ride the same
        # pytrees). The draft cache stays f32: it is tiny and its
        # tokens are only proposals, verified by the target anyway.
        self.kv_dtype = canonical_kv_dtype(kv_dtype)
        self.default_timeout_ms = float(default_timeout_ms)
        self.min_prompt_bucket = int(min_prompt_bucket)
        if prompt_buckets is None:
            prompt_buckets = []
            b = self.min_prompt_bucket
            while b < self.max_seq_len:
                prompt_buckets.append(b)
                b <<= 1
        # max_seq_len is always a bucket so every admissible prompt
        # (validated <= max_seq_len - 1) has a compiled home; a custom
        # list with gaps just routes up to the next present bucket
        self.prompt_buckets = sorted(
            set(int(b) for b in prompt_buckets) | {self.max_seq_len})
        if self.prompt_buckets[0] < 1 or \
                self.prompt_buckets[-1] > self.max_seq_len:
            raise ValueError(f"prompt_buckets {self.prompt_buckets} "
                             f"outside [1, max_seq_len]")
        if cache not in ("slots", "paged"):
            raise ValueError(f"cache must be 'slots' or 'paged', "
                             f"got {cache!r}")
        self.cache_backend = cache
        if cache == "paged":
            self.block_size = int(block_size)
            if not 1 <= self.block_size <= self.max_seq_len:
                raise ValueError(f"block_size {block_size} outside "
                                 f"[1, max_seq_len]")
            # dense decode-table width: every position < max_seq_len
            # has a table entry, so one decode executable serves all
            self._blocks_per_seq = blocks_for(self.max_seq_len,
                                              self.block_size)
            if num_blocks is None:
                # dense-equivalent capacity (+1 for the null block);
                # shrink it to realize the memory win, or keep it and
                # raise num_slots to realize the concurrency win
                num_blocks = self.num_slots * self._blocks_per_seq + 1
            self.num_blocks = int(num_blocks)
            # chunk ladder: the prompt buckets capped at the chunk
            # size; prefill_chunk_tokens=None means whole-prompt
            # single-chunk prefill (chunking off, paging still on)
            cap = self.prompt_buckets[-1]
            if prefill_chunk_tokens is not None:
                if int(prefill_chunk_tokens) < 1:
                    raise ValueError("prefill_chunk_tokens must be >= 1")
                cap = min(pow2_bucket(int(prefill_chunk_tokens)), cap)
            self.prefill_chunk_tokens = (
                cap if prefill_chunk_tokens is not None else None)
            self._chunk_cap = cap
            self.chunk_buckets = sorted(
                set(b for b in self.prompt_buckets if b < cap) | {cap})
            # largest per-request table bucket: the last chunk's
            # bucket can overshoot the allocation by < chunk_cap, and
            # a speculative verify span's padded tail by < its bucket.
            # The overshoot MUST stay inside the table (not merely be
            # masked): an out-of-range gather index clamps to the
            # table's LAST entry, which for an exactly-sized table is
            # a REAL block — the padded rows' junk writes would land
            # in live data
            self._tbl_top = pow2_bucket(
                blocks_for(self.max_seq_len + max(cap, self._vbucket),
                           self.block_size))
            self._tbl_buckets = []
            b = 1
            while b <= self._tbl_top:
                self._tbl_buckets.append(b)
                b <<= 1
            self._allocator = BlockAllocator(self.num_blocks)
            self._tables = np.full(
                (self.num_slots, self._blocks_per_seq), NULL_BLOCK,
                np.int32)
            self._slot_blocks: List[Optional[BlockTable]] = \
                [None] * self.num_slots
            self._prefilling: "collections.deque[_ChunkState]" = \
                collections.deque()
            self._held: Optional[_GenRequest] = None
            # prefix sharing: chained-hash index over full prompt
            # blocks + session pins; both are scheduler-thread state
            self.enable_prefix_sharing = bool(enable_prefix_sharing)
            self._prefix_index = PrefixIndex(int(prefix_index_capacity))
            self._sessions = SessionStore(int(session_capacity))
        else:
            self.prefill_chunk_tokens = None
            self.enable_prefix_sharing = False
        # -- hierarchical KV tier (PR 16; serving/offload.py) --------
        # offload_host_bytes > 0 turns demote-on-evict on: evicted
        # session/prefix pins copy device->host (at kv_dtype, scale
        # sidecars included) instead of being discarded, and a
        # returning session RESTORES host->device instead of
        # re-prefilling. offload_disk_bytes adds a mmap'd ring file
        # as a third tier below host RAM.
        self.offload_host_bytes = int(offload_host_bytes)
        self._offload: Optional[HostBlockStore] = None
        self._offload_prefetcher: Optional[OffloadPrefetcher] = None
        self._off_buckets: List[int] = []
        if self.offload_host_bytes > 0:
            if self.cache_backend != "paged":
                raise ValueError("offload_host_bytes requires the "
                                 "paged cache backend (cache='paged')")
            if not self.enable_prefix_sharing:
                raise ValueError(
                    "offload_host_bytes requires prefix sharing "
                    "(enable_prefix_sharing=True): restores re-enter "
                    "the engine through session/prefix matching")
            disk = None
            if int(offload_disk_bytes) > 0:
                import os as _os
                path = (_os.path.join(offload_dir, "kv_ring.bin")
                        if offload_dir else None)
                disk = DiskRing(int(offload_disk_bytes), path=path)
            self._offload = HostBlockStore(self.offload_host_bytes,
                                           disk=disk)
            # demoted runs span 1..blocks_for(max_seq_len) blocks;
            # pow2-bucketing the gather/scatter index keeps the
            # executable set finite and AOT-warmable (the same rule
            # the block tables use)
            top = pow2_bucket(self._blocks_per_seq)
            self._off_buckets = [b for b in self._tbl_buckets
                                 if b <= top]
            if offload_prefetch:
                self._offload_prefetcher = OffloadPrefetcher(
                    self._stage_restore)
        self.metrics = metrics or GenerationMetrics()
        self.metrics.queue_max = int(max_queue)
        self.metrics.num_slots = self.num_slots
        self.metrics.cache_backend = self.cache_backend
        self._cache = self._fresh_cache()
        self.metrics.cache_bytes = self._cache.nbytes()
        self.metrics.kv_dtype = self.kv_dtype
        self.metrics.kv_bits = {"f32": 32, "bf16": 16, "int8": 8}[
            self.kv_dtype]
        self.metrics.kv_bytes_per_token = kv_bytes_per_token(
            self._cache.layer_shapes, self.kv_dtype)
        self.metrics.quant_scale_bytes = self._cache.scale_nbytes()
        self._kcs = self._cache.ks
        self._vcs = self._cache.vs
        self._slots = SlotTable(self.num_slots)
        # -- speculative decoding state -----------------------------
        self._draft = None
        self._draft_cache = None
        self._draft_kcs = self._draft_vcs = None
        if self.speculation_k:
            if draft_model is None:
                from ..zoo.transformer_lm import make_draft_lm
                draft_model = make_draft_lm(model)
            if getattr(draft_model, "_params", None) is None:
                draft_model.init()
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.vocab_size} != target "
                    f"vocab {model.vocab_size}: the draft must share "
                    f"the target's tokenizer")
            if draft_model.max_seq_len < self.max_seq_len:
                raise ValueError(
                    f"draft position table ({draft_model.max_seq_len})"
                    f" shorter than max_seq_len {self.max_seq_len}")
            self._draft = draft_model
            self._reset_draft_cache()
            self.metrics.cache_bytes += self._draft_cache.nbytes()
            # draft-prime bucket ladder: pow2 steps TOPPED BY
            # max_seq_len itself (not its pow2 round-up — the draft's
            # dense cache is exactly max_seq_len deep, and the prime
            # update slab must fit inside it)
            self._prime_buckets = []
            b = self.min_prompt_bucket
            while b < self.max_seq_len:
                self._prime_buckets.append(b)
                b <<= 1
            self._prime_buckets.append(self.max_seq_len)
        self.metrics.speculation_k = self.speculation_k
        if self.cache_backend == "paged":
            self.metrics.block_size = self.block_size
            self.metrics.blocks_total = self._allocator.capacity
            self.metrics.prefix_sharing = self.enable_prefix_sharing
            self.metrics.offload_enabled = self._offload is not None
            self._update_block_gauges()
        self._profiler = OpProfiler.get_instance()
        # exactly two executable kinds: decode (one) + prefill (per
        # prompt bucket). Compiled lazily or via warmup(); the dict is
        # bounded by len(prompt_buckets), so no LRU is needed.
        self._decode_exe = None
        self._prefill_exe: Dict[int, Any] = {}
        self._cow_exe = None  # paged + sharing: block device-copy
        # hierarchical KV tier: block-run gather (demote) / scatter
        # (restore) executables, one per pow2 idx bucket
        self._offload_save_exe: Dict[int, Any] = {}
        self._offload_load_exe: Dict[int, Any] = {}
        # speculative executables: one draft-propose, draft-prime per
        # prime bucket, verify per table bucket (paged) or one (slots)
        self._draft_exe = None
        self._draft_prime_exe: Dict[int, Any] = {}
        self._verify_exe: Dict[Any, Any] = {}
        self._exe_lock = threading.Lock()
        # K/V caches are DONATED to every prefill/decode call: XLA then
        # updates the cache in place instead of copying the whole
        # [num_slots, max_seq_len, ...] arrays each step — without this
        # the per-step cost scales with num_slots and continuous
        # batching loses its amortization (measured 0.5x vs sequential
        # on CPU with copies; 4x+ with donation)
        self._donate = (1, 2)
        # -- pipelined decode (ISSUE 14) ----------------------------
        # With the pipeline on (default; speculation forces it off —
        # verify rounds are inherently synchronous), the scheduler
        # dispatches decode step t+1 BEFORE syncing step t's tokens,
        # so host bookkeeping (emit, retire, admit) overlaps device
        # compute. Donation already forces device program order, so
        # the overlap changes WHEN the host learns each token, never
        # WHICH token. The knob exists for A/B identity tests.
        self.decode_pipeline = bool(decode_pipeline) \
            and not self.speculation_k
        # in-flight decode steps, oldest first (depth is at most 2 for
        # the moment between dispatching t+1 and collecting t)
        self._pending: "collections.deque" = collections.deque()
        # device handle of the LAST dispatched step's sampled tokens
        # ([num_slots] int32, never synced) — fed back as the next
        # step's tok_dev input; None until the first dispatch
        self._nxt_dev = None
        # lanes whose current token lives ONLY on the device (True
        # after a pipelined dispatch; False on prefill / free /
        # recovery, which refresh the host mirror)
        self._tok_on_dev = np.zeros(self.num_slots, bool)
        # constants for the non-pipelined path: read host tokens for
        # every lane, no device feedback (never mutated, safe to share
        # across calls without the defensive .copy())
        self._all_host = np.ones(self.num_slots, bool)
        self._no_dev_tok = np.zeros(self.num_slots, np.int32)
        # engine-cumulative pipeline accounting (seconds): the span
        # from dispatch to results-on-host, and how long the host
        # actually BLOCKED at the sync — terminal request spans and
        # tools/trace_report.py's phase table read the deltas
        self._step_span_s = 0.0
        self._sync_wait_s = 0.0
        self._queue: "queue.Queue[_GenRequest]" = queue.Queue(
            maxsize=int(max_queue))
        # submit-wake: an idle scheduler parks on this event instead
        # of polling the queue every 50 ms (ISSUE 14) — set by
        # _enqueue after each put and by stop()/drain()
        self._wake = threading.Event()
        # priority shedding: batch-class work only gets the front
        # fraction of the queue; interactive gets all of it
        self.batch_queue_fraction = float(batch_queue_fraction)
        self._batch_queue_limit = max(
            1, int(self.batch_queue_fraction * int(max_queue)))
        # cost-aware admission: measured EWMAs (per PROMPT TOKEN of
        # prefill, per STEP of decode) — 0.0 until the first call
        # lands, so a cold engine admits everything
        self._prefill_ms_per_tok = 0.0
        self._decode_ewma_ms = 0.0
        # -- fault tolerance (serving/faults.py) --------------------
        # seams fire only when an injector is configured; the
        # supervised loop always runs (real device faults need no
        # injector to happen)
        self._faults = fault_injector
        self._max_step_retries = int(max_step_retries)
        self._retry_backoff_s = float(retry_backoff_ms) / 1e3
        self._retry_backoff_max_s = float(retry_backoff_max_ms) / 1e3
        self._max_recoveries = int(max_recoveries_per_request)
        self._stall_timeout_s = float(stall_timeout_s)
        # requests to re-admit AHEAD of the queue: transient-faulted
        # admissions and recompute-recovery re-admissions (they were
        # already accepted — later arrivals must not starve them)
        self._requeue: "collections.deque[_GenRequest]" = \
            collections.deque()
        self._draining = False
        self._beat = time.monotonic()  # scheduler heartbeat (/healthz)
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="generation-scheduler")
        self._thread.start()

    def _fresh_cache(self):
        """Cache sized to the ENGINE's max_seq_len (which may be below
        the model's position table) — decode attention scans the full
        cache capacity every step, so capacity must match the
        configured bound, not the architectural one. Paged: the pool's
        per-block layer shapes come from the same model surface."""
        if self.cache_backend == "paged":
            return PagedKVCache(self.model.cache_shapes(self.block_size),
                                self.num_blocks,
                                kv_dtype=self.kv_dtype)
        return KVCache(self.model.cache_shapes(self.max_seq_len),
                       self.num_slots, kv_dtype=self.kv_dtype)

    def _update_block_gauges(self):
        """Push allocator + liveness gauges into the metrics object
        (snapshot() reads them lock-free from the stats thread).

        ``kv_tokens_live`` counts each UNIQUE block once at its
        maximum valid fill across owners — summing per-owner lengths
        (the pre-sharing rule) would double-count a shared prefix and
        drive fragmentation negative. With sharing disabled every
        block has one owner and this reduces to the old sum."""
        a = self._allocator
        self.metrics.blocks_free = a.free_count
        self.metrics.blocks_peak_used = a.peak_used
        bs = self.block_size
        fill: Dict[int, int] = {}

        def cover(blocks, n_tokens):
            for i, b in enumerate(blocks):
                f = min(bs, int(n_tokens) - i * bs)
                if f <= 0:
                    break
                if f > fill.get(b, 0):
                    fill[b] = f

        st = self._slots
        for s in range(self.num_slots):
            if st.requests[s] is not None and st.step[s] > 0:
                t = self._slot_blocks[s]
                if t is not None:
                    cover(t.blocks, int(st.pos[s]) + 1)
        for c in self._prefilling:
            cover(c.table.blocks, c.done_tokens)
        for blocks, n in self._sessions.iter_pins():
            cover(blocks, n)
        for b in self._prefix_index.blocks():
            fill[b] = bs  # indexed blocks are full prompt blocks
        self.metrics.kv_tokens_live = sum(fill.values())
        self.metrics.kv_tokens_allocated = a.used_count * bs
        if self.kv_dtype == "int8":
            # every allocated block holds quantize-on-write content
            self.metrics.quant_blocks_quantized = a.used_count
        self.metrics.shared_blocks = a.shared_count
        self.metrics.prefix_blocks = len(self._prefix_index)
        self.metrics.sessions_live = len(self._sessions)
        off = self._offload
        if off is not None:
            s = off.stats()
            m = self.metrics
            m.offload_host_runs = s["host_runs"]
            m.offload_host_blocks = s["host_blocks"]
            m.offload_host_bytes = s["host_bytes"]
            m.offload_disk_blocks = s["disk_blocks"]
            m.offload_disk_bytes = s["disk_bytes"]
            m.offload_spills = s["spills"]
            m.offload_drops = s["drops"]

    # -- executables ---------------------------------------------------
    # Every executable also returns a FINITE-LOGITS flag computed
    # in-graph (an all-reduce over isfinite — noise next to the
    # matmuls): the poison-request guard. A request whose own weights+
    # tokens drive the logits to NaN/Inf is QUARANTINED by the host
    # loop — failed alone with 500, slot/blocks freed — instead of
    # silently emitting garbage or wedging the batch.
    def _decode_fn(self):
        """One decode step over the full slot batch.

        Two ISSUE 14 additions, both in-graph so the pipelined
        scheduler never needs an extra host round-trip:

        - **Token merge.** Each lane's input token comes from EITHER
          the host mirror (``tok_host`` — fresh prefills, recovery
          resumes, the non-pipelined path) OR the PREVIOUS step's
          device output fed straight back in (``tok_dev``), selected
          per lane by ``use_host``. That is what lets the scheduler
          dispatch step t+1 before step t's tokens ever reach the
          host: a continuing lane's token never leaves the device.
        - **Fused termination.** ``done`` = sampled-EOS | length-cap,
          computed from the per-lane ``eos`` id (-1 = none; sampled
          tokens are >= 0 so -1 never matches) and ``max_steps``
          (``steps`` counts tokens already emitted, so this step is
          number ``steps + 1``). Retirement needs no host-side
          re-derivation from request state."""
        model = self.model
        impl = self.decode_impl

        if self.cache_backend == "paged":
            def step(params, kcs, vcs, tok_host, tok_dev, use_host,
                     pos, tables, seeds, steps, temps, top_ks, eos,
                     max_steps):
                tokens = jnp.where(use_host, tok_host, tok_dev)
                logits, kcs, vcs = model.forward_decode_paged(
                    params, tokens, pos, kcs, vcs, tables, impl)
                ok = jnp.all(jnp.isfinite(logits), axis=-1)  # per lane
                nxt = _sample_batch(logits, temps, top_ks, seeds, steps)
                done = ((nxt == eos) & (eos >= 0)) \
                    | (steps + 1 >= max_steps)
                return nxt, ok, done, kcs, vcs
            return step

        def step(params, kcs, vcs, tok_host, tok_dev, use_host, pos,
                 seeds, steps, temps, top_ks, eos, max_steps):
            tokens = jnp.where(use_host, tok_host, tok_dev)
            logits, kcs, vcs = model.forward_decode(params, tokens, pos,
                                                    kcs, vcs, impl)
            ok = jnp.all(jnp.isfinite(logits), axis=-1)      # per lane
            nxt = _sample_batch(logits, temps, top_ks, seeds, steps)
            done = ((nxt == eos) & (eos >= 0)) | (steps + 1 >= max_steps)
            return nxt, ok, done, kcs, vcs
        return step

    def _chunk_fn(self):
        model = self.model

        def chunk(params, kcs, vcs, tokens, p0, chunk_len, table, seed,
                  temp, top_k):
            logits, kcs, vcs = model.forward_prefill_chunk(
                params, tokens, p0, chunk_len, kcs, vcs, table)
            # guard only rows < chunk_len: padded tail rows attend
            # positions past the live length — stale block junk that
            # is allowed to be anything (no-zeroing invariant)
            ok = jnp.all(jnp.where(
                (jnp.arange(tokens.shape[1]) < chunk_len)[:, None],
                jnp.isfinite(logits), True))
            last = jax.lax.dynamic_index_in_dim(
                logits, chunk_len - 1, axis=0, keepdims=False)
            # same step-0 fold as the slot prefill — the first token's
            # sample is bit-identical across backends
            key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)
            first = _sample_one(last, temp, top_k, key)
            return first, ok, kcs, vcs
        return chunk

    def _prefill_fn(self):
        model = self.model

        def prefill(params, kcs, vcs, tokens, length, slot, seed, temp,
                    top_k):
            bucket = tokens.shape[1]
            key_mask = (jnp.arange(bucket)[None] < length).astype(
                jnp.float32)
            logits, ks, vs = model.forward_prefill(params, tokens,
                                                   key_mask)
            # padded rows only see keys under key_mask, so any
            # non-finite value traces back to the request's own tokens
            ok = jnp.all(jnp.isfinite(logits))
            # write this request's K/V rows into its slot; positions
            # past ``length`` hold junk from the padded prompt tail but
            # stay masked (and are overwritten as decode advances)
            kcs = [kv_update_slice(kc, k, (slot, 0, 0, 0))
                   for kc, k in zip(kcs, ks)]
            vcs = [kv_update_slice(vc, v, (slot, 0, 0, 0))
                   for vc, v in zip(vcs, vs)]
            last = jax.lax.dynamic_index_in_dim(
                logits[0], length - 1, axis=0, keepdims=False)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)
            first = _sample_one(last, temp, top_k, key)
            return first, ok, kcs, vcs
        return prefill

    def _get_decode_exe(self):
        if self._decode_exe is not None:
            return self._decode_exe
        with self._exe_lock:
            if self._decode_exe is not None:
                return self._decode_exe
            S = self.num_slots
            if self.cache_backend == "paged":
                args = (self.model._params, self._kcs, self._vcs,
                        np.zeros(S, np.int32), np.zeros(S, np.int32),
                        np.ones(S, bool), np.zeros(S, np.int32),
                        np.full((S, self._blocks_per_seq), NULL_BLOCK,
                                np.int32),
                        np.zeros(S, np.uint32), np.zeros(S, np.int32),
                        np.zeros(S, np.float32), np.zeros(S, np.int32),
                        np.full(S, -1, np.int32), np.zeros(S, np.int32))
            else:
                args = (self.model._params, self._kcs, self._vcs,
                        np.zeros(S, np.int32), np.zeros(S, np.int32),
                        np.ones(S, bool), np.zeros(S, np.int32),
                        np.zeros(S, np.uint32), np.zeros(S, np.int32),
                        np.zeros(S, np.float32), np.zeros(S, np.int32),
                        np.full(S, -1, np.int32), np.zeros(S, np.int32))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(self._decode_fn(), args,
                                       self._donate)
            self.metrics.inc("compiles")
            self._decode_exe = exe
            return exe

    def _get_chunk_exe(self, chunk_bucket: int, tbl_bucket: int):
        """Paged prefill executable for one (chunk bucket, table
        bucket) pair — the bounded grid replacing the slot backend's
        per-prompt-bucket prefill set."""
        key = (chunk_bucket, tbl_bucket)
        exe = self._prefill_exe.get(key)
        if exe is not None:
            return exe
        with self._exe_lock:
            exe = self._prefill_exe.get(key)
            if exe is not None:
                return exe
            args = (self.model._params, self._kcs, self._vcs,
                    np.zeros((1, chunk_bucket), np.int32), np.int32(0),
                    np.int32(1),
                    np.full(tbl_bucket, NULL_BLOCK, np.int32),
                    np.uint32(0), np.float32(0.0), np.int32(0))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(self._chunk_fn(), args,
                                       self._donate)
            self.metrics.inc("compiles")
            self._prefill_exe[key] = exe
            return exe

    def _cow_fn(self):
        def cow(kcs, vcs, src, dst):
            # kv_copy_row copies the int8 block AND its scale row
            # together — a scale-less copy would silently rescale the
            # shared prefix (tests/test_kv_quant.py::TestCOWScales)
            kcs = [kv_copy_row(kc, src, dst) for kc in kcs]
            vcs = [kv_copy_row(vc, src, dst) for vc in vcs]
            return kcs, vcs
        return cow

    def _get_cow_exe(self):
        """Copy-on-write executable: duplicate one pool block (all
        layers, K+V) into another. src/dst are runtime scalars, so ONE
        executable covers every copy — warmed like the rest, it can
        never recompile under traffic."""
        if self._cow_exe is not None:
            return self._cow_exe
        with self._exe_lock:
            if self._cow_exe is not None:
                return self._cow_exe
            args = (self._kcs, self._vcs, np.int32(0), np.int32(0))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(self._cow_fn(), args, (0, 1))
            self.metrics.inc("compiles")
            self._cow_exe = exe
            return exe

    def _cow(self, src: int, dst: int):
        """Device-copy block ``src`` into ``dst`` so the admitted
        request can write into its private copy while every other
        reader of ``src`` stays bit-unchanged. The pools are donated;
        the caller maps a failure here to recompute-recovery exactly
        like a failed prefill/decode call."""
        with self._profiler.record("generation.cow"):
            self._kcs, self._vcs = self._get_cow_exe()(
                self._kcs, self._vcs, np.int32(src), np.int32(dst))
            jax.block_until_ready(self._kcs[0])  # surface device faults

    # -- hierarchical KV tier (PR 16; serving/offload.py) --------------
    # Demotion gathers a block run device->host; restore scatters it
    # back. Both are one executable per pow2 idx bucket, compiled
    # through the same memoized path as the COW copy — the idx array
    # and row operands are RUNTIME values, so after warmup() no
    # offload traffic can ever recompile.
    def _get_offload_save_exe(self, bucket: int):
        """Block-run gather executable (demotion read). Pools are NOT
        donated: a failed demotion must leave the device tier exactly
        as it was, so the engine can fall back to plain discard."""
        exe = self._offload_save_exe.get(bucket)
        if exe is not None:
            return exe
        with self._exe_lock:
            exe = self._offload_save_exe.get(bucket)
            if exe is not None:
                return exe
            args = (self._kcs, self._vcs,
                    np.full(bucket, NULL_BLOCK, np.int32))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(export_block_run, args, ())
            self.metrics.inc("compiles")
            self._offload_save_exe[bucket] = exe
            return exe

    def _get_offload_load_exe(self, bucket: int):
        """Block-run scatter executable (restore write). Pools ARE
        donated (the restore writes in place); padded idx rows point
        at the null block. A real failure here donated the pools away
        — the caller maps it to recompute-recovery, exactly like a
        failed prefill."""
        exe = self._offload_load_exe.get(bucket)
        if exe is not None:
            return exe
        with self._exe_lock:
            exe = self._offload_load_exe.get(bucket)
            if exe is not None:
                return exe
            rows_k = [kv_zeros((bucket,) + s, self.kv_dtype)
                      for s in self._cache.layer_shapes]
            rows_v = [kv_zeros((bucket,) + s, self.kv_dtype)
                      for s in self._cache.layer_shapes]
            args = (self._kcs, self._vcs, rows_k, rows_v,
                    np.full(bucket, NULL_BLOCK, np.int32))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(import_block_run, args, (0, 1))
            self.metrics.inc("compiles")
            self._offload_load_exe[bucket] = exe
            return exe

    def _export_run(self, tokens: np.ndarray,
                    blocks: List[int]) -> HostRun:
        """Device half of a demotion: gather the run's pool rows (all
        layers, K+V) and pack them into contiguous host arrays at the
        pool dtype. kv_pack_host's np.asarray forces the device->host
        sync, so on return the source blocks may be freed."""
        bucket = pow2_bucket(len(blocks))
        idx = np.full(bucket, NULL_BLOCK, np.int32)
        idx[:len(blocks)] = blocks
        with self._profiler.record("generation.offload_demote"):
            k_rows, v_rows = self._get_offload_save_exe(bucket)(
                self._kcs, self._vcs, idx)
            ks = [kv_pack_host(r, len(blocks)) for r in k_rows]
            vs = [kv_pack_host(r, len(blocks)) for r in v_rows]
        return HostRun(tokens, ks, vs, self.kv_dtype)

    def _build_restore_ops(self, run: HostRun, bucket: int):
        """Zero-pad a HostRun's packed layers up to ``bucket`` rows —
        the scatter executable's operands. Pure host/h2d work: this is
        the half a prefetch overlaps with admission."""
        return ([kv_unpack_host(layer, bucket) for layer in run.ks],
                [kv_unpack_host(layer, bucket) for layer in run.vs])

    def _import_run(self, run: HostRun, blocks: List[int], ops=None):
        """Device half of a restore: scatter the packed run into the
        freshly-allocated ``blocks``. Raises whatever the device call
        raises — the pools were donated, so the CALLER maps failures
        to recompute-recovery."""
        bucket = pow2_bucket(len(blocks))
        idx = np.full(bucket, NULL_BLOCK, np.int32)
        idx[:len(blocks)] = blocks
        if ops is None:
            ops = self._build_restore_ops(run, bucket)
        k_rows, v_rows = ops
        with self._profiler.record("generation.offload_restore"):
            self._kcs, self._vcs = self._get_offload_load_exe(bucket)(
                self._kcs, self._vcs, k_rows, v_rows, idx)
            jax.block_until_ready(self._kcs[0])  # surface device faults

    def _demote_session(self, sess) -> bool:
        """Copy an evicted session's block run to the host tier (the
        caller still frees the device blocks — ownership of the BYTES
        moves down a tier, ownership of the BLOCKS ends). Any failure
        — the offload_io seam or a real gather error — degrades to the
        old discard path: the gather never donates, so the device tier
        is untouched and dropping the copy is always safe."""
        off = self._offload
        sid = sess.session_id
        if off is None or sid is None:
            return False
        t0 = time.perf_counter()
        try:
            self._hit("offload_io")
            run = self._export_run(sess.tokens, sess.blocks)
        except Exception:  # noqa: BLE001 — torn demotion -> discard
            self.metrics.inc("offload_demote_failures")
            return False
        off.put(sid, run)
        self.metrics.inc("offload_demotions")
        self.metrics.offload_demote_ms.record(
            (time.perf_counter() - t0) * 1e3)
        return True

    def _demote_prefix(self, digest: bytes, block: int) -> bool:
        """Demote one evicted prefix-index block, keyed by its chained
        digest — a future admission whose prompt hashes to the same
        chain restores it instead of re-prefilling the block."""
        off = self._offload
        if off is None:
            return False
        try:
            self._hit("offload_io")
            run = self._export_run(np.zeros(0, np.int32), [block])
        except Exception:  # noqa: BLE001 — torn demotion -> discard
            self.metrics.inc("offload_demote_failures")
            return False
        off.put("px:" + digest.hex(), run)
        self.metrics.inc("offload_demotions")
        return True

    def _stage_restore(self, key: str):
        """Prefetch-thread staging: read the run (RAM or disk) and
        build the padded scatter operands. HOST + h2d work only — the
        allocator and every pool-mutating device call stay on the
        scheduler thread, so staging can never race engine state."""
        off = self._offload
        if off is None:
            return None
        run = off.get(key)
        if run is None:
            return None
        bucket = pow2_bucket(run.n_blocks)
        return run, self._build_restore_ops(run, bucket)

    def _offload_restore(self, req: _GenRequest) -> bool:
        """The restore-vs-reprefill decision for one admission: if the
        request's session was demoted, scatter its run back into
        freshly-allocated blocks and re-pin it — ``_match_prefix`` then
        finds a normal session hit and the turn pays only its suffix
        prefill (a restore is a planned cache miss, never a
        re-prefill). Falls back to the plain path (full prefill) on:
        no host copy, token mismatch, pool too full even after
        eviction, or a torn restore (offload_io seam). Only a REAL
        scatter failure escapes — as CorruptedStateFault, because the
        pools were donated to the scatter call."""
        off = self._offload
        if off is None or req.tokens or req.session_id is None:
            return False
        sid = req.session_id
        if sid in self._sessions:
            return False  # device pin is current; host copy is stale
        staged = None
        pf = self._offload_prefetcher
        if pf is not None:
            staged = pf.take(sid)
        run = ops = None
        if staged is not None:
            run, ops = staged
            if off.peek(sid) is not run:
                # the session was re-demoted (or popped) after staging
                # — the staged operands describe stale bytes
                run = ops = None
        if run is None:
            run = off.get(sid)
            if run is None:
                return False
        # token-granular usefulness check, same rule as _match_prefix's
        # session branch: the stored turn must prefix-match the prompt
        prompt = req.prompt
        stored = run.tokens
        n = min(len(stored), len(prompt) - 1)
        neq = stored[:n] != prompt[:n]
        m = int(np.argmax(neq)) if neq.any() else n
        if m <= 0:
            return False
        try:
            self._hit("offload_io")
        except (TransientFault, CorruptedStateFault):
            # torn restore: invalidate the host copy and re-prefill —
            # the lane never saw a device call, nothing to corrupt
            off.pop(sid)
            if pf is not None:
                pf.discard(sid)
            self.metrics.inc("offload_restore_failures")
            return False
        blocks = self._alloc_with_eviction(run.n_blocks)
        if blocks is None:
            return False  # pool cannot hold the run; re-prefill
        t0 = time.perf_counter()
        try:
            self._import_run(run, blocks, ops)
        except Exception as e:  # noqa: BLE001 — pools donated
            raise CorruptedStateFault(
                f"offload restore device call failed: {e!r}")
        displaced = self._sessions.put(sid, run.tokens, list(blocks))
        evictions = 0
        for old in displaced:
            if old.session_id != sid:
                self._demote_session(old)
                evictions += 1
            self._allocator.free(old.blocks)
        if evictions:
            self.metrics.inc("session_evictions", evictions)
        off.pop(sid)
        self.metrics.inc("offload_restores")
        if ops is not None:
            self.metrics.inc("offload_prefetch_hits")
        self.metrics.offload_restore_ms.record(
            (time.perf_counter() - t0) * 1e3)
        if req.trace is not None:
            req.trace.span("offload_restore", tokens=len(stored),
                           blocks=run.n_blocks,
                           prefetched=ops is not None).end()
        return True

    def _restore_prefix_blocks(self, req: _GenRequest):
        """Restore demoted PREFIX blocks the prompt's chain hashes
        to. Runs before ``_match_prefix`` so restored entries are
        matched by the normal index path; stops at the first digest
        found in neither the index nor the host tier (the chain is
        broken there — later blocks cannot be used anyway)."""
        off = self._offload
        if off is None or req.tokens or not self.enable_prefix_sharing:
            return
        if req.session_id is not None and req.session_id in self._sessions:
            return  # the session pin already covers the prefix
        for h in chain_hashes(req.prompt, self.block_size):
            if self._prefix_index.match([h]):
                continue
            key = "px:" + h.hex()
            run = off.get(key)
            if run is None:
                return
            try:
                self._hit("offload_io")
            except (TransientFault, CorruptedStateFault):
                off.pop(key)
                self.metrics.inc("offload_restore_failures")
                return
            blocks = self._alloc_with_eviction(1)
            if blocks is None:
                return
            try:
                self._import_run(run, blocks)
            except Exception as e:  # noqa: BLE001 — pools donated
                raise CorruptedStateFault(
                    f"offload prefix restore device call failed: {e!r}")
            self._prefix_index.register(h, blocks[0])
            off.pop(key)
            self.metrics.inc("offload_restores")

    def _get_prefill_exe(self, bucket: int):
        exe = self._prefill_exe.get(bucket)
        if exe is not None:
            return exe
        with self._exe_lock:
            exe = self._prefill_exe.get(bucket)
            if exe is not None:
                return exe
            args = (self.model._params, self._kcs, self._vcs,
                    np.zeros((1, bucket), np.int32), np.int32(1),
                    np.int32(0), np.uint32(0), np.float32(0.0),
                    np.int32(0))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(self._prefill_fn(), args,
                                       self._donate)
            self.metrics.inc("compiles")
            self._prefill_exe[bucket] = exe
            return exe

    # -- speculative executables (serving/speculative.py) --------------
    def _reset_draft_cache(self, disable_lanes: bool = False):
        """(Re)build the draft model's dense slot cache. Called at
        construction, after recompute-recovery (the draft replays
        nothing — lanes re-prime at their next decode entry), and when
        a draft device call dies mid-flight (its caches were donated;
        ``disable_lanes`` then drops every lane to plain decode until
        re-primed, WITHOUT touching the target's state — a draft
        failure must never cost target work)."""
        self._draft_cache = KVCache(
            self._draft.cache_shapes(self.max_seq_len), self.num_slots)
        self._draft_kcs = self._draft_cache.ks
        self._draft_vcs = self._draft_cache.vs
        if disable_lanes:
            self._slots.spec_ok[:] = False

    def _get_draft_exe(self):
        """One batched draft-propose executable: k greedy draft steps
        over ALL slots in a single device call."""
        if self._draft_exe is not None:
            return self._draft_exe
        with self._exe_lock:
            if self._draft_exe is not None:
                return self._draft_exe
            S = self.num_slots
            args = (self._draft._params, self._draft_kcs,
                    self._draft_vcs, np.zeros(S, np.int32),
                    np.zeros(S, np.int32))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(
                    make_propose_fn(self._draft, self.speculation_k,
                                    self.decode_impl),
                    args, (1, 2))
            self.metrics.inc("compiles")
            self._draft_exe = exe
            return exe

    def _get_draft_prime_exe(self, bucket: int):
        exe = self._draft_prime_exe.get(bucket)
        if exe is not None:
            return exe
        with self._exe_lock:
            exe = self._draft_prime_exe.get(bucket)
            if exe is not None:
                return exe
            args = (self._draft._params, self._draft_kcs,
                    self._draft_vcs, np.zeros((1, bucket), np.int32),
                    np.int32(1), np.int32(0))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(make_prime_fn(self._draft),
                                       args, (1, 2))
            self.metrics.inc("compiles")
            self._draft_prime_exe[bucket] = exe
            return exe

    def _get_verify_exe(self, tbl_bucket: Optional[int] = None):
        """Target-side verification executable: per table bucket on
        the paged backend (the verify span's block table is padded to
        the same pow2 ladder the chunk prefill uses), a single one on
        slots."""
        key = tbl_bucket if self.cache_backend == "paged" else "slots"
        exe = self._verify_exe.get(key)
        if exe is not None:
            return exe
        with self._exe_lock:
            exe = self._verify_exe.get(key)
            if exe is not None:
                return exe
            vb = self._vbucket
            if self.cache_backend == "paged":
                fn = make_verify_paged_fn(self.model)
                args = (self.model._params, self._kcs, self._vcs,
                        np.zeros((1, vb), np.int32), np.int32(0),
                        np.int32(1),
                        np.full(tbl_bucket, NULL_BLOCK, np.int32),
                        np.uint32(0), np.int32(0), np.float32(0.0),
                        np.int32(0))
            else:
                fn = make_verify_slots_fn(self.model)
                args = (self.model._params, self._kcs, self._vcs,
                        np.zeros((1, vb), np.int32), np.int32(0),
                        np.int32(1), np.int32(0), np.uint32(0),
                        np.int32(0), np.float32(0.0), np.int32(0))
            with self._profiler.record("generation.compile"):
                exe = compile_memoized(fn, args, self._donate)
            self.metrics.inc("compiles")
            self._verify_exe[key] = exe
            return exe

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> List[int]:
        """AOT-compile the decode executable plus every prefill
        executable, so traffic never compiles. Slots: one prefill per
        prompt bucket (default: all of ``prompt_buckets``). Paged: one
        per (chunk bucket, table bucket) pair — only pairs where the
        table can actually hold the chunk (``tbl * block_size >=
        chunk``) exist in traffic, so only those are compiled. With
        speculation enabled, also the draft-propose, per-bucket
        draft-prime, and per-table-bucket verify executables — so a
        speculative engine is exactly as recompile-free under traffic
        as a plain one (test-asserted).
        Returns the warmed (chunk-)bucket list."""
        self._get_decode_exe()
        warmed = []
        if self.cache_backend == "paged":
            if self.enable_prefix_sharing:
                self._get_cow_exe()
            if self._offload is not None:
                # one gather + one scatter executable per pow2 run
                # bucket: warmed here, offload traffic never compiles
                for b in self._off_buckets:
                    self._get_offload_save_exe(b)
                    self._get_offload_load_exe(b)
            for c in sorted(set(int(x) for x in (buckets
                                                 or self.chunk_buckets))):
                if c not in self.chunk_buckets:
                    raise ValueError(f"bucket {c} not in chunk_buckets "
                                     f"{self.chunk_buckets}")
                for t in self._tbl_buckets:
                    if t * self.block_size >= c:
                        self._get_chunk_exe(c, t)
                warmed.append(c)
        else:
            for b in sorted(set(int(x) for x in (buckets
                                                 or self.prompt_buckets))):
                if b not in self.prompt_buckets:
                    raise ValueError(f"bucket {b} not in prompt_buckets "
                                     f"{self.prompt_buckets}")
                self._get_prefill_exe(b)
                warmed.append(b)
        if self.speculation_k:
            self._get_draft_exe()
            for b in self._prime_buckets:
                self._get_draft_prime_exe(b)
            if self.cache_backend == "paged":
                for t in self._tbl_buckets:
                    if t * self.block_size >= self._vbucket:
                        self._get_verify_exe(t)
            else:
                self._get_verify_exe()
        self.metrics.warmed_buckets = sorted(
            set(self.metrics.warmed_buckets) | set(warmed))
        return warmed

    # -- client side ---------------------------------------------------
    def _make_request(self, prompt, max_tokens, temperature, top_k, seed,
                      eos_id, timeout_ms, stream,
                      priority="interactive",
                      session_id=None) -> _GenRequest:
        if priority not in PRIORITIES:
            raise ClientError(
                f"unknown priority {priority!r}; expected one of "
                f"{PRIORITIES}")
        if session_id is not None:
            if not isinstance(session_id, str) or not session_id:
                raise ClientError("session_id must be a non-empty "
                                  "string")
            if len(session_id) > 256:
                raise ClientError("session_id must be <= 256 chars")
            if self.cache_backend != "paged":
                raise ClientError("session_id requires the paged cache "
                                  "backend (cache='paged')")
            if not self.enable_prefix_sharing:
                raise ClientError(
                    "session_id requires prefix sharing "
                    "(enable_prefix_sharing=True)")
        if self._draining:
            # checked before _running: a drained replica answers 503 +
            # Retry-After (retry elsewhere), not 500, for its lifetime
            self.metrics.inc("shed")
            raise DrainingError("generation engine is draining; retry "
                                "against another replica")
        if not self._running:
            raise ServingError("generation engine is stopped")
        try:
            raw = np.asarray(prompt)
        except (TypeError, ValueError) as e:
            raise ClientError(f"prompt is not a token array: {e}")
        if not np.issubdtype(raw.dtype, np.integer):
            # np.asarray(.., int32) would silently truncate [3.7, 12.2]
            # to [3, 12] — answer for the wrong prompt, no error
            raise ClientError("prompt token ids must be integers")
        prompt = raw.astype(np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ClientError("prompt must be a non-empty 1-D list of "
                              "token ids")
        vocab = self.model.vocab_size
        if (prompt < 0).any() or (prompt >= vocab).any():
            raise ClientError(f"prompt token ids must be in [0, {vocab})")
        if len(prompt) > self.max_seq_len - 1:
            raise ClientError(
                f"prompt length {len(prompt)} leaves no room to generate "
                f"(max_seq_len {self.max_seq_len})")
        max_tokens = int(max_tokens)
        if max_tokens < 1:
            raise ClientError("max_tokens must be >= 1")
        temperature = float(temperature)
        if not np.isfinite(temperature):
            # json.loads happily parses NaN/Infinity; a NaN here would
            # silently produce argmax-of-all-False = token 0 forever
            raise ClientError("temperature must be finite")
        if timeout_ms is not None and not np.isfinite(float(timeout_ms)):
            raise ClientError("timeout_ms must be finite")
        top_k = int(top_k)
        # normalize the documented no-filter spellings HERE so every
        # value reaching the scheduler is int32-safe — an overflow at
        # the np.int32() device call would poison all in-flight work
        if top_k <= 0 or top_k >= vocab:
            top_k = 0
        elif top_k > TOP_K_CAP:
            raise ClientError(
                f"top_k {top_k} exceeds the engine's static top-k cap "
                f"({TOP_K_CAP}); use top_k=0 (or >= vocab) for "
                "unfiltered sampling")
        # the cache slot is the hard budget: prompt + generation fit it
        max_tokens = min(max_tokens, self.max_seq_len - len(prompt))
        if self.cache_backend == "paged":
            need = blocks_for(len(prompt) + max_tokens, self.block_size)
            if need > self._allocator.capacity:
                raise ClientError(
                    f"request needs {need} KV blocks but the pool has "
                    f"{self._allocator.capacity}; lower max_tokens or "
                    "grow num_blocks")
        if eos_id is None:
            eos_id = getattr(self.model, "eos_id", None)
        timeout = (self.default_timeout_ms if timeout_ms is None
                   else float(timeout_ms)) / 1000.0
        est_ms = self._est_cost_ms(len(prompt), max_tokens)
        if est_ms > timeout * 1e3:
            # cost-aware admission: the measured per-token prefill +
            # per-step decode EWMAs say this request CANNOT finish
            # inside its own deadline budget (worst case: the full
            # max_tokens) — reject before any device work, 504 (no
            # replica can serve it; lower max_tokens or raise the
            # timeout)
            self.metrics.inc("shed_deadline")
            self.metrics.inc("timeouts")
            raise DeadlineExceededError(
                f"estimated cost {est_ms:.0f} ms ({len(prompt)} prompt "
                f"tokens + {max_tokens} max_tokens at measured rates) "
                f"exceeds the {timeout * 1e3:.0f} ms deadline budget")
        return _GenRequest(prompt, max_tokens, float(temperature),
                           int(top_k), int(seed) & 0xFFFFFFFF, eos_id,
                           time.perf_counter() + timeout, stream,
                           priority=priority, session_id=session_id)

    def _padded_prefill_len(self, prompt_len: int) -> int:
        """Prompt tokens the device will actually COMPUTE over during
        prefill: the padded bucket width(s), not the raw length.
        ``_note_prefill_cost`` normalizes the per-token EWMA by padded
        width, so cost estimates must scale by the same quantity — a
        5-token prompt in a 128 bucket pays the full bucket's
        prefill. Paged: the sum of the chunk plan's buckets; slots:
        the prompt bucket the request rounds up to."""
        if self.cache_backend == "paged":
            return sum(b for _, b, _ in self._chunk_plan(prompt_len))
        return next((b for b in self.prompt_buckets if b >= prompt_len),
                    self.prompt_buckets[-1])

    def _est_cost_ms(self, prompt_len: int, max_tokens: int) -> float:
        """Worst-case service estimate from measured rates: prefill of
        the whole PADDED prompt plus ``max_tokens`` decode steps. 0.0
        on a cold engine (no data, no rejection)."""
        return (self._padded_prefill_len(prompt_len)
                * self._prefill_ms_per_tok
                + max_tokens * self._decode_ewma_ms)

    def _deadline_blown(self, req: _GenRequest,
                        now: Optional[float] = None) -> bool:
        """Dequeue-admission deadline budget: not merely 'past the
        deadline' but 'the time left cannot cover even a first token'
        (prefill of the pending prefix + one decode step, at measured
        rates) — in which case prefilling would burn device steps on
        rows nobody will read."""
        now = time.perf_counter() if now is None else now
        min_work_ms = (self._padded_prefill_len(len(req.prompt))
                       * self._prefill_ms_per_tok
                       + self._decode_ewma_ms)
        return now > req.deadline - min_work_ms / 1e3

    def _enqueue(self, req: _GenRequest):
        if self._draining:
            self.metrics.inc("shed")
            raise DrainingError("generation engine is draining; retry "
                                "against another replica")
        if req.priority == "batch" and \
                self._queue.qsize() >= self._batch_queue_limit:
            # shed order: batch first — interactive may still use the
            # remaining queue, so its p99 TTFT holds while batch sheds
            self.metrics.inc("shed")
            self.metrics.inc("shed_batch")
            raise QueueFullError(
                f"generation queue at the batch-priority limit "
                f"({self._batch_queue_limit}/{self.metrics.queue_max});"
                f" shedding batch-class work first")
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.inc("shed")
            raise QueueFullError(
                f"generation queue full ({self.metrics.queue_max}); "
                "shedding load")
        self._wake.set()  # unpark an idle scheduler immediately
        if not self._running:
            req.abandoned = True
            raise ServingError("generation engine is stopped")
        self.metrics.inc("requests")
        self.metrics.queue_depth = self._queue.qsize()

    def generate(self, prompt, max_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 eos_id: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 priority: str = "interactive",
                 session_id: Optional[str] = None,
                 trace=None) -> Dict[str, Any]:
        """Blocking generate: returns ``{"tokens", "prompt_tokens",
        "finish_reason"}``. Raises :class:`~.engine.ClientError` /
        :class:`~.batcher.QueueFullError` /
        :class:`~.batcher.DeadlineExceededError`. ``priority`` is
        ``"interactive"`` (default) or ``"batch"`` (shed first under
        pressure). ``session_id`` (paged backend with prefix sharing
        only) pins the finished request's KV blocks in the session
        store so the conversation's next turn re-prefills only its new
        suffix — see docs/generation.md "Prefix sharing". ``trace``
        (a :class:`~..tracing.Trace`, default ``None`` = untraced)
        records admission/queue/prefill spans plus a retroactive
        decode span — the decode loop itself carries no
        instrumentation, so tracing costs nothing per step."""
        req = self._submit(prompt, max_tokens, temperature, top_k,
                           seed, eos_id, timeout_ms, stream=False,
                           priority=priority, session_id=session_id,
                           trace=trace)
        budget = req.deadline - time.perf_counter()
        if not req.event.wait(budget + 1.0):  # grace for the device call
            req.abandoned = True
            req.count_timeout_once(self.metrics)
            raise DeadlineExceededError(
                f"no result within {budget * 1e3:.0f} ms")
        if req.error is not None:
            raise req.error
        self.metrics.inc("responses")
        return req.result()

    def stream(self, prompt, max_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               eos_id: Optional[int] = None,
               timeout_ms: Optional[float] = None,
               priority: str = "interactive",
               session_id: Optional[str] = None,
               trace=None) -> Iterator[Dict]:
        """Streaming generate: yields ``{"token", "index"}`` per token
        as the scheduler produces it, then ``{"done": True,
        "finish_reason", ...}``. Admission (validation, queue bounds)
        happens HERE — synchronously — so callers can still map those
        to status codes; later failures raise from the iterator."""
        req = self._submit(prompt, max_tokens, temperature, top_k,
                           seed, eos_id, timeout_ms, stream=True,
                           priority=priority, session_id=session_id,
                           trace=trace)
        return _TokenStream(self, req)

    def _submit(self, *args, trace=None, **kw) -> _GenRequest:
        """Validate + enqueue, counting pre-admission 5xx here — the
        engine owns ALL of its server_errors accounting (requests that
        never reach the scheduler have no _fail to count them; the
        HTTP layer deliberately counts none for generation)."""
        t0 = time.perf_counter()
        try:
            req = self._make_request(*args, **kw)
            if trace is not None:
                # attach BEFORE enqueue: the scheduler can admit the
                # request the instant it lands in the queue
                req.trace = trace
                trace.span(
                    "admission", t_start=t0, verdict="admitted",
                    est_cost_ms=round(self._est_cost_ms(
                        len(req.prompt), req.max_tokens), 3),
                    prefill_ms_per_tok=round(
                        self._prefill_ms_per_tok, 4),
                    decode_ewma_ms=round(self._decode_ewma_ms, 3)).end()
                req.qspan = trace.span("queue",
                                       priority=req.priority)
            if (self._offload is not None
                    and self._offload_prefetcher is not None
                    and req.session_id is not None
                    and req.session_id not in self._sessions
                    and req.session_id in self._offload):
                # async prefetch: start staging the demoted run (disk
                # read + padded operand build + h2d) NOW, so it
                # overlaps this request's queue wait — the scheduler
                # takes the staged operands at admission and pays only
                # the scatter. Staleness is re-checked at take time,
                # so a racy glance at the session store here is safe.
                self._offload_prefetcher.request(req.session_id)
            self._enqueue(req)
            return req
        except (ClientError, QueueFullError, DeadlineExceededError) as e:
            if trace is not None:
                trace.span(
                    "admission", t_start=t0, verdict="shed",
                    error=str(e),
                    prefill_ms_per_tok=round(
                        self._prefill_ms_per_tok, 4),
                    decode_ewma_ms=round(self._decode_ewma_ms, 3)).end()
            raise  # counted via their own counters / client's fault
        except Exception:
            self.metrics.inc("server_errors")
            raise

    # -- scheduler side ------------------------------------------------
    def _hit(self, seam: str):
        """Fire the fault-injection seam (no-op without an injector:
        one attribute load)."""
        fi = self._faults
        if fi is not None:
            fi.fire(seam)

    def _trace_terminal(self, req: _GenRequest, reason=None, exc=None):
        """Record the request's terminal span RETROACTIVELY from fields
        the engine already tracks (t_first/t_last/token count) — this
        is how the decode hot loop stays entirely free of tracing code
        while enabled traces still show per-request decode timing and
        the PR 4 fault counters (recoveries/quarantine)."""
        tr = req.trace
        if tr is None:
            return
        if req.qspan is not None:
            req.qspan.end()  # idempotent; covers never-admitted sheds
        attrs = {"steps": len(req.tokens),
                 "recoveries": req.recoveries}
        if reason is not None:
            attrs["finish_reason"] = reason
        if exc is not None:
            attrs["error"] = repr(exc)
            if isinstance(exc, PoisonRequestError):
                attrs["quarantined"] = True
        if req.t_first is not None:
            end = req.t_last if req.t_last is not None else req.t_first
            tr.span("decode", t_start=req.t_first, t_end=end, **attrs)
        else:
            tr.span("error" if exc is not None else "decode",
                    **attrs).end()
        if req.pipe_d0 is not None and self._step_span_s > req.pipe_d0:
            # pipelined-decode accounting over this request's decode
            # lifetime, rebuilt retroactively from engine-cumulative
            # counters snapshotted at admission (the hot loop stores
            # two floats per request, nothing else). ENGINE-wide, not
            # per-lane: every lane in the batch shares one dispatch
            # and one sync. device_ms is the dispatch->results span;
            # sync_wait_ms is how long the scheduler actually blocked
            # — their gap is host work that overlapped device compute.
            dev_s = self._step_span_s - req.pipe_d0
            wait_s = self._sync_wait_s - req.pipe_w0
            tr.span("step_pipeline",
                    device_ms=round(dev_s * 1e3, 3),
                    sync_wait_ms=round(wait_s * 1e3, 3),
                    overlap_frac=round(
                        max(0.0, 1.0 - wait_s / dev_s), 4)).end()
        if req.spec_rounds:
            # speculative participation, rebuilt retroactively from the
            # per-request aggregates (the hot loop never touches the
            # tracer): one draft span + one verify span covering first
            # to last round, with the accounting as attributes
            rate = round(req.spec_accepted / max(req.spec_proposed, 1),
                         4)
            tr.span("draft", t_start=req.spec_dt0, t_end=req.spec_dt1,
                    rounds=req.spec_rounds,
                    proposed=req.spec_proposed)
            tr.span("verify", t_start=req.spec_vt0, t_end=req.spec_vt1,
                    rounds=req.spec_rounds,
                    proposed=req.spec_proposed,
                    accepted=req.spec_accepted,
                    accept_rate=rate,
                    spec_tokens=req.spec_emitted,
                    saved_est_ms=round(
                        max(req.spec_emitted - req.spec_rounds, 0)
                        * self._decode_ewma_ms, 3))

    def _fail(self, req: _GenRequest, exc: BaseException,
              count: bool = True):
        """``count=False`` for graceful-shutdown drains: a deploy
        restart is not an outage and must not spike server_errors
        (matching the MicroBatcher's uncounted drain)."""
        req.error = exc
        if isinstance(exc, DeadlineExceededError):
            req.count_timeout_once(self.metrics)
        elif count and not isinstance(exc, ClientError):
            self.metrics.inc("server_errors")
        self._trace_terminal(req, exc=exc)
        if req.stream_q is not None:
            req._stream_push(("error", exc))
        req.event.set()

    def _emit(self, req: _GenRequest, token: int, now: float,
              itl_out: Optional[List[float]] = None):
        """Deliver one generated token. Latency samples are appended to
        ``itl_out`` (when given) so the decode loop can record the
        whole step's batch under one histogram lock; the tokens-rate
        meter is likewise batched per device call by the callers."""
        req.tokens.append(token)
        if req.t_first is None:
            req.t_first = now
            self.metrics.ttft_ms.record((now - req.t_submit) * 1e3)
        elif itl_out is not None:
            itl_out.append((now - req.t_last) * 1e3)
        else:
            self.metrics.itl_ms.record((now - req.t_last) * 1e3)
        req.t_last = now
        if req.stream_q is not None:
            req._stream_push(("token", token))
            fi = self._faults
            if fi is not None and fi.fire("client_disconnect"):
                # simulate the HTTP consumer hanging up mid-stream:
                # exactly what _TokenStream.close() does on a real
                # disconnect — the scheduler frees the slot/blocks at
                # the next retirement check
                req.abandoned = True

    def _release_slot(self, slot: int):
        """Free a slot AND (paged) its blocks + decode-table row. No
        zeroing either way: the next occupant's writes overwrite what
        it uses and lengths mask the rest (`serving/paging.py`
        invariants)."""
        self._slots.free(slot)
        self._tok_on_dev[slot] = False
        if self.cache_backend == "paged":
            table = self._slot_blocks[slot]
            if table is not None:
                self._allocator.free(table.blocks)
                self._slot_blocks[slot] = None
            self._tables[slot] = NULL_BLOCK
            self._update_block_gauges()
        self.metrics.active_slots = self._slots.active_count

    def _finish(self, slot: int, req: _GenRequest, reason: str):
        req.finish_reason = reason
        if (req.session_id is not None
                and self.cache_backend == "paged"
                and self.enable_prefix_sharing):
            # clean finish with a session: pin the blocks for turn N+1
            # (failure paths — quarantine, deadline, abandonment — all
            # release via _release_slot and never reach here)
            self._pin_session(slot, req)
        else:
            self._release_slot(slot)
        self._trace_terminal(req, reason=reason)
        if req.stream_q is not None:
            req._stream_push(("done", reason))
        req.event.set()

    def _check_done(self, slot: int, req: _GenRequest, token: int,
                    now: Optional[float] = None) -> bool:
        """Retirement test after each emitted token. EOS wins over
        length so the reason is stable when both trip at once."""
        if req.abandoned:
            # the waiter gave up (and counted its own timeout): free
            # the slot now instead of decoding tokens nobody will read
            self._release_slot(slot)
            return True
        if req.eos_id is not None and token == req.eos_id:
            self._finish(slot, req, "eos")
            return True
        if len(req.tokens) >= req.max_tokens:
            self._finish(slot, req, "length")
            return True
        if (time.perf_counter() if now is None else now) > req.deadline:
            self._release_slot(slot)
            self._fail(req, DeadlineExceededError(
                "deadline exceeded mid-generation "
                f"({len(req.tokens)} tokens emitted)"))
            return True
        return False

    def _retire(self, slot: int, req: _GenRequest, token: int,
                done: bool, now: float) -> bool:
        """Retirement off the decode executable's FUSED ``done`` flag
        (EOS | length, computed in-graph — see :meth:`_decode_fn`):
        the host only disambiguates WHICH of the two tripped, for the
        finish_reason, with EOS winning when both trip at once —
        identical semantics to :meth:`_check_done`, which remains the
        host-side test for paths without fused flags (prefill's first
        token, speculative commits). Abandonment and deadline stay
        host-side: both are wall-clock/consumer conditions the device
        cannot know."""
        if req.abandoned:
            self._release_slot(slot)
            return True
        if done:
            if req.eos_id is not None and token == req.eos_id:
                self._finish(slot, req, "eos")
            else:
                self._finish(slot, req, "length")
            return True
        if now > req.deadline:
            self._release_slot(slot)
            self._fail(req, DeadlineExceededError(
                "deadline exceeded mid-generation "
                f"({len(req.tokens)} tokens emitted)"))
            return True
        return False

    def _next_queued(self, busy: bool) -> Optional[_GenRequest]:
        """Pop the next queued request without idle-spinning. A BUSY
        engine (active lanes / chunks mid-prefill) must keep its
        decode loop stepping, so the pop is non-blocking exactly as
        before. A fully IDLE engine used to poll ``get(timeout=0.05)``
        — 20 wakeups/s and up to 50 ms of added TTFT per idle engine;
        it now parks on the submit-wake event (_enqueue sets it after
        every put; stop()/drain() set it too), with a 1 s backstop
        wait in case a wake is ever lost."""
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            if busy:
                return None
        # clear-then-recheck closes the lost-wakeup race: a submit
        # landing between the failed pop and clear() re-sets the
        # event and the second pop sees its request. The backstop
        # wait is bounded well under the stall watchdog so an idle
        # engine's heartbeat never looks wedged to /healthz.
        self._wake.clear()
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            self._wake.wait(
                max(0.05, min(1.0, self._stall_timeout_s / 4.0)))
            return None

    def _admit(self):
        """Fill free slots from the queue (the re-admission deque
        first — transient-faulted and recovery re-admissions were
        accepted earlier than anything still queued). Blocks briefly
        only when the engine is fully idle — with active slots the
        decode loop must keep stepping, so admission is non-blocking.

        Fault contract for one admission: a :class:`TransientFault`
        (injected before any state changed) re-stashes the request and
        propagates so the loop retries with backoff; a
        :class:`CorruptedStateFault` propagates for recompute-recovery
        (re-stashing the request unless it was already failed — the
        attributed-device-failure path fails it inside
        :meth:`_prefill`); anything else fails just this request."""
        if self.cache_backend == "paged":
            return self._admit_paged()
        while self._running and self._slots.free_count:
            if self._requeue:
                req = self._requeue.popleft()
            else:
                req = self._next_queued(
                    busy=bool(self._slots.active_count))
                if req is None:
                    return
                self.metrics.queue_depth = self._queue.qsize()
            if req.abandoned:
                continue
            if self._deadline_blown(req):
                # deadline budget gone while queued: shed at dequeue-
                # admission — zero prefill/decode steps spent on it
                self.metrics.inc("shed_deadline")
                if req.trace is not None:
                    req.trace.span(
                        "admission", verdict="expired",
                        prefill_ms_per_tok=round(
                            self._prefill_ms_per_tok, 4),
                        decode_ewma_ms=round(
                            self._decode_ewma_ms, 3)).end()
                self._fail(req, DeadlineExceededError(
                    "deadline budget exhausted in the generation queue"))
                continue
            try:
                self._prefill(req)
            except TransientFault:
                self._requeue.appendleft(req)
                raise
            except CorruptedStateFault:
                if req.error is None and req.finish_reason is None:
                    self._requeue.appendleft(req)
                raise
            except Exception as e:  # noqa: BLE001 — fail one request
                self._fail(req, e)

    def _chunk_plan(self, prompt_len: int,
                    start: int = 0) -> List[Tuple[int, int, int]]:
        """Split a prompt into (start, chunk bucket, valid length)
        pieces: full ``_chunk_cap`` chunks, then the remainder routed
        to the smallest configured chunk bucket that holds it.
        ``start`` > 0 (a prefix-cache match) skips the matched tokens:
        the first chunk begins mid-prompt, at the same chunk-bucket
        ladder — prefill position is a runtime scalar, so a partial
        plan reuses the exact executables the full plan would."""
        plan = []
        p0 = int(start)
        while p0 < prompt_len:
            rem = prompt_len - p0
            if rem >= self._chunk_cap:
                bucket = clen = self._chunk_cap
            else:
                bucket = next(c for c in self.chunk_buckets if c >= rem)
                clen = rem
            plan.append((p0, bucket, clen))
            p0 += clen
        return plan

    def _match_prefix(self, req: _GenRequest
                      ) -> Tuple[int, List[int], Optional[int],
                                 Optional[str]]:
        """Longest cached prefix of a FRESH admission's prompt →
        ``(match_len, shared_blocks, cow_src, source)``.

        The session store is consulted first (token-granular: the
        pinned turn is almost always a strict prefix of the next
        turn's prompt), then the cross-request index (block-granular
        via chained hashes). ``shared_blocks`` are matched full blocks
        the request will READ through its table; ``cow_src`` is the
        block holding the matched tail when the match ends mid-block —
        the request must WRITE there from position ``match_len`` on,
        so admission copies it into a private block first.
        ``match_len`` is capped at prompt_len - 1: the last prompt
        position must be computed to sample the first output token.
        Recovery re-admissions never match — their block budget and
        token stream are already settled."""
        if not self.enable_prefix_sharing or req.tokens:
            return 0, [], None, None
        bs = self.block_size
        prompt = req.prompt
        L = len(prompt)
        if req.session_id is not None:
            sess = self._sessions.get(req.session_id)
            if sess is not None:
                stored = sess.tokens
                n = min(len(stored), L - 1)
                neq = stored[:n] != prompt[:n]
                m = int(np.argmax(neq)) if neq.any() else n
                if m > 0:
                    self.metrics.inc("session_hits")
                    self.metrics.inc("prefix_hits")
                    self.metrics.inc("prefix_tokens_matched", m)
                    shared = sess.blocks[:m // bs]
                    cow = sess.blocks[m // bs] if m % bs else None
                    return m, list(shared), cow, "session"
            self.metrics.inc("session_misses")
        matched = self._prefix_index.match(chain_hashes(prompt, bs))
        if not matched:
            return 0, [], None, None
        m = len(matched) * bs
        cow = None
        if m >= L:
            # every full block matched and the prompt is block-aligned:
            # keep the last matched block as a COW source so only the
            # final prompt position re-prefills (for its logits)
            m = L - 1
            matched, cow = matched[:m // bs], matched[m // bs]
        self.metrics.inc("prefix_hits")
        self.metrics.inc("prefix_tokens_matched", m)
        return m, list(matched), cow, "index"

    def _evict_one_pin(self) -> bool:
        """Release ONE cache pin under block pressure: the LRU prefix-
        index entry first (one block, finest granularity), then the
        LRU session. False when nothing is evictable — every block is
        held by in-flight work.

        With the hierarchical KV tier enabled, eviction DEMOTES
        instead of discarding: the pin's block run copies device->host
        before its blocks are freed, so the state is a planned cache
        miss (restorable) rather than gone. A torn demotion degrades
        to the old discard — the free below runs either way."""
        ent = self._prefix_index.evict_lru_entry()
        if ent is not None:
            digest, b = ent
            self._demote_prefix(digest, b)
            self._allocator.free([b])
            self.metrics.inc("prefix_evictions")
            return True
        sess = self._sessions.evict_lru()
        if sess is not None:
            self._demote_session(sess)
            self._allocator.free(sess.blocks)
            self.metrics.inc("session_evictions")
            return True
        return False

    def _alloc_with_eviction(self, n: int) -> Optional[List[int]]:
        """All-or-nothing alloc that reclaims cache pins (prefix index
        entries, then sessions) under pressure — in-flight requests
        always outrank opportunistic caching. None only when even a
        fully-evicted pool cannot cover ``n``."""
        while True:
            blocks = self._allocator.alloc(n)
            if blocks is not None:
                return blocks
            if not self._evict_one_pin():
                return None

    def _admit_paged(self):
        """Paged admission: claim a slot AND the request's full
        worst-case block count, all-or-nothing. When blocks run out
        the request is HELD at the queue head (FIFO — admitting later
        arrivals first would starve it) until retirements free blocks;
        the engine never admits work it could fail to finish.
        Admission only STARTS the prefill — chunks run interleaved
        with decode steps in the scheduler loop.

        With prefix sharing, admission first matches the prompt
        against the session store + prefix index: matched full blocks
        join the request's table by refcount (no allocation, no
        prefill), a mid-block match tail is copy-on-write duplicated,
        and the chunk plan starts at the first unmatched token."""
        while self._running and self._slots.free_count:
            if self._requeue:
                req = self._requeue.popleft()
            elif self._held is not None:
                req, self._held = self._held, None
            else:
                req = self._next_queued(
                    busy=bool(self._slots.active_count
                              or self._prefilling))
                if req is None:
                    return
                self.metrics.queue_depth = self._queue.qsize()
            if req.abandoned:
                continue
            if self._deadline_blown(req):
                # deadline budget gone while queued: shed at dequeue-
                # admission — zero prefill/decode steps spent on it
                self.metrics.inc("shed_deadline")
                if req.trace is not None:
                    req.trace.span(
                        "admission", verdict="expired",
                        prefill_ms_per_tok=round(
                            self._prefill_ms_per_tok, 4),
                        decode_ewma_ms=round(
                            self._decode_ewma_ms, 3)).end()
                self._fail(req, DeadlineExceededError(
                    "deadline budget exhausted in the generation queue"))
                continue
            seq = _recovery_seq(req)
            L = len(seq)
            # block budget is unchanged by recovery: prefix + remaining
            # generation == prompt + max_tokens positions either way
            need = blocks_for(len(req.prompt) + req.max_tokens,
                              self.block_size)
            try:
                self._hit("alloc")
            except (TransientFault, CorruptedStateFault):
                # nothing allocated yet — re-stash the request so the
                # retry (or recovery) re-admits it, in order
                self._requeue.appendleft(req)
                raise
            if self._offload is not None:
                # restore-vs-reprefill decision: a demoted session (or
                # demoted prefix blocks) scatters back into the pool
                # BEFORE matching, so _match_prefix sees a normal hit.
                # Torn restores were already degraded to re-prefill
                # inside; only a real device failure escapes (pools
                # donated to the scatter) -> recompute-recovery, with
                # the request re-admitted in order like any other
                # corrupting admission fault
                try:
                    self._offload_restore(req)
                    self._restore_prefix_blocks(req)
                except CorruptedStateFault:
                    self._requeue.appendleft(req)
                    raise
                self._update_block_gauges()
            match_len, shared, cow_src, source = self._match_prefix(req)
            pinned = shared + ([cow_src] if cow_src is not None else [])
            if pinned:
                # pin the matched blocks BEFORE allocating: the alloc
                # below may evict the very index/session entries that
                # own them — without this extra reference an evicted
                # match would re-enter the free list and come back as
                # someone's "fresh" block while this request still
                # reads it
                self._allocator.share(pinned)
            fresh = self._alloc_with_eviction(need - len(shared))
            if fresh is None:
                if pinned:
                    self._allocator.free(pinned)
                if self._held is None:
                    self._held = req
                else:
                    # a different request already waits at the head
                    # for blocks (req came from the re-admission
                    # deque) — it must go back there, NOT overwrite
                    # the held one into oblivion
                    self._requeue.appendleft(req)
                return
            if cow_src is not None:
                # the match ends mid-block: the request must write
                # positions >= match_len into that block, so it gets a
                # private copy (its first fresh block — table index
                # len(shared)) and drops its pin on the original
                try:
                    self._cow(cow_src, fresh[0])
                except Exception as e:  # noqa: BLE001 — pools donated
                    self._requeue.appendleft(req)
                    raise CorruptedStateFault(
                        f"copy-on-write device call failed: {e!r}")
                self._allocator.free([cow_src])
                self.metrics.inc("cow_copies")
            blocks = shared + fresh
            plan = self._chunk_plan(L, start=match_len)
            table = BlockTable(blocks, self.block_size)
            if req.trace is not None and match_len:
                full = sum(b for _, b, _ in self._chunk_plan(L))
                part = sum(b for _, b, _ in plan)
                req.trace.span(
                    "prefix_match", source=source,
                    matched_tokens=match_len,
                    matched_blocks=len(shared),
                    cow=cow_src is not None,
                    saved_est_ms=round(
                        (full - part) * self._prefill_ms_per_tok,
                        3)).end()
            # the table bucket must also cover the LAST chunk's padded
            # tail. Its junk writes stay harmless two ways: rows inside
            # the allocation hit positions beyond the live length of
            # THIS request's own blocks (masked until decode overwrites
            # them at pos before ever unmasking), and rows past the
            # allocation hit padded NULL entries -> the null block.
            # Either way, never another request's blocks — which is
            # exactly what an undersized table would break.
            span = max(len(req.prompt) + req.max_tokens,
                       plan[-1][0] + plan[-1][1])
            tbl_bucket = pow2_bucket(
                blocks_for(span, self.block_size), cap=self._tbl_top)
            slot = self._slots.alloc(req)
            assert slot is not None  # guarded by free_count
            self._slot_blocks[slot] = table
            if req.trace is not None:
                req.qspan.end()  # queue wait ends at the block claim
            self._prefilling.append(
                _ChunkState(req, slot, table, tbl_bucket, plan, seq,
                            start=match_len))
            self.metrics.active_slots = self._slots.active_count
            self._update_block_gauges()

    def _prefill_chunk_step(self):
        """Run ONE prefill chunk for the oldest mid-prefill request —
        the scheduler interleaves these with decode steps, so the
        decode loop's stall per iteration is bounded by one chunk's
        compute regardless of prompt length."""
        st = self._prefilling[0]
        req = st.req
        if req.abandoned:
            self._prefilling.popleft()
            self._release_slot(st.slot)
            return
        if time.perf_counter() > req.deadline:
            self._prefilling.popleft()
            self._release_slot(st.slot)
            self._fail(req, DeadlineExceededError(
                "deadline exceeded during chunked prefill "
                f"({st.done_tokens}/{len(st.seq)} prompt tokens)"))
            return
        # injection seam: BEFORE any mutation — a TransientFault here
        # leaves the chunk state at the deque head, so the retried
        # iteration re-runs this same chunk
        self._hit("prefill")
        p0, bucket, clen = st.plan[st.idx]
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :clen] = st.seq[p0:p0 + clen]
        table = st.table.padded(st.tbl_bucket)
        c0 = self.metrics.compiles
        t0 = time.perf_counter()
        try:
            exe = self._get_chunk_exe(bucket, st.tbl_bucket)
        except Exception as e:  # noqa: BLE001 — compile failed BEFORE
            # any donation: only this request is affected
            self._prefilling.popleft()
            self._release_slot(st.slot)
            self._fail(req, e)
            return
        try:
            with self._profiler.record("generation.prefill"):
                first, okd, self._kcs, self._vcs = exe(
                    self.model._params, self._kcs, self._vcs, tokens,
                    np.int32(p0), np.int32(clen), table,
                    np.uint32(req.seed), np.float32(req.temperature),
                    np.int32(req.top_k))
                first = int(np.asarray(first))  # device sync
                ok = bool(np.asarray(okd))
        except Exception as e:  # noqa: BLE001 — the call died with the
            # pools donated: attribute the failure to THIS request
            # (fail it alone), then let the loop recompute-recover
            # every other in-flight sequence's lost prefix
            self._prefilling.popleft()
            self._release_slot(st.slot)
            self._fail(req, e)
            raise CorruptedStateFault(
                f"prefill chunk device call failed: {e!r}")
        t1 = time.perf_counter()
        dt_ms = (t1 - t0) * 1e3
        self.metrics.prefill_ms.record(dt_ms)
        if self.metrics.compiles == c0:
            # a sample that paid a lazy compile would poison the
            # cost-admission estimate for thousands of requests
            self._note_prefill_cost(dt_ms, bucket)
        if req.trace is not None:
            req.trace.span("prefill", t_start=t0, t_end=t1,
                           bucket=bucket, chunk=st.idx,
                           chunks=len(st.plan))
        self.metrics.inc("prefill_chunks")
        self.metrics.inc("prefill_tokens", clen)
        self.metrics.prompt_bucket_hist.record(bucket)
        if not ok:
            # poison quarantine: this request's own tokens drove the
            # logits non-finite — fail it alone, free its blocks now
            self._prefilling.popleft()
            self._release_slot(st.slot)
            self.metrics.inc("quarantined")
            self._fail(req, PoisonRequestError(
                "request produced non-finite logits during prefill; "
                "quarantined"))
            return
        st.idx += 1
        if st.idx < len(st.plan):
            return
        # final chunk: the request becomes a decode lane. Fresh
        # admission: its sampled token is generated token #1 (TTFT
        # stops here). Recovery re-admission: the already-emitted
        # stream stands — restore the decode cursor (last token, pos,
        # PRNG fold index) instead of emitting; the re-sampled first
        # token is discarded.
        self._prefilling.popleft()
        self.metrics.inc("prefills")
        if len(st.plan) > 1:
            self.metrics.inc("chunked_prefills")
        L = len(st.seq)
        slots = self._slots
        resumed = bool(req.tokens)
        slots.token[st.slot] = req.tokens[-1] if resumed else first
        slots.pos[st.slot] = L
        slots.step[st.slot] = len(req.tokens) if resumed else 1
        slots.seed[st.slot] = req.seed
        slots.temp[st.slot] = req.temperature
        slots.top_k[st.slot] = req.top_k
        slots.eos[st.slot] = -1 if req.eos_id is None else req.eos_id
        slots.max_steps[st.slot] = req.max_tokens
        # the lane's current token was just written host-side — the
        # next dispatch must feed it from tok_host, not the device
        self._tok_on_dev[st.slot] = False
        if req.pipe_d0 is None:
            req.pipe_d0 = self._step_span_s
            req.pipe_w0 = self._sync_wait_s
        self._tables[st.slot] = st.table.padded(self._blocks_per_seq)
        if self.enable_prefix_sharing and not resumed:
            # the prompt's full blocks now hold finished, immutable
            # K/V (decode writes land at pos >= prompt_len): publish
            # them for cross-request reuse
            self._register_prefix(req, st.table)
        self._update_block_gauges()
        if self.speculation_k:
            # decode entry: prime the draft over the whole committed
            # prefix. The DRAFT always prefills from scratch — prefix
            # sharing may have skipped most of the target's prefill,
            # but the draft shares nothing
            self._spec_prime(st.slot, st.seq)
        if resumed:
            return
        self.metrics.tokens.record(1)
        self._emit(req, first, time.perf_counter())
        self._check_done(st.slot, req, first)

    def _register_prefix(self, req: _GenRequest, table: BlockTable):
        """Publish a freshly-prefilled prompt's FULL blocks into the
        prefix index. A newly-registered block gains one reference
        owned by the index (so it outlives the request); a digest
        already present keeps its existing block — identical content,
        and the old block may be mid-read by other tables."""
        n_full = len(req.prompt) // self.block_size
        if not n_full:
            return
        hashes = chain_hashes(req.prompt, self.block_size)
        for h, b in zip(hashes, table.blocks[:n_full]):
            if self._prefix_index.register(h, b):
                self._allocator.share([b])
        evicted = self._prefix_index.evict_over_capacity()
        if evicted:
            self._allocator.free(evicted)
            self.metrics.inc("prefix_evictions", len(evicted))

    def _pin_session(self, slot: int, req: _GenRequest):
        """Transfer a cleanly-finished request's live blocks to the
        session store instead of freeing them. The store inherits the
        request's own reference on the kept blocks (ownership moves,
        refcounts don't); trailing blocks past the K/V-valid prefix
        (prompt + emitted minus the last token, whose K/V was never
        written) are freed now. Mirrors :meth:`_release_slot`'s slot
        bookkeeping."""
        table = self._slot_blocks[slot]
        seq = _recovery_seq(req)  # the K/V-valid token prefix
        keep = blocks_for(len(seq), self.block_size)
        kept, trailing = table.blocks[:keep], table.blocks[keep:]
        if trailing:
            self._allocator.free(trailing)
        displaced = self._sessions.put(req.session_id, seq, kept)
        evictions = 0
        for sess in displaced:
            if sess.session_id == req.session_id:
                # the same session's superseded pin: the new pin is
                # the truth, nothing to demote
                self._allocator.free(sess.blocks)
            else:
                # LRU displacement: demote to the host tier (or
                # discard if demotion tears), then free
                self._demote_session(sess)
                self._allocator.free(sess.blocks)
                evictions += 1
        if evictions:
            self.metrics.inc("session_evictions", evictions)
        if self._offload is not None:
            # the freshly-pinned device copy supersedes any demoted
            # one — a stale host run must never be restored over it
            self._offload.pop(req.session_id)
            if self._offload_prefetcher is not None:
                self._offload_prefetcher.discard(req.session_id)
        self._slots.free(slot)
        self._slot_blocks[slot] = None
        self._tables[slot] = NULL_BLOCK
        self._update_block_gauges()
        self.metrics.active_slots = self._slots.active_count

    def _poison(self, why: str):
        """LAST RESORT (recovery itself failed): every in-flight
        sequence lost its prefix and cannot be rebuilt. Fail them all
        loudly (silently decoding from a zeroed cache would be worse)
        and reallocate so the engine stays servable."""
        for slot in self._slots.active_slots():
            req = self._slots.requests[slot]
            self._slots.free(slot)
            self._fail(req, ServingError(f"generation step failed: "
                                         f"{why}"))
        self.metrics.active_slots = 0
        self._drop_pending()
        if self.cache_backend == "paged":
            # mid-prefill requests hold slots too, so they were failed
            # above; reset the block bookkeeping wholesale — including
            # the prefix/session pins, whose K/V went with the pools
            self._prefilling.clear()
            self._allocator = BlockAllocator(self.num_blocks)
            self._tables[:] = NULL_BLOCK
            self._slot_blocks = [None] * self.num_slots
            self._prefix_index.clear()
            self._sessions.clear()
            # the HOST tier deliberately survives: demoted runs are
            # host numpy, independent of the donated-away device
            # pools, so previously-demoted sessions stay restorable
            # after the rebuild
            self._update_block_gauges()
        self._cache = self._fresh_cache()
        self._kcs = self._cache.ks
        self._vcs = self._cache.vs
        if self.speculation_k:
            self._reset_draft_cache()

    def _recover(self, why: str):
        """Recompute-recovery (the vLLM preempt-and-recompute insight:
        decode state is CHEAP to rebuild — it is a pure function of
        prompt + emitted tokens). After a cache-corrupting failure,
        every in-flight request is re-admitted at the FRONT of the
        line and re-prefilled from prompt + already-emitted tokens;
        its PRNG stream continues at ``fold_in(seed, len(emitted))``,
        so post-recovery output is token-identical to a fault-free
        run and NO accepted request is ever lost. Only requests that
        keep triggering recoveries (``max_recoveries_per_request``) or
        age past their deadline are failed."""
        recovered: List[_GenRequest] = []
        st = self._slots
        for slot in st.active_slots():
            recovered.append(st.requests[slot])
            st.free(slot)
        self.metrics.active_slots = 0
        # any in-flight pipelined step died with the caches; its
        # tokens were never emitted, so the recovery replay below
        # regenerates them bit-identically (same PRNG fold indices)
        self._drop_pending()
        if self.cache_backend == "paged":
            # mid-prefill requests hold slots too, so the slot sweep
            # above already collected them EXACTLY once (collecting
            # from _prefilling as well would re-admit them twice);
            # they re-prefill from scratch — req.tokens carries
            # whatever they had already emitted. Block bookkeeping
            # resets wholesale: the pool arrays were donated away with
            # the caches.
            self._prefilling.clear()
            self._allocator = BlockAllocator(self.num_blocks)
            self._tables[:] = NULL_BLOCK
            self._slot_blocks = [None] * self.num_slots
            # cached prefixes and session pins died with the pools:
            # drop the bookkeeping (no frees — the allocator is new)
            # so post-recovery admissions rebuild refcounts from zero
            # instead of matching blocks whose K/V no longer exists.
            # The HOST tier survives on purpose — demoted runs are
            # host numpy, untouched by device donation, so sessions
            # demoted BEFORE the fault still restore afterwards
            self._prefix_index.clear()
            self._sessions.clear()
        self._cache = self._fresh_cache()
        self._kcs = self._cache.ks
        self._vcs = self._cache.vs
        if self.speculation_k:
            # the draft cache may hold donated-away device state too;
            # it replays nothing — each re-admitted lane re-primes at
            # its decode entry (spec_ok was cleared with the slots)
            self._reset_draft_cache()
        now = time.perf_counter()
        for req in recovered:
            if req.abandoned:
                continue
            if now > req.deadline:
                self._fail(req, DeadlineExceededError(
                    "deadline exceeded during fault recovery "
                    f"({len(req.tokens)} tokens emitted)"))
            elif req.recoveries >= self._max_recoveries:
                # a request that rides every crash is probably causing
                # them — attribution of last resort
                self._fail(req, ServingError(
                    f"request failed {req.recoveries} recovery "
                    f"attempts: {why}"))
            else:
                req.recoveries += 1
                if req.trace is not None:
                    req.trace.span("recovery", why=why,
                                   tokens_kept=len(req.tokens)).end()
                self._requeue.append(req)
        if self.cache_backend == "paged":
            self._update_block_gauges()

    def _prefill(self, req: _GenRequest):
        # injection seam: BEFORE the slot claim, so a TransientFault
        # leaves nothing to unwind — _admit re-stashes the request and
        # the loop retries with backoff
        self._hit("prefill")
        if req.trace is not None:
            req.qspan.end()  # queue wait ends at the slot claim
        resumed = bool(req.tokens)
        seq = _recovery_seq(req)
        slot = self._slots.alloc(req)
        assert slot is not None  # guarded by free_count in _admit
        L = len(seq)
        # route to the smallest CONFIGURED bucket, not the raw pow2
        # ladder — warmup() covered exactly prompt_buckets, and an
        # off-list bucket here would compile under traffic. Recovery
        # prefixes fit too: prompt + emitted <= max_seq_len, and
        # max_seq_len is always a bucket.
        bucket = next(b for b in self.prompt_buckets if b >= L)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :L] = seq
        c0 = self.metrics.compiles
        t0 = time.perf_counter()
        try:
            exe = self._get_prefill_exe(bucket)
        except Exception:
            # compile failed BEFORE any donation: only this request is
            # affected — free its slot and let the caller fail it
            self._release_slot(slot)
            raise
        try:
            with self._profiler.record("generation.prefill"):
                first, okd, self._kcs, self._vcs = exe(
                    self.model._params, self._kcs, self._vcs, tokens,
                    np.int32(L), np.int32(slot), np.uint32(req.seed),
                    np.float32(req.temperature), np.int32(req.top_k))
                first = int(np.asarray(first))  # device sync
                ok = bool(np.asarray(okd))
        except Exception as e:
            # the call itself died mid-flight with the caches donated:
            # attribute the failure to THIS request (fail it alone),
            # then raise for recompute-recovery of everyone else
            self._release_slot(slot)
            self._fail(req, e)
            raise CorruptedStateFault(
                f"prefill device call failed: {e!r}")
        t1 = time.perf_counter()
        dt_ms = (t1 - t0) * 1e3
        self.metrics.prefill_ms.record(dt_ms)
        if self.metrics.compiles == c0:
            # a sample that paid a lazy compile would poison the
            # cost-admission estimate for thousands of requests
            self._note_prefill_cost(dt_ms, bucket)
        if req.trace is not None:
            req.trace.span("prefill", t_start=t0, t_end=t1,
                           bucket=bucket, chunks=1, resumed=resumed)
        self.metrics.inc("prefills")
        self.metrics.prompt_bucket_hist.record(bucket)
        if not ok:
            # poison quarantine: only this request's logits are
            # non-finite — fail it alone with 500, free the slot now.
            # Its NaN K/V rows stay in the cache but are stale-tail
            # data the no-zeroing invariant already masks.
            self._release_slot(slot)
            self.metrics.inc("quarantined")
            self._fail(req, PoisonRequestError(
                "request produced non-finite logits during prefill; "
                "quarantined"))
            return
        st = self._slots
        st.token[slot] = req.tokens[-1] if resumed else first
        st.pos[slot] = L          # where the next token's K/V will go
        st.step[slot] = len(req.tokens) if resumed else 1  # PRNG fold
        st.seed[slot] = req.seed
        st.temp[slot] = req.temperature
        st.top_k[slot] = req.top_k
        st.eos[slot] = -1 if req.eos_id is None else req.eos_id
        st.max_steps[slot] = req.max_tokens
        # the lane's current token was just written host-side — the
        # next dispatch must feed it from tok_host, not the device
        self._tok_on_dev[slot] = False
        if req.pipe_d0 is None:
            req.pipe_d0 = self._step_span_s
            req.pipe_w0 = self._sync_wait_s
        if self.speculation_k:
            self._spec_prime(slot, seq)
        self.metrics.active_slots = st.active_count
        if resumed:
            # the emitted stream stands — the re-sampled first token is
            # discarded; decode continues at fold_in(seed, step), the
            # same stream position a fault-free run would use
            return
        # prefill's own sampled token is generated token #1
        self.metrics.tokens.record(1)
        self._emit(req, first, time.perf_counter())
        self._check_done(slot, req, first)

    def _note_prefill_cost(self, dt_ms: float, bucket: int):
        """Feed the per-PROMPT-TOKEN prefill EWMA (scheduler thread
        only). Normalized by the padded bucket width — that is what
        the device call actually computed over."""
        per_tok = dt_ms / max(bucket, 1)
        self._prefill_ms_per_tok = per_tok \
            if not self._prefill_ms_per_tok else \
            0.8 * self._prefill_ms_per_tok + 0.2 * per_tok

    # -- speculative decoding (serving/speculative.py) -----------------
    def _spec_prime(self, slot: int, seq: np.ndarray):
        """Prefill the DRAFT over a lane's committed prefix at decode
        entry, marking the lane speculation-eligible on success. Any
        draft-side failure here — compile, device call, non-finite
        draft logits — costs speculation only, never the request: the
        lane (or, after a donation-destroying call failure, every
        lane until re-primed) simply decodes plainly."""
        seq = np.asarray(seq, np.int32)
        L = len(seq)
        bucket = next(b for b in self._prime_buckets if b >= L)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :L] = seq
        try:
            ok, self._draft_kcs, self._draft_vcs = \
                self._get_draft_prime_exe(bucket)(
                    self._draft._params, self._draft_kcs,
                    self._draft_vcs, tokens, np.int32(L),
                    np.int32(slot))
            ok = bool(np.asarray(ok))
        except Exception:  # noqa: BLE001 — draft caches were donated
            # to the dead call: rebuild them; the target is untouched
            self._reset_draft_cache(disable_lanes=True)
            self.metrics.inc("spec_draft_fallbacks")
            return
        self._slots.spec_ok[slot] = ok
        if not ok:
            self.metrics.inc("spec_draft_fallbacks")

    def _spec_cow_guard(self, slot: int, p0: int) -> bool:
        """Copy-on-write isolation BEFORE any speculative write: a
        verify span scatters K/V across ``[p0, p0 + vbucket)`` (plus
        the passenger decode write at ``p0 + emitted``), and none of
        those positions may land in a block other tables still read.
        Today's sharing paths only ever share prompt-prefix blocks —
        always below a decode cursor — but the guard is cheap
        (refcount loads) and makes speculation safe against ANY future
        sharing pattern. False = could not isolate (pool exhausted):
        the caller skips speculation for this lane this round."""
        table = self._slot_blocks[slot]
        bs = self.block_size
        last = min((p0 + self._vbucket) // bs, len(table.blocks) - 1)
        for i in range(p0 // bs, last + 1):
            b = table.blocks[i]
            if self._allocator.ref(b) <= 1:
                continue
            fresh = self._alloc_with_eviction(1)
            if fresh is None:
                return False
            try:
                self._cow(b, fresh[0])
            except Exception as e:  # noqa: BLE001 — pools donated
                raise CorruptedStateFault(
                    f"speculative COW device copy failed: {e!r}")
            self._allocator.free([b])
            table.blocks[i] = fresh[0]
            self._tables[slot] = table.padded(self._blocks_per_seq)
            self.metrics.inc("cow_copies")
        return True

    def _spec_step(self) -> frozenset:
        """One speculative round: ONE batched draft call proposes k
        tokens for every eligible lane, then each lane's proposals are
        verified in ONE target forward over the chunk-ladder kernels.
        Returns the slots whose cursors this round advanced (the plain
        decode step skips them). Acceptance, rollback, and the
        bit-identity contract live in `serving/speculative.py`."""
        st = self._slots
        k = self.speculation_k
        lanes = []
        for s in self._ready_slots():
            if not st.spec_ok[s]:
                continue
            req = st.requests[s]
            # a lane within k tokens of its budget plain-decodes to
            # the finish line: every verify span then has full width,
            # and speculative writes can never run past the lane's
            # block allocation / slot capacity
            if req.max_tokens - len(req.tokens) >= k + 1:
                lanes.append(s)
        if not lanes:
            return frozenset()
        # -- draft: one batched proposal call for all lanes ---------
        t0 = time.perf_counter()
        try:
            # the injection seam lives INSIDE the except scope: any
            # draft-side fault — injected or real, transient or
            # corrupting — costs speculation only, never a recovery
            self._hit("draft")
            with self._profiler.record("generation.spec_draft"):
                props, dok, self._draft_kcs, self._draft_vcs = \
                    self._get_draft_exe()(
                        self._draft._params, self._draft_kcs,
                        self._draft_vcs, st.token.copy(),
                        st.pos.copy())
                props = np.asarray(props)
                dok = np.asarray(dok)
        except Exception:  # noqa: BLE001 — the draft call died with
            # ITS OWN caches donated; the target state is intact, so
            # this costs speculation (until lanes re-prime), never
            # recovery and never a request
            self._reset_draft_cache(disable_lanes=True)
            self.metrics.inc("spec_draft_fallbacks", len(lanes))
            return frozenset()
        t1 = time.perf_counter()
        # -- verify: one target forward per lane --------------------
        vb = self._vbucket
        paged = self.cache_backend == "paged"
        serviced = set()
        emitted = 0
        itl: List[float] = []
        for s in lanes:
            req = st.requests[s]
            if not dok[s]:
                # draft NaN: fail ONLY speculation for this lane — it
                # decodes plainly from here on (re-primes on recovery)
                st.spec_ok[s] = False
                self.metrics.inc("spec_draft_fallbacks")
                continue
            p0 = int(st.pos[s])
            tokens = np.zeros((1, vb), np.int32)
            tokens[0, 0] = st.token[s]
            tokens[0, 1:k + 1] = props[s, :k]
            if paged:
                if not self._spec_cow_guard(s, p0):
                    continue
                table = self._slot_blocks[s]
                # the padded table must COVER the span's padded tail:
                # an out-of-range gather clamps to the table's last
                # entry — a real block — so junk rows would otherwise
                # write into live data
                tv = pow2_bucket(
                    max(blocks_for(p0 + vb, self.block_size),
                        len(table.blocks)), cap=self._tbl_top)
                extra = (table.padded(tv),)
            else:
                extra = (np.int32(s),)
            self._hit("verify")
            v0 = time.perf_counter()
            try:
                with self._profiler.record("generation.spec_verify"):
                    tgt, n_acc, vok, self._kcs, self._vcs = \
                        self._get_verify_exe(tv if paged else None)(
                            self.model._params, self._kcs, self._vcs,
                            tokens, np.int32(p0), np.int32(k + 1),
                            *extra, np.uint32(req.seed),
                            np.int32(st.step[s]),
                            np.float32(req.temperature),
                            np.int32(req.top_k))
                    tgt = np.asarray(tgt)
                    n_acc = int(np.asarray(n_acc))
                    vok = bool(np.asarray(vok))
            except Exception as e:  # noqa: BLE001 — the TARGET pools
                # were donated to the dead call: same attribution as a
                # failed prefill chunk — fail this request alone, then
                # recompute-recover everyone else
                self._release_slot(s)
                self._fail(req, e)
                raise CorruptedStateFault(
                    f"speculative verify device call failed: {e!r}")
            v1 = time.perf_counter()
            if not vok:
                # the TARGET's logits went non-finite on this lane's
                # own tokens: the standard poison quarantine, exactly
                # as a plain decode step would rule
                self.metrics.inc("quarantined")
                exc = PoisonRequestError(
                    "request produced non-finite logits during "
                    f"speculative verify at step {int(st.step[s])}; "
                    "quarantined")
                self._release_slot(s)
                self._fail(req, exc)
                continue
            n_emit = n_acc + 1
            self.metrics.inc("spec_verify_batches")
            self.metrics.inc("spec_draft_tokens_proposed", k)
            self.metrics.inc("spec_draft_tokens_accepted", n_acc)
            if n_acc < k:
                # rejected tail: rolled back by NOT committing it —
                # the draft cursor and the target write position both
                # rewind for free because pos is the only commit
                # pointer and stale K/V past it stays masked
                self.metrics.inc("spec_rollbacks")
            req.spec_rounds += 1
            req.spec_proposed += k
            req.spec_accepted += n_acc
            req.spec_emitted += n_emit
            if req.spec_dt0 is None:
                req.spec_dt0 = t0
            req.spec_dt1 = t1
            if req.spec_vt0 is None:
                req.spec_vt0 = v0
            req.spec_vt1 = v1
            serviced.add(s)
            committed = 0
            last_tok = 0
            done = False
            for j in range(n_emit):
                token = int(tgt[j])
                self._emit(req, token, v1, itl_out=itl)
                emitted += 1
                committed += 1
                last_tok = token
                if self._check_done(s, req, token, v1):
                    done = True
                    break
            if not done:
                st.commit(s, last_tok, committed)
        if emitted:
            self.metrics.tokens.record(emitted)
        if itl:
            self.metrics.itl_ms.record_many(itl)
        if paged:
            self._update_block_gauges()
        return frozenset(serviced)

    def _ready_slots(self) -> List[int]:
        """Slots in the DECODE phase. On the paged backend a slot is
        claimed at admission but only decode-ready after its final
        prefill chunk (step > 0); mid-prefill slots ride the decode
        batch as masked lanes (NULL tables — their writes land in the
        null block) and their sampled junk is never read."""
        st = self._slots
        return [s for s in range(self.num_slots)
                if st.requests[s] is not None and st.step[s] > 0]

    def _decode_step(self, skip=frozenset()):
        """One plain decode step. ``skip`` holds slots a speculative
        round already advanced this iteration: they ride the batch as
        masked passengers (the executable's shape is the full slot
        panel either way) and their lane results are simply not
        applied — the passenger's one K/V write lands at the position
        the NEXT verify span rewrites before attending, so it leaves
        no observable residue."""
        st = self._slots
        active = [s for s in self._ready_slots() if s not in skip]
        if not active:
            return
        # injection seam: BEFORE the device call (and its donation), so
        # a TransientFault here is retryable with all state intact
        self._hit("device_step")
        c0 = self.metrics.compiles
        t0 = time.perf_counter()
        with self._profiler.record("generation.decode_step"):
            if self.cache_backend == "paged":
                nxt, okd, dnd, self._kcs, self._vcs = \
                    self._get_decode_exe()(
                        self.model._params, self._kcs, self._vcs,
                        st.token.copy(), self._no_dev_tok,
                        self._all_host, st.pos.copy(),
                        self._tables.copy(), st.seed.copy(),
                        st.step.copy(), st.temp.copy(),
                        st.top_k.copy(), st.eos.copy(),
                        st.max_steps.copy())
            else:
                nxt, okd, dnd, self._kcs, self._vcs = \
                    self._get_decode_exe()(
                        self.model._params, self._kcs, self._vcs,
                        st.token.copy(), self._no_dev_tok,
                        self._all_host, st.pos.copy(), st.seed.copy(),
                        st.step.copy(), st.temp.copy(),
                        st.top_k.copy(), st.eos.copy(),
                        st.max_steps.copy())
            nxt = np.asarray(nxt)  # device sync: the step really ran
            ok = np.asarray(okd)
            done = np.asarray(dnd)
        now = time.perf_counter()
        dt_ms = (now - t0) * 1e3
        self.metrics.decode_step_ms.record(dt_ms)
        # feed the cost-aware-admission EWMA (scheduler thread only) —
        # but never from a sample that paid a lazy compile, which
        # would poison the estimate for thousands of requests
        if self.metrics.compiles == c0:
            self._decode_ewma_ms = dt_ms if not self._decode_ewma_ms \
                else 0.8 * self._decode_ewma_ms + 0.2 * dt_ms
        self.metrics.inc("decode_steps")
        self.metrics.occupancy_hist.record(len(active))
        tokens = nxt.tolist()
        flags = done.tolist()
        emitted = 0
        itl: List[float] = []
        for slot in active:
            req = st.requests[slot]
            if not ok[slot]:
                # poison quarantine: only THIS lane's logits are
                # non-finite (the guard is per-row, sampling is
                # per-row) — fail the offending request with 500 and
                # free its slot/blocks immediately; every other lane
                # in this same batch keeps decoding untouched
                self.metrics.inc("quarantined")
                exc = PoisonRequestError(
                    "request produced non-finite logits at decode "
                    f"step {int(st.step[slot])}; quarantined")
                self._release_slot(slot)  # zeroes the slot row — build
                self._fail(req, exc)      # the message first
                continue
            token = tokens[slot]
            st.token[slot] = token
            st.pos[slot] += 1
            st.step[slot] += 1
            self._emit(req, token, now, itl_out=itl)
            emitted += 1
            self._retire(slot, req, token, flags[slot], now)
        # count only tokens actually delivered — a quarantined lane
        # emitted nothing, and pre-counting len(active) would inflate
        # tokens/sec under poison load
        if emitted:
            self.metrics.tokens.record(emitted)
        if itl:
            self.metrics.itl_ms.record_many(itl)
        if self.cache_backend == "paged":
            self._update_block_gauges()

    def _dispatch_decode(self) -> bool:
        """Launch one decode step WITHOUT waiting for its results (the
        pipelined half of ISSUE 14). The sampled-token array stays on
        the device and feeds the NEXT dispatch directly (tok_dev);
        pos/step are pure +1 increments the host advances immediately,
        so the next step's inputs never depend on anything the sync
        would deliver. Donation already serializes device execution in
        program order — a later prefill or chunk can never overtake
        this step on the device."""
        st = self._slots
        active = self._ready_slots()
        if not active:
            return False
        # injection seam: BEFORE the device call (and its donation), so
        # a TransientFault here is retryable with all state intact —
        # the not-yet-collected previous step stays queued
        self._hit("device_step")
        c0 = self.metrics.compiles
        tok_dev = self._nxt_dev
        if tok_dev is None:
            tok_dev = self._no_dev_tok
        use_host = ~self._tok_on_dev
        t0 = time.perf_counter()
        if self.cache_backend == "paged":
            nxt, okd, dnd, self._kcs, self._vcs = self._get_decode_exe()(
                self.model._params, self._kcs, self._vcs,
                st.token.copy(), tok_dev, use_host, st.pos.copy(),
                self._tables.copy(), st.seed.copy(), st.step.copy(),
                st.temp.copy(), st.top_k.copy(), st.eos.copy(),
                st.max_steps.copy())
        else:
            nxt, okd, dnd, self._kcs, self._vcs = self._get_decode_exe()(
                self.model._params, self._kcs, self._vcs,
                st.token.copy(), tok_dev, use_host, st.pos.copy(),
                st.seed.copy(), st.step.copy(), st.temp.copy(),
                st.top_k.copy(), st.eos.copy(), st.max_steps.copy())
        self._nxt_dev = nxt
        self._tok_on_dev[:] = False
        self._tok_on_dev[active] = True
        # batched cursor bookkeeping: two vectorized adds, no per-lane
        # Python in the dispatch path
        st.pos[active] += 1
        st.step[active] += 1
        self.metrics.inc("decode_steps")
        self.metrics.occupancy_hist.record(len(active))
        self._pending.append(
            (nxt, okd, dnd, [(s, st.requests[s]) for s in active],
             t0, c0))
        return True

    def _collect_decode(self, keep: int = 0):
        """Sync and apply in-flight decode steps, oldest first, until
        only ``keep`` remain (keep=1 right after a dispatch: the new
        step stays in flight while THIS host work overlaps it — that
        overlap is the entire point of the pipeline). The sync is the
        only blocking point; everything after runs off host arrays."""
        st = self._slots
        while len(self._pending) > keep:
            nxt_d, okd, dnd, lanes, t0, c0 = self._pending.popleft()
            t_wait = time.perf_counter()
            nxt = np.asarray(nxt_d)  # device sync: the step really ran
            ok = np.asarray(okd)
            done = np.asarray(dnd)
            now = time.perf_counter()
            span_s = now - t0         # dispatch -> results on host
            wait_s = now - t_wait     # how long the host BLOCKED
            self._profiler.note("generation.decode_step", span_s)
            self._step_span_s += span_s
            self._sync_wait_s += wait_s
            dt_ms = span_s * 1e3
            self.metrics.decode_step_ms.record(dt_ms)
            self.metrics.decode_sync_wait_ms.record(wait_s * 1e3)
            if self.metrics.compiles == c0:
                self._decode_ewma_ms = dt_ms \
                    if not self._decode_ewma_ms \
                    else 0.8 * self._decode_ewma_ms + 0.2 * dt_ms
            tokens = nxt.tolist()
            flags = done.tolist()
            emitted = 0
            itl: List[float] = []
            for slot, req in lanes:
                if st.requests[slot] is not req \
                        or req.finish_reason is not None \
                        or req.error is not None:
                    # the lane retired (or its slot changed hands)
                    # while this step was in flight: its junk write
                    # landed past the retired sequence's valid length
                    # — masked and later overwritten, per the
                    # no-zeroing invariant — and its sampled token is
                    # simply never read
                    continue
                if not ok[slot]:
                    # poison quarantine, same contract as the
                    # synchronous path
                    self.metrics.inc("quarantined")
                    exc = PoisonRequestError(
                        "request produced non-finite logits at decode "
                        f"step {int(st.step[slot])}; quarantined")
                    self._release_slot(slot)
                    self._fail(req, exc)
                    continue
                token = tokens[slot]
                # backfill the host mirror; the NEXT step's input (if
                # already dispatched) came from tok_dev, not this
                st.token[slot] = token
                self._emit(req, token, now, itl_out=itl)
                emitted += 1
                self._retire(slot, req, token, flags[slot], now)
            if emitted:
                self.metrics.tokens.record(emitted)
            if itl:
                self.metrics.itl_ms.record_many(itl)
            if self.cache_backend == "paged":
                self._update_block_gauges()

    def _drop_pending(self):
        """Discard in-flight pipelined state (recovery/poison/stop:
        the device buffers it refers to are gone or about to be).
        Nothing from a dropped step was ever emitted, so a recovery
        replay regenerates the same tokens from the same PRNG folds."""
        self._pending.clear()
        self._nxt_dev = None
        self._tok_on_dev[:] = False

    def _loop(self):
        """The supervised scheduler loop. One iteration = admit, one
        prefill chunk (paged), one decode step. Failure ladder:

        - :class:`~.faults.TransientFault` (raised before any
          donation): retry the iteration with bounded exponential
          backoff, up to ``max_step_retries`` consecutive strikes.
        - strikes exhausted, :class:`~.faults.CorruptedStateFault`, or
          ANY other exception (a device call dying after the caches
          were donated): recompute-recovery via :meth:`_recover`.
        - recovery itself failing: :meth:`_poison` (fail all in-flight
          loudly, reallocate, keep serving).

        The loop itself never dies to a fault — the heartbeat
        (``/healthz`` watchdog) goes stale only when an iteration
        genuinely hangs."""
        paged = self.cache_backend == "paged"
        backoff = self._retry_backoff_s
        strikes = 0
        while self._running:
            self._beat = time.monotonic()
            try:
                self._hit("latency")  # injected tail latency (sleeps)
                self._admit()
                if paged and self._prefilling:
                    self._prefill_chunk_step()
                if self.decode_pipeline:
                    # dispatch step t+1 FIRST, then collect step t:
                    # the admit/prefill work above and the emit/retire
                    # work inside the collect all overlap the device
                    # computing the step just dispatched
                    launched = self._dispatch_decode()
                    self._collect_decode(keep=1 if launched else 0)
                elif self._ready_slots():
                    # speculative round first (no-op at k=0); lanes it
                    # advanced sit out the plain step that finishes
                    # everyone else
                    spun = (self._spec_step() if self.speculation_k
                            else frozenset())
                    self._decode_step(skip=spun)
            except TransientFault as e:
                strikes += 1
                if strikes > self._max_step_retries:
                    # bounded give-up: rebuild rather than spin forever
                    self.metrics.inc("recoveries")
                    try:
                        self._recover(f"retries exhausted: {e!r}")
                    except Exception as e2:  # noqa: BLE001
                        self._poison(repr(e2))
                    strikes = 0
                    backoff = self._retry_backoff_s
                else:
                    self.metrics.inc("retries")
                    time.sleep(backoff)
                    backoff = min(backoff * 2.0,
                                  self._retry_backoff_max_s)
            except Exception as e:  # noqa: BLE001 — cache-corrupting
                # (donated buffers gone) or an unexpected scheduler
                # error: rebuild all in-flight state by recompute
                self.metrics.inc("recoveries")
                try:
                    self._recover(repr(e))
                except Exception as e2:  # noqa: BLE001
                    self._poison(repr(e2))
                strikes = 0
                backoff = self._retry_backoff_s
            else:
                strikes = 0
                backoff = self._retry_backoff_s
        # shutdown cleanup runs HERE, on the scheduler thread — stop()
        # must not mutate the slot table from another thread while a
        # final device call might still be in flight
        self._drop_pending()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            self._fail(req, ServingError("generation engine stopped"),
                       count=False)
        for req in self._requeue:
            self._fail(req, ServingError("generation engine stopped"),
                       count=False)
        self._requeue.clear()
        if paged:
            self._prefilling.clear()  # their slots drain just below
            if self._held is not None:
                self._fail(self._held,
                           ServingError("generation engine stopped"),
                           count=False)
                self._held = None
        for slot in self._slots.active_slots():
            req = self._slots.requests[slot]
            self._slots.free(slot)
            self._fail(req, ServingError("generation engine stopped"),
                       count=False)
        self.metrics.active_slots = 0

    # -- admin ---------------------------------------------------------
    def stats(self) -> Dict:
        return self.metrics.snapshot()

    def evict_sessions(self) -> int:
        """Release every session pin, returning how many sessions were
        evicted. The session store is scheduler-thread state — call
        only on an idle/drained engine (tests, admin maintenance), not
        under traffic."""
        if self.cache_backend != "paged":
            return 0
        sessions = self._sessions.clear()
        for sess in sessions:
            self._allocator.free(sess.blocks)
        if sessions:
            self.metrics.inc("session_evictions", len(sessions))
        self._update_block_gauges()
        return len(sessions)

    def offload_sessions(self) -> int:
        """Demote EVERY session pin to the host tier (freeing its
        device blocks), returning how many demoted cleanly. The bulk
        version of demote-on-evict — admin maintenance before a
        planned restart, or tests forcing the cold path. Same
        idle-engine-only contract as :meth:`evict_sessions`."""
        if self.cache_backend != "paged" or self._offload is None:
            return 0
        sessions = self._sessions.clear()
        demoted = 0
        for sess in sessions:
            if self._demote_session(sess):
                demoted += 1
            self._allocator.free(sess.blocks)
        if sessions:
            self.metrics.inc("session_evictions", len(sessions))
        self._update_block_gauges()
        return demoted

    def clear_offload(self) -> int:
        """Drop every demoted run from the host AND disk tiers,
        returning how many runs were discarded. Sessions fall back to
        re-prefill on their next turn — correctness is unaffected,
        only the planned-miss optimization is reset."""
        off = self._offload
        if off is None:
            return 0
        n = len(off.keys())
        off.clear()
        self._update_block_gauges()
        return n

    def clear_prefix_cache(self) -> int:
        """Release every prefix-index pin, returning how many blocks
        were unpinned. Same idle-engine-only contract as
        :meth:`evict_sessions`."""
        if self.cache_backend != "paged":
            return 0
        blocks = self._prefix_index.clear()
        if blocks:
            self._allocator.free(blocks)
            self.metrics.inc("prefix_evictions", len(blocks))
        self._update_block_gauges()
        return len(blocks)

    def set_fault_injector(self, injector) -> None:
        """Swap the fault injector (``None`` disables injection). The
        seams read it per call, so this is safe between workloads —
        chaos tests and staging probes can reuse one warmed engine
        instead of paying a fresh compile set per fault scenario."""
        self._faults = injector

    @property
    def draining(self) -> bool:
        """True once :meth:`drain` has started (new submissions shed
        with 503 + Retry-After). Surfaced in the /stats summary so
        external load balancers steer away without parsing error
        counters."""
        return self._draining

    def alive(self) -> bool:
        """Liveness for ``/healthz``: False only when the scheduler is
        WEDGED — thread dead while it should be running, or no
        heartbeat within ``stall_timeout_s`` (the loop beats every
        iteration; its longest legitimate pause is one device call).
        A deliberately stopped/drained engine is not wedged."""
        if not self._running:
            return True
        if not self._thread.is_alive():
            return False
        return (time.monotonic() - self._beat) <= self._stall_timeout_s

    def _idle(self) -> bool:
        empty = (self._queue.empty() and not self._requeue
                 and self._slots.active_count == 0)
        if self.cache_backend == "paged":
            empty = empty and not self._prefilling \
                and self._held is None
        return empty

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown: new submissions are rejected with 503
        (:class:`~.batcher.DrainingError`), every queued and in-flight
        generation runs to completion, then the scheduler thread
        joins. Returns True when the engine fully drained within
        ``timeout_s``; leftovers past the budget are failed by
        :meth:`stop`'s shutdown path (uncounted, as for any deploy
        restart). Safe to call from a signal handler's thread."""
        first = not self._draining
        self._draining = True
        if first:
            self.metrics.inc("drains")
        self._wake.set()  # an idle-parked scheduler should re-check
        clean = poll_until_idle(self._idle, timeout_s)
        self.stop()
        return clean

    def stop(self, timeout_s: float = 5.0):
        """Stop the scheduler. Queued and in-flight requests are
        failed by the scheduler thread's own exit path (mutating the
        slot table from here would race a final in-flight device call
        if the join times out); waiters are additionally bounded by
        their deadlines."""
        self._running = False
        self._wake.set()  # unpark an idle scheduler immediately
        self._thread.join(timeout=timeout_s)
        if self._offload_prefetcher is not None:
            self._offload_prefetcher.stop()
        if self._offload is not None:
            # drops the host entries and unlinks the disk ring's
            # tempfile; runs after the scheduler join so no demote/
            # restore can still be writing into the store
            self._offload.close()
