"""Hierarchical KV tier: host-RAM / disk offload below the device
block pool (PR 16, ROADMAP item 2).

The SessionStore (PR 11) pins sessions IN the device pool, so live-
session capacity equals device pool bytes — most users are idle
between turns yet still occupy HBM. The reference stack's memory
design says evicted state should demote to a cheaper tier and restore
on demand (SURVEY §L0 host/device workspaces + ``memcpyAsync`` in
``NativeOps.h``), not be discarded. This module is that cheaper tier:

- :class:`HostRun` — one demoted block run: the token history plus
  per-layer contiguous numpy copies of the K and V pool rows AT THE
  POOL DTYPE (int8 values + f32 scale sidecars ride together, so the
  PR 15 4× byte saving carries straight into host GB and PCIe
  traffic).
- :class:`DiskRing` — optional third tier: a fixed-size mmap'd ring
  file. Writes append; when the cursor would overrun, the entries in
  the overwritten range are evicted (ring semantics — oldest bytes
  die first). Reads rebuild a :class:`HostRun` from the mapped bytes.
- :class:`HostBlockStore` — LRU + byte-budget map over both tiers.
  ``put`` inserts into RAM and demotes LRU runs over budget to the
  disk ring (or drops them when there is none). All methods are
  thread-safe: the scheduler thread demotes/restores while the
  prefetch thread stages reads.
- :class:`OffloadPrefetcher` — one daemon thread that overlaps the
  slow half of a restore (disk read + padded scatter-operand build)
  with admission/queueing. The engine ``request()``s a stage at
  submit time and ``take()``s the staged operands at admission — the
  allocator and every device call stay on the scheduler thread; the
  prefetcher only ever touches host memory.

Division of labor with the engine (:mod:`.generation`): this module
never sees JAX arrays, allocators, or executables — it stores bytes
and token arrays. The engine owns the device halves (gather/scatter
executables compiled per pow2 bucket, demote-on-evict, the
restore-vs-reprefill decision) and the ``offload_io`` fault seam
(:mod:`..faults`): a torn demotion drops the host copy, a torn
restore falls back to clean re-prefill — a lane is never corrupted by
tier IO.
"""
from __future__ import annotations

import collections
import os
import queue
import tempfile
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class HostRun:
    """One demoted block run: ``tokens`` (the K/V-valid token history,
    int32 copy) plus per-layer packed K and V rows as produced by
    :func:`~deeplearning4j_tpu.kernels.kv_quant.kv_pack_host` — each
    layer a tuple of contiguous numpy arrays (``(values,)`` for
    f32/bf16 pools, ``(q, scale)`` for int8). ``nbytes`` is the host
    footprint the byte budget charges."""

    __slots__ = ("tokens", "ks", "vs", "n_blocks", "kv_dtype", "nbytes")

    def __init__(self, tokens: np.ndarray,
                 ks: Sequence[Tuple[np.ndarray, ...]],
                 vs: Sequence[Tuple[np.ndarray, ...]],
                 kv_dtype: str):
        self.tokens = np.ascontiguousarray(np.asarray(tokens, np.int32))
        self.ks = tuple(tuple(p for p in layer) for layer in ks)
        self.vs = tuple(tuple(p for p in layer) for layer in vs)
        self.n_blocks = int(self.ks[0][0].shape[0])
        self.kv_dtype = str(kv_dtype)
        self.nbytes = int(self.tokens.nbytes
                          + sum(p.nbytes for layer in self.ks
                                for p in layer)
                          + sum(p.nbytes for layer in self.vs
                                for p in layer))

    # ---------------------------------------------- disk serialization

    def pack(self) -> Tuple[bytes, dict]:
        """Flatten to (payload bytes, meta dict) for the disk ring.
        Meta holds every shape/dtype so :meth:`unpack` needs no pickle
        — plain concatenated buffers, self-describing and compact."""
        parts: List[np.ndarray] = [self.tokens]
        for layer in self.ks:
            parts.extend(layer)
        for layer in self.vs:
            parts.extend(layer)
        meta = {
            "kv_dtype": self.kv_dtype,
            "n_blocks": self.n_blocks,
            "k_layers": [[(p.shape, str(p.dtype)) for p in layer]
                         for layer in self.ks],
            "v_layers": [[(p.shape, str(p.dtype)) for p in layer]
                         for layer in self.vs],
            "n_tokens": int(self.tokens.shape[0]),
        }
        return b"".join(np.ascontiguousarray(p).tobytes()
                        for p in parts), meta

    @classmethod
    def unpack(cls, buf: memoryview, meta: dict) -> "HostRun":
        off = 0

        def take(shape, dtype):
            nonlocal off
            n = int(np.prod(shape)) * np.dtype(dtype).itemsize
            arr = np.frombuffer(buf[off:off + n],
                                dtype=dtype).reshape(shape).copy()
            off += n
            return arr

        tokens = take((meta["n_tokens"],), np.int32)
        ks = [tuple(take(s, d) for s, d in layer)
              for layer in meta["k_layers"]]
        vs = [tuple(take(s, d) for s, d in layer)
              for layer in meta["v_layers"]]
        return cls(tokens, ks, vs, meta["kv_dtype"])


class DiskRing:
    """Fixed-capacity mmap'd ring file: the third KV tier.

    Entries are appended at a rolling cursor; when an entry would
    overrun the remaining tail, the cursor wraps to 0. Any stored
    entry whose bytes overlap the incoming write is evicted first —
    classic ring semantics, the oldest bytes on disk die to make room.
    An entry larger than the whole ring is rejected (returns False).

    The file is created lazily (a tempfile when no ``path`` is given)
    and unlinked on :meth:`close`. All coordination is the caller's
    (:class:`HostBlockStore` holds the lock)."""

    def __init__(self, capacity_bytes: int, path: Optional[str] = None):
        self.capacity = int(capacity_bytes)
        if self.capacity < 1:
            raise ValueError("disk ring capacity must be >= 1 byte, "
                             f"got {capacity_bytes}")
        self._path = path
        self._own_file = path is None
        self._mm: Optional[np.memmap] = None
        self._cursor = 0
        # key -> (offset, length, meta); insertion order == write order
        self._entries: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()

    def _ensure_open(self) -> np.memmap:
        if self._mm is None:
            if self._path is None:
                fd, self._path = tempfile.mkstemp(prefix="kv_ring_",
                                                  suffix=".bin")
                os.close(fd)
            self._mm = np.memmap(self._path, dtype=np.uint8, mode="w+",
                                 shape=(self.capacity,))
        return self._mm

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    @property
    def used_bytes(self) -> int:
        return sum(length for _, length, _ in self._entries.values())

    def _evict_range(self, start: int, end: int):
        doomed = [k for k, (off, length, _) in self._entries.items()
                  if off < end and off + length > start]
        for k in doomed:
            del self._entries[k]

    def put(self, key: str, payload: bytes, meta: dict) -> bool:
        """Write one entry, evicting whatever the ring overwrites.
        False iff the payload cannot fit the ring at all."""
        n = len(payload)
        if n > self.capacity:
            return False
        mm = self._ensure_open()
        self._entries.pop(key, None)
        if self._cursor + n > self.capacity:
            # wrapping: the abandoned tail's entries die too
            self._evict_range(self._cursor, self.capacity)
            self._cursor = 0
        start = self._cursor
        self._evict_range(start, start + n)
        mm[start:start + n] = np.frombuffer(payload, np.uint8)
        self._cursor = start + n
        self._entries[key] = (start, n, meta)
        return True

    def get(self, key: str) -> Optional[HostRun]:
        ent = self._entries.get(key)
        if ent is None:
            return None
        off, length, meta = ent
        mm = self._ensure_open()
        return HostRun.unpack(memoryview(mm)[off:off + length], meta)

    def pop(self, key: str):
        self._entries.pop(key, None)

    def clear(self):
        self._entries.clear()
        self._cursor = 0

    def close(self):
        self._entries.clear()
        if self._mm is not None:
            del self._mm
            self._mm = None
        if self._own_file and self._path is not None:
            try:
                os.unlink(self._path)
            except OSError:
                pass
            self._path = None


class HostBlockStore:
    """LRU + byte-budget map ``key -> HostRun`` over host RAM with an
    optional :class:`DiskRing` below it.

    ``put`` inserts into RAM, then while RAM is over ``byte_budget``
    the LRU run spills to the disk ring (or is dropped when there is
    none / it will not fit). ``get`` checks RAM then disk; a disk hit
    is NOT promoted back to RAM (the caller is about to scatter it to
    the device anyway — promotion would only churn the budget).
    ``pop`` removes from both tiers.

    Thread-safe: one lock serializes the scheduler thread's demotes/
    restores against the prefetch thread's staged reads."""

    def __init__(self, byte_budget: int,
                 disk: Optional[DiskRing] = None):
        self.byte_budget = int(byte_budget)
        if self.byte_budget < 1:
            raise ValueError("host byte budget must be >= 1, got "
                             f"{byte_budget}")
        self.disk = disk
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, HostRun]" = \
            collections.OrderedDict()
        self._bytes = 0
        # counters surfaced through the engine's offload gauges
        self.spills = 0        # RAM -> disk demotions
        self.drops = 0         # runs lost at the bottom of the hierarchy

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            if key in self._entries:
                return True
            return self.disk is not None and key in self.disk

    def put(self, key: str, run: HostRun):
        """Insert (replacing any same-key entry in either tier), then
        enforce the byte budget by spilling LRU runs down a tier."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            if self.disk is not None:
                self.disk.pop(key)
            self._entries[key] = run
            self._bytes += run.nbytes
            # the just-inserted run is never evicted even when it alone
            # exceeds the budget (len > 1 guard): an oversized demotion
            # degrading to a silent discard would break zero-re-prefill
            while self._bytes > self.byte_budget and len(self._entries) > 1:
                lru_key, lru_run = self._entries.popitem(last=False)
                self._bytes -= lru_run.nbytes
                spilled = False
                if self.disk is not None:
                    payload, meta = lru_run.pack()
                    spilled = self.disk.put(lru_key, payload, meta)
                if spilled:
                    self.spills += 1
                else:
                    self.drops += 1

    def get(self, key: str) -> Optional[HostRun]:
        """RAM first (LRU-touching), then disk. None on full miss."""
        with self._lock:
            run = self._entries.get(key)
            if run is not None:
                self._entries.move_to_end(key)
                return run
            if self.disk is not None:
                return self.disk.get(key)
            return None

    def peek(self, key: str) -> Optional[HostRun]:
        """RAM-tier lookup WITHOUT LRU touch or disk read — identity
        checks (is this staged run still current?) must not pay a disk
        read or perturb eviction order."""
        with self._lock:
            return self._entries.get(key)

    def pop(self, key: str):
        """Remove ``key`` from both tiers (after a successful restore,
        or to invalidate a torn copy)."""
        with self._lock:
            run = self._entries.pop(key, None)
            if run is not None:
                self._bytes -= run.nbytes
            if self.disk is not None:
                self.disk.pop(key)

    def keys(self) -> List[str]:
        with self._lock:
            out = list(self._entries.keys())
            if self.disk is not None:
                out.extend(k for k in self.disk._entries
                           if k not in self._entries)
            return out

    def stats(self) -> dict:
        with self._lock:
            host_blocks = sum(r.n_blocks for r in self._entries.values())
            out = {"host_runs": len(self._entries),
                   "host_blocks": host_blocks,
                   "host_bytes": self._bytes,
                   "spills": self.spills,
                   "drops": self.drops,
                   "disk_blocks": 0, "disk_bytes": 0}
            if self.disk is not None:
                out["disk_blocks"] = sum(
                    int(m.get("n_blocks", 0))
                    for _, _, m in self.disk._entries.values())
                out["disk_bytes"] = self.disk.used_bytes
            return out

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            if self.disk is not None:
                self.disk.clear()

    def close(self):
        self.clear()
        if self.disk is not None:
            self.disk.close()


class OffloadPrefetcher:
    """One daemon thread that runs ``stage_fn(key)`` ahead of need and
    parks the result until the scheduler ``take()``s it.

    ``stage_fn`` must touch HOST state only (store read — possibly a
    disk read — plus padded scatter-operand construction): the
    allocator and all device calls stay on the scheduler thread, so a
    prefetch can never race engine state. Staged results are capped at
    ``max_staged``; when full, new requests stage lazily at admission
    instead (correct, just not overlapped)."""

    def __init__(self, stage_fn: Callable[[str], object],
                 max_staged: int = 64):
        self._stage_fn = stage_fn
        self.max_staged = int(max_staged)
        self._lock = threading.Lock()
        self._staged: Dict[str, object] = {}
        self._inflight: set = set()
        self._q: "queue.Queue[Optional[str]]" = queue.Queue()
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="kv-offload-prefetch",
                                        daemon=True)
        self._thread.start()

    def request(self, key: str):
        """Ask for ``key`` to be staged. Deduplicates against both
        in-flight and already-staged work; silently drops when the
        staging buffer is full (admission will stage inline)."""
        with self._lock:
            if not self._running:
                return
            if key in self._staged or key in self._inflight:
                return
            if len(self._staged) + len(self._inflight) >= self.max_staged:
                return
            self._inflight.add(key)
        self._q.put(key)

    def take(self, key: str):
        """Pop the staged result for ``key`` (None if not staged —
        not requested, still in flight, or the stage failed)."""
        with self._lock:
            return self._staged.pop(key, None)

    def discard(self, key: str):
        """Drop any staged result for ``key`` (it went stale)."""
        with self._lock:
            self._staged.pop(key, None)

    def _loop(self):
        while True:
            key = self._q.get()
            if key is None:
                return
            try:
                result = self._stage_fn(key)
            except Exception:
                # staging is best-effort: a failed stage falls back to
                # the inline path at admission
                result = None
            with self._lock:
                self._inflight.discard(key)
                if result is not None and self._running:
                    self._staged[key] = result

    def stop(self):
        with self._lock:
            self._running = False
            self._staged.clear()
        self._q.put(None)
        self._thread.join(timeout=5.0)
