"""Micro-batching scheduler: coalesce concurrent requests into one
device call.

Ref role: TensorFlow Serving's BatchingSession / Clipper's adaptive
batching layer (PAPERS.md) — the standard accelerator-serving design:
a bounded request queue feeds a single scheduler thread that waits up
to ``max_latency_ms`` for the batch to fill (or ``max_batch_size``
rows, whichever first), issues ONE padded device call through the
:class:`~.engine.InferenceEngine`, and scatters the rows back to the
waiting clients.

Overload semantics are explicit: a full queue SHEDS the request
(:class:`QueueFullError` → HTTP 503) rather than growing without
bound, and every request carries a deadline
(:class:`DeadlineExceededError` → HTTP 504) so a stalled device cannot
strand clients forever.

Admission control (docs/serving.md "Overload and admission control"):
requests carry a priority class — ``interactive`` (default) or
``batch`` — and under pressure batch work is shed FIRST: batch-class
requests only get the front ``batch_queue_fraction`` of the queue,
interactive requests get all of it. Admission is also deadline-aware
and adaptive: the batcher keeps an EWMA of the device-call time and
(a) sheds at submit when the estimated queue wait alone already blows
the request's deadline budget (503 — another, shorter-queued replica
may still make it), and (b) drops a request at dequeue when its
remaining budget cannot cover even one device call (504) — zero
device steps are ever spent on a request that cannot finish in time.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Optional, Sequence

from ..profiler import OpProfiler
from .engine import (ClientError, InferenceEngine, ServingError,
                     _concat_results, _slice)
from .faults import TransientFault, poll_until_idle


class QueueFullError(ServingError):
    """Load shed: the request queue is at capacity (HTTP 503)."""


class DrainingError(QueueFullError):
    """The server is draining for shutdown: new work is rejected with
    503 + ``Retry-After`` while in-flight requests finish."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before a result was ready
    (HTTP 504)."""


#: Priority classes, in shed order: under pressure "batch" is shed
#: first so "interactive" p99 holds. Anything else is a ClientError.
PRIORITIES = ("interactive", "batch")


class _Request:
    __slots__ = ("feed", "n", "sig", "deadline", "priority", "event",
                 "result", "error", "t_submit", "abandoned", "_lock",
                 "_timeout_counted", "trace", "qspan")

    def __init__(self, feed, n, sig, deadline, priority="interactive"):
        self.feed = feed
        self.n = n
        self.sig = sig
        self.deadline = deadline
        self.priority = priority
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.abandoned = False  # submitter gave up; don't execute/count
        self._lock = threading.Lock()
        self._timeout_counted = False
        self.trace = None   # tracing.Trace when the request is traced
        self.qspan = None   # its open queue-wait span

    def count_timeout_once(self, metrics) -> None:
        """Waiter and scheduler can both observe the deadline expiring
        at the same instant; the counter must move once per request."""
        with self._lock:
            if self._timeout_counted:
                return
            self._timeout_counted = True
        metrics.inc("timeouts")


class MicroBatcher:
    """Thread-based request queue + scheduler over one engine.

    ``submit`` blocks the calling (HTTP handler) thread until its rows
    come back; the scheduler thread owns all device calls, so requests
    admitted while one batch executes pile up and ride the next call —
    that queueing is exactly what produces coalescing under load.
    """

    def __init__(self, engine: InferenceEngine,
                 max_batch_size: Optional[int] = None,
                 max_latency_ms: float = 5.0,
                 max_queue: int = 256,
                 default_timeout_ms: float = 30_000.0,
                 max_retries: int = 3,
                 retry_backoff_ms: float = 1.0,
                 retry_backoff_max_ms: float = 50.0,
                 stall_timeout_s: float = 30.0,
                 batch_queue_fraction: float = 0.5):
        self.engine = engine
        self.max_batch_size = int(max_batch_size or engine.max_batch_size)
        if self.max_batch_size > engine.max_batch_size:
            raise ValueError("batcher max_batch_size exceeds the engine's")
        self.max_latency_ms = float(max_latency_ms)
        self.default_timeout_ms = float(default_timeout_ms)
        # supervision: a TransientFault from the device call is retried
        # up to max_retries times with bounded exponential backoff (the
        # inference path is stateless — no donation — so a retry is
        # always safe); anything else fails the batch as before
        self.max_retries = int(max_retries)
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.retry_backoff_max_ms = float(retry_backoff_max_ms)
        self.stall_timeout_s = float(stall_timeout_s)
        self.metrics = engine.metrics
        self.metrics.queue_max = int(max_queue)
        # priority shedding: batch-class work only gets the front
        # fraction of the queue; interactive gets all of it
        self.batch_queue_fraction = float(batch_queue_fraction)
        self._batch_queue_limit = max(
            1, int(self.batch_queue_fraction * max_queue))
        # adaptive admission: EWMA of one device call, measured — the
        # deadline-budget checks key off it, so the limits track the
        # actual service rate instead of a hand-tuned constant
        self._device_ewma_ms = 0.0
        # total ROWS waiting (in the queue, signature-held, or in a
        # batch being formed): the queue-wait estimate must count
        # rows, not requests — one queued request can carry up to
        # max_batch_size rows
        self._pending_rows = 0
        self._rows_lock = threading.Lock()
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=max_queue)
        # submit-wake: an idle scheduler parks on this event instead of
        # polling the queue every 50 ms (ISSUE 14) — set by submit()
        # after each enqueue and by stop() so shutdown is immediate
        self._wake = threading.Event()
        self._held: "deque[_Request]" = deque()  # signature-mismatched
        self._profiler = OpProfiler.get_instance()
        self._running = True
        self._draining = False
        self._beat = time.monotonic()  # scheduler heartbeat (/healthz)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-batcher")
        self._thread.start()

    # -- client side ---------------------------------------------------
    def submit(self, inputs, outputs: Optional[Sequence[str]] = None,
               timeout_ms: Optional[float] = None,
               priority: str = "interactive", trace=None) -> Any:
        """Enqueue one request and block until its result. Raises
        :class:`~.engine.ClientError` on malformed payloads,
        :class:`QueueFullError` when shedding, and
        :class:`DeadlineExceededError` past the deadline. ``priority``
        is ``"interactive"`` (default) or ``"batch"``; batch-class
        work is shed first under pressure. ``trace`` (a
        :class:`~..tracing.Trace`, default ``None`` = untraced) records
        the admission verdict — with the EWMA estimates that drove it —
        plus queue-wait and device spans."""
        if trace is not None:
            return self._submit_traced(inputs, outputs, timeout_ms,
                                       priority, trace)
        return self._submit(inputs, outputs, timeout_ms, priority, None)

    def _submit_traced(self, inputs, outputs, timeout_ms, priority,
                       trace):
        """Wrap :meth:`_submit` so every shed/timeout path lands the
        admission verdict in the trace exactly once."""
        t0 = time.perf_counter()
        try:
            return self._submit(inputs, outputs, timeout_ms, priority,
                                trace)
        except (QueueFullError, DeadlineExceededError) as e:
            trace.span(
                "admission", t_start=t0, verdict="shed",
                error=str(e),
                device_ewma_ms=round(self._device_ewma_ms, 3),
                est_wait_ms=round(
                    self._est_queue_wait_ms(self._pending_rows), 3)
            ).end()
            raise

    def _submit(self, inputs, outputs, timeout_ms, priority,
                trace) -> Any:
        if priority not in PRIORITIES:
            raise ClientError(
                f"unknown priority {priority!r}; expected one of "
                f"{PRIORITIES}")
        if self._draining:
            # checked before _running: a drained replica answers 503 +
            # Retry-After (retry elsewhere), not 500, for its lifetime
            self.metrics.inc("shed")
            raise DrainingError("batcher is draining; retry against "
                                "another replica")
        if not self._running:
            raise ServingError("batcher is stopped")
        feed, n, sig = self.engine.normalize(inputs, outputs)
        if n > self.max_batch_size:
            raise ClientError(
                f"request batch {n} exceeds max_batch_size="
                f"{self.max_batch_size}; split the request")
        timeout = (self.default_timeout_ms if timeout_ms is None
                   else float(timeout_ms)) / 1000.0
        depth = self._queue.qsize()
        if priority == "batch" and depth >= self._batch_queue_limit:
            # shed order: batch first — interactive may still use the
            # remaining queue, so its p99 holds while batch degrades
            self.metrics.inc("shed")
            self.metrics.inc("shed_batch")
            raise QueueFullError(
                f"queue depth {depth} at the batch-priority limit "
                f"({self._batch_queue_limit}/{self.metrics.queue_max});"
                f" shedding batch-class work first")
        est_wait_ms = self._est_queue_wait_ms(self._pending_rows)
        if est_wait_ms + self._device_ewma_ms > timeout * 1e3:
            # deadline-aware early rejection at SUBMIT. Two distinct
            # verdicts: a budget smaller than ONE device call can
            # never be met anywhere (504, same as expiring in queue);
            # a budget eaten by THIS queue's wait is load-local (503 —
            # a shorter-queued replica may still make it)
            self.metrics.inc("shed_deadline")
            if self._device_ewma_ms > timeout * 1e3:
                self.metrics.inc("timeouts")
                raise DeadlineExceededError(
                    f"deadline budget {timeout * 1e3:.0f} ms is below "
                    f"one device call ({self._device_ewma_ms:.0f} ms);"
                    f" rejecting at admission")
            self.metrics.inc("shed")
            raise QueueFullError(
                f"estimated queue wait {est_wait_ms:.0f} ms exceeds "
                f"the {timeout * 1e3:.0f} ms deadline budget; shedding"
                f" at admission")
        req = _Request(feed, n, sig,
                       deadline=time.perf_counter() + timeout,
                       priority=priority)
        if trace is not None:
            # attach BEFORE enqueue: the scheduler may dequeue the
            # request the instant it lands
            req.trace = trace
            trace.span("admission", t_start=req.t_submit,
                       verdict="admitted",
                       est_wait_ms=round(est_wait_ms, 3),
                       device_ewma_ms=round(self._device_ewma_ms, 3),
                       rows=n).end()
            req.qspan = trace.span("queue", rows=n, priority=priority)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.inc("shed")
            raise QueueFullError(
                f"queue full ({self.metrics.queue_max}); shedding load")
        with self._rows_lock:
            self._pending_rows += req.n
        self._wake.set()
        if not self._running:
            # raced with stop(): the scheduler may already have drained
            # the queue — fail fast, don't strand the caller on wait()
            req.abandoned = True
            raise ServingError("batcher is stopped")
        self.metrics.inc("requests")
        self.metrics.queue_depth = self._queue.qsize()
        if not req.event.wait(timeout + 1.0):  # grace for the device call
            req.abandoned = True  # scheduler: skip it, don't re-execute
            req.count_timeout_once(self.metrics)
            raise DeadlineExceededError(
                f"no result within {timeout * 1e3:.0f} ms")
        if req.error is not None:
            raise req.error
        self.metrics.inc("responses")
        self.metrics.latency_ms.record(
            (time.perf_counter() - req.t_submit) * 1e3)
        return req.result

    def _est_queue_wait_ms(self, rows: int) -> float:
        """Estimated time for ``rows`` queued ROWS to drain, from the
        measured device-call EWMA. 0.0 until the first call lands (a
        cold batcher admits everything — no data, no shedding)."""
        if not self._device_ewma_ms or rows <= 0:
            return 0.0
        calls = -(-rows // self.max_batch_size)  # ceil division
        return calls * self._device_ewma_ms

    def _rows_done(self, n: int):
        """``n`` rows left the pending set (executed, expired, or
        failed at stop) — keep the queued-rows gauge honest."""
        with self._rows_lock:
            self._pending_rows -= n

    # -- scheduler side ------------------------------------------------
    def _next(self, block_s: Optional[float]):
        if self._held:
            return self._held.popleft()
        try:
            return self._queue.get(timeout=block_s) if block_s else \
                self._queue.get_nowait()
        except queue.Empty:
            return None

    def _next_head(self):
        """Pop the next batch HEAD without idle-polling: the old
        ``_next(0.05)`` woke an idle scheduler 20 times a second just
        to find the queue still empty. Instead, park on the
        submit-wake event (1 s backstop in case a wake is ever lost)
        — idle wakeups drop ~20x and a submit still starts its batch
        immediately. The fill loop keeps its timed ``queue.get``: that
        wait is the deliberate batch-forming window, not a poll."""
        if self._held:
            return self._held.popleft()
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            pass
        # clear-then-recheck closes the lost-wakeup race: a submit
        # landing between the failed pop and clear() re-sets the event
        # and the second pop sees its request
        self._wake.clear()
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            # bounded well under the stall watchdog so an idle
            # batcher's heartbeat never looks wedged to /healthz
            self._wake.wait(
                max(0.05, min(1.0, self.stall_timeout_s / 4.0)))
            return None

    def _expired(self, req) -> bool:
        """Drop a dead request instead of spending device time on rows
        nobody will read. Deadline-BUDGET aware: a request whose
        remaining budget cannot cover even one device call (EWMA) is
        already lost — shed it at dequeue-admission, before it burns a
        device step. The timeout count is a per-request CAS — the
        waiter may be counting the same expiry concurrently."""
        if req.abandoned:
            self._rows_done(req.n)
            return True
        if time.perf_counter() > req.deadline - self._device_ewma_ms / 1e3:
            req.error = DeadlineExceededError(
                "deadline budget exhausted in queue")
            req.count_timeout_once(self.metrics)
            self.metrics.inc("shed_deadline")
            self._rows_done(req.n)
            if req.trace is not None:
                req.qspan.end()
                req.trace.span(
                    "admission", verdict="expired",
                    device_ewma_ms=round(self._device_ewma_ms, 3)).end()
            req.event.set()
            return True
        return False

    def _loop(self):
        while self._running:
            self._beat = time.monotonic()
            head = self._next_head()
            if head is None or self._expired(head):
                continue
            batch = [head]
            rows = head.n
            flush_at = time.perf_counter() + self.max_latency_ms / 1000.0
            skipped = []
            while rows < self.max_batch_size:
                wait = flush_at - time.perf_counter()
                nxt = self._next(wait if wait > 0 else None)
                if nxt is None:
                    break
                if self._expired(nxt):
                    continue
                if nxt.sig != head.sig:
                    skipped.append(nxt)  # rides a later batch; keep
                    continue             # filling this one
                if rows + nxt.n > self.max_batch_size:
                    skipped.append(nxt)
                    break  # same sig but over budget — batch is full
                batch.append(nxt)
                rows += nxt.n
            self._held.extend(skipped)
            # final expiry sweep: members (the head included) can age
            # out DURING the fill wait — dead rows must not ride the
            # device call, and an all-expired batch must skip the call
            # entirely. _expired counts each drop exactly once (CAS
            # against the waiter's own timeout accounting).
            batch = [r for r in batch if not self._expired(r)]
            if batch:
                n_rows = sum(r.n for r in batch)
                self._rows_done(n_rows)
                self._execute(batch, n_rows)
            self.metrics.queue_depth = self._queue.qsize()
        # drain on stop: fail fast rather than strand waiters
        for req in list(self._held):
            self._rows_done(req.n)
            req.error = ServingError("batcher stopped")
            req.event.set()

    def _execute(self, batch, rows):
        feeds = [r.feed for r in batch]
        feed = feeds[0] if len(feeds) == 1 else _concat_results(feeds)
        self.metrics.inc("batches")
        self.metrics.batch_hist.record(rows)
        for r in batch:
            if r.trace is not None:  # queue wait ends as the batch forms
                r.qspan.end(batch_rows=rows)
        # live-occupancy gauge for the /stats summary: rows on the
        # device RIGHT NOW (a fleet router reads it to steer load)
        self.metrics.inflight = rows
        try:
            self._execute_inner(batch, rows, feed)
        finally:
            self.metrics.inflight = 0

    def _execute_inner(self, batch, rows, feed):
        backoff = self.retry_backoff_ms / 1e3
        attempt = 0
        while True:
            c0 = self.metrics.compiles
            t0 = time.perf_counter()  # device_ms times the call that
            try:                      # succeeded, not the backoffs
                with self._profiler.record("serving.batch"):
                    # rows were normalized in submit(); the sig is
                    # shared by construction — skip re-validating on
                    # the hot path
                    res = self.engine.predict_normalized(feed, rows,
                                                         batch[0].sig)
                break
            except TransientFault as e:
                # raised before the device call touched anything —
                # retry the SAME batch with bounded backoff; give up
                # only after max_retries and fail the batch like any
                # other device error
                attempt += 1
                if attempt > self.max_retries:
                    for r in batch:
                        r.error = e
                        r.event.set()
                    return
                self.metrics.inc("retries")
                time.sleep(backoff)
                backoff = min(backoff * 2.0,
                              self.retry_backoff_max_ms / 1e3)
            except Exception as e:  # noqa: BLE001 — scatter to waiters
                for r in batch:
                    r.error = e
                    r.event.set()
                return
        t1 = time.perf_counter()
        dt_ms = (t1 - t0) * 1e3
        self.metrics.device_ms.record(dt_ms)
        for r in batch:
            if r.trace is not None:
                # retroactive: the device window measured above, not a
                # second clock read per row
                r.trace.span("device", t_start=t0, t_end=t1,
                             batch_rows=rows, retries=attempt)
        # feed the adaptive-admission EWMA (scheduler thread only) —
        # but never from a call that paid a lazy XLA compile: one
        # multi-second sample would push the estimate above every
        # deadline budget, and with all traffic then shed at submit
        # no new samples could ever decay it back down
        if self.metrics.compiles == c0:
            self._device_ewma_ms = dt_ms if not self._device_ewma_ms \
                else 0.8 * self._device_ewma_ms + 0.2 * dt_ms
        lo = 0
        for r in batch:
            r.result = _slice(res, lo, lo + r.n)
            lo += r.n
            r.event.set()

    @property
    def draining(self) -> bool:
        """True once :meth:`drain` has started (new submits shed with
        503 + Retry-After). Surfaced in the /stats summary so external
        load balancers steer away without parsing error counters."""
        return self._draining

    def alive(self) -> bool:
        """Liveness for ``/healthz``: False only when the scheduler is
        WEDGED — thread dead while it should run, or no heartbeat
        within ``stall_timeout_s`` (the loop beats every iteration,
        bounded by its 50 ms idle poll, so a stale beat means a stuck
        device call). A deliberately stopped/drained batcher is not
        wedged."""
        if not self._running:
            return True
        if not self._thread.is_alive():
            return False
        return (time.monotonic() - self._beat) <= self.stall_timeout_s

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown: reject new submits with 503
        (:class:`DrainingError`), let queued + in-flight requests
        finish, then join the scheduler thread. Returns True when the
        queue fully drained within ``timeout_s`` (leftovers past the
        budget are failed by :meth:`stop`)."""
        first = not self._draining
        self._draining = True
        if first:
            self.metrics.inc("drains")
        clean = poll_until_idle(
            lambda: self._queue.empty() and not self._held, timeout_s)
        # the scheduler finishes its in-flight batch (waiters get their
        # results) before observing _running=False; join covers it
        self.stop()
        return clean

    def stop(self, timeout_s: float = 5.0):
        self._running = False
        self._wake.set()  # unpark an idle scheduler immediately
        self._thread.join(timeout=timeout_s)
        # fail anything still queued
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            self._rows_done(req.n)
            req.error = ServingError("batcher stopped")
            req.event.set()



