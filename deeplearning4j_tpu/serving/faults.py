"""Deterministic fault injection for the serving stack.

Ref role: the reference DL4J stack is built around surviving worker
failure — its Aeron parameter server retries lost updates and the
Spark training master re-schedules dead executors — and it proves that
story with chaos-style tests that kill workers mid-run. This module is
the serving-side equivalent: a seeded, scriptable
:class:`FaultInjector` that the engines call at named SEAMS so tests
and the bench chaos probe can make the runtime fail in exactly the
ways real deployments do, deterministically.

Seams (where the engines fire the injector):

- ``device_step``   — immediately before a decode/batch device call
  (`GenerationEngine._decode_step`, `InferenceEngine.predict_normalized`)
- ``prefill``       — immediately before a prefill / prefill-chunk
  (`GenerationEngine._prefill` / `_prefill_chunk_step`)
- ``alloc``         — before claiming KV blocks at paged admission
- ``client_disconnect`` — per streamed token; a fire marks the request
  abandoned, as if the HTTP consumer hung up mid-stream
- ``latency``       — once per scheduler iteration; a fire sleeps
  ``latency_ms`` instead of raising (injects tail latency, not errors)

Fault types injected at the raising seams:

- :class:`TransientFault` — raised BEFORE any buffer donation, so the
  engine's state is intact and the step can simply be retried (the
  supervised loops do, with bounded exponential backoff).
- :class:`CorruptedStateFault` — models a device call dying AFTER the
  KV caches were donated to it: the prefixes are gone and the engine
  must rebuild by recompute-recovery (re-prefill every in-flight
  request from prompt + already-emitted tokens). Configure via
  ``corrupting={"device_step", ...}``.

The injector is INERT unless explicitly constructed and passed to an
engine (``fault_injector=``); engines hold ``None`` by default and
guard every seam with one attribute load, so production traffic pays
zero overhead. Decisions are deterministic: each seam has its own call
counter and its own ``RandomState`` seeded from ``(seed, seam)``, so
the fire pattern at one seam never depends on how other seams
interleave — the same workload replays the same faults.
"""
from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Dict, Iterable, Optional

import numpy as np

from .engine import ServingError

#: the seams engines fire; anything else is a configuration typo and
#: fails loudly at construction rather than silently never firing
SEAMS = ("device_step", "prefill", "alloc", "client_disconnect",
         "latency")


class TransientFault(ServingError):
    """A retryable failure raised BEFORE any buffer donation: engine
    state is intact, so the supervised loop retries the step with
    bounded exponential backoff (HTTP 5xx only if retries exhaust AND
    recovery fails)."""


class CorruptedStateFault(ServingError):
    """A device call failed after the KV caches were donated to it —
    the in-flight prefixes are unrecoverable from the device and the
    engine must rebuild by recompute-recovery."""


class PoisonRequestError(ServingError):
    """One request produced non-finite logits (NaN/Inf) — it is
    quarantined: failed alone with HTTP 500, its slot/blocks freed
    immediately, while the rest of the batch keeps decoding."""


class FaultInjector:
    """Seeded, scriptable fault source the engines consult at named
    seams (see module docstring).

    ``rates``: ``{seam: probability}`` — fire ~that fraction of calls,
    from a per-seam seeded stream.
    ``plan``: ``{seam: [call indices]}`` — fire exactly on those
    1-based invocation counts of that seam (deterministic scripting
    for tests; composes with ``rates``).
    ``corrupting``: seams whose fires raise
    :class:`CorruptedStateFault` instead of :class:`TransientFault`.
    """

    def __init__(self, seed: int = 0,
                 rates: Optional[Dict[str, float]] = None,
                 plan: Optional[Dict[str, Iterable[int]]] = None,
                 corrupting: Iterable[str] = (),
                 latency_ms: float = 1.0):
        self.seed = int(seed)
        self.rates = {s: float(p) for s, p in (rates or {}).items()}
        self.plan = {s: frozenset(int(i) for i in idx)
                     for s, idx in (plan or {}).items()}
        self.corrupting = frozenset(corrupting)
        unknown = [s for s in (set(self.rates) | set(self.plan)
                               | self.corrupting) if s not in SEAMS]
        if unknown:
            raise ValueError(f"unknown fault seams {sorted(unknown)}; "
                             f"valid seams: {list(SEAMS)}")
        for s, p in self.rates.items():
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"rate for seam {s!r} must be in "
                                 f"[0, 1], got {p}")
        self.latency_ms = float(latency_ms)
        self._lock = threading.Lock()
        self._calls = {s: 0 for s in SEAMS}
        self._fired = {s: 0 for s in SEAMS}
        # one stream PER SEAM, keyed by (seed, seam name): the decision
        # at call #n of a seam depends only on n — never on how many
        # times OTHER seams fired in between — so a workload replays
        # the same fault pattern regardless of thread interleaving
        self._rngs = {s: np.random.RandomState(
            (self.seed * 1_000_003 + zlib.crc32(s.encode())) & 0xFFFFFFFF)
            for s in self.rates}

    def fire(self, seam: str) -> bool:
        """Consult the injector at ``seam``. Returns False (no fault)
        or True (``latency`` slept / ``client_disconnect`` should be
        interpreted by the caller); the error seams raise instead of
        returning True."""
        if seam not in self._calls:
            raise ValueError(f"unknown seam {seam!r}")
        with self._lock:
            self._calls[seam] += 1
            n = self._calls[seam]
            hit = n in self.plan.get(seam, ())
            if not hit and seam in self.rates:
                hit = bool(self._rngs[seam].random_sample()
                           < self.rates[seam])
            if not hit:
                return False
            self._fired[seam] += 1
        if seam == "latency":
            time.sleep(self.latency_ms / 1e3)
            return True
        if seam == "client_disconnect":
            return True
        if seam in self.corrupting:
            raise CorruptedStateFault(
                f"injected cache-corrupting fault at {seam!r} "
                f"(call #{n})")
        raise TransientFault(
            f"injected transient fault at {seam!r} (call #{n})")

    def snapshot(self) -> Dict:
        """Per-seam call/fire counters (for tests and the bench chaos
        probe's report)."""
        with self._lock:
            return {"calls": dict(self._calls),
                    "fired": dict(self._fired)}


def poll_until_idle(is_idle: Callable[[], bool], timeout_s: float,
                    quiet_obs: int = 3, poll_s: float = 0.02) -> bool:
    """True once ``is_idle()`` holds for ``quiet_obs`` CONSECUTIVE
    observations before the deadline. A single idle glimpse is not
    enough: a request can sit between ``queue.get()`` and its device
    call / slot claim for a moment with every queue already empty.
    Shared by the engine and batcher drain loops so the quiet
    heuristic cannot drift between them."""
    deadline = time.monotonic() + timeout_s
    quiet = 0
    while time.monotonic() < deadline:
        if is_idle():
            quiet += 1
            if quiet >= quiet_obs:
                return True
        else:
            quiet = 0
        time.sleep(poll_s)
    return False
