"""Backwards-compat re-export: the fault-injection toolkit moved up to
:mod:`deeplearning4j_tpu.faults` so training and serving chaos share
ONE injector (same seams machinery, same seeded per-seam decision
streams, same fault taxonomy). Serving code and existing callers keep
importing from here; the classes ARE the shared ones — ``isinstance``
checks and ``except`` clauses match across both runtimes.

Note the fault types now subclass :class:`~..faults.FaultError`
(a RuntimeError) rather than the serving-layer ``ServingError``; the
HTTP front-end's default branch still maps them to 5xx, and nothing in
the runtime caught them via ``except ServingError``.
"""
from __future__ import annotations

from ..faults import (SEAMS, CorruptedStateFault, FaultError,  # noqa: F401
                      FaultInjector, PoisonRequestError, PreemptionFault,
                      TransientFault, poll_until_idle)

__all__ = ["SEAMS", "CorruptedStateFault", "FaultError", "FaultInjector",
           "PoisonRequestError", "PreemptionFault", "TransientFault",
           "poll_until_idle"]
