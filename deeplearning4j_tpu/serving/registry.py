"""Named, versioned multi-model registry.

Ref role: TF Serving's ServableManager / the reference's model-server
routing — one server process hosts many models, each addressed as
``/v1/models/<name>/predict``, with versions so a new model can be
registered next to the old one and the old one retired atomically.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from .batcher import MicroBatcher
from .engine import ClientError, InferenceEngine
from .generation import GenerationEngine
from .metrics import GenerationMetrics, ServingMetrics


class ModelNotFound(ClientError):
    """No such model name/version in the registry (HTTP 404)."""


class ServedModel:
    """One (model, version) plus its engine and (optional) batcher."""

    def __init__(self, name: str, version: int, model,
                 default_outputs: Optional[Sequence[str]] = None,
                 batching: bool = True, max_batch_size: int = 64,
                 max_latency_ms: float = 5.0, max_queue: int = 256,
                 cache_size: int = 16,
                 default_timeout_ms: float = 30_000.0,
                 fault_injector=None,
                 max_retries: int = 3,
                 retry_backoff_ms: float = 1.0):
        self.name = name
        self.version = int(version)
        self.model = model
        self.engine = InferenceEngine(
            model, default_outputs=default_outputs,
            max_batch_size=max_batch_size, cache_size=cache_size,
            fault_injector=fault_injector)
        self.batcher = MicroBatcher(
            self.engine, max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms, max_queue=max_queue,
            default_timeout_ms=default_timeout_ms,
            max_retries=max_retries,
            retry_backoff_ms=retry_backoff_ms) if batching else None

    @property
    def metrics(self) -> ServingMetrics:
        return self.engine.metrics

    def predict(self, inputs, outputs: Optional[Sequence[str]] = None,
                timeout_ms: Optional[float] = None,
                priority: str = "interactive", trace=None):
        if self.batcher is not None:
            return self.batcher.submit(inputs, outputs,
                                       timeout_ms=timeout_ms,
                                       priority=priority, trace=trace)
        # direct path (batching=False): synchronous, so timeout_ms has
        # no queue to bound — but request metrics must still flow,
        # including the live-occupancy gauge the /stats summary feeds
        # to routers (without it a saturated unbatched replica would
        # read as idle and keep attracting fleet traffic)
        m = self.metrics
        m.inc("requests")
        t0 = time.perf_counter()
        m.inc("inflight")
        try:
            res = self.engine.predict(inputs, outputs, trace=trace)
        finally:
            m.inc("inflight", -1)
        m.inc("responses")
        m.latency_ms.record((time.perf_counter() - t0) * 1e3)
        return res

    def warmup(self, buckets: Sequence[int], example=None,
               outputs: Optional[Sequence[str]] = None):
        return self.engine.warmup(buckets, example=example, outputs=outputs)

    def alive(self) -> bool:
        """Liveness (``/healthz``): the batcher's scheduler loop is
        not wedged. Unbatched models have no loop to stall."""
        return self.batcher.alive() if self.batcher is not None else True

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Reject new work (503 + Retry-After), finish in-flight
        requests, join the scheduler thread."""
        if self.batcher is not None:
            return self.batcher.drain(timeout_s)
        return True

    def stop(self):
        if self.batcher is not None:
            self.batcher.stop()

    def stats(self) -> Dict:
        s = self.metrics.snapshot()
        s["version"] = self.version
        s["model_class"] = type(self.model).__name__
        s["batching"] = self.batcher is not None
        return s

    def summary(self) -> Dict:
        """Compact machine-readable routing summary (the ``summary``
        block of ``GET /stats``): live occupancy, queue depth, and the
        draining flag — everything a load balancer needs to pick a
        replica, with no histogram parsing. ``load`` is the one-number
        backlog score routers sort by (queued + on-device rows)."""
        m = self.metrics
        cap = (self.batcher.max_batch_size if self.batcher is not None
               else self.engine.max_batch_size)
        active = m.inflight
        return {"mode": "predict",
                "queue_depth": m.queue_depth,
                "queue_max": m.queue_max,
                "active": active,
                "capacity": cap,
                "occupancy": round(active / cap, 4) if cap else 0.0,
                "draining": bool(self.batcher is not None
                                 and self.batcher.draining),
                "load": m.queue_depth + active,
                # shed total, so a fleet poller can aggregate per-
                # replica overload without parsing the full /stats
                "shed": m.shed}


class ServedGenerator:
    """One (causal LM, version) plus its continuous-batching generation
    engine — the token-by-token sibling of :class:`ServedModel`,
    routed at ``/v1/models/<name>/generate``."""

    def __init__(self, name: str, version: int, model,
                 num_slots: int = 8, max_queue: int = 256,
                 default_timeout_ms: float = 60_000.0, **engine_opts):
        # remaining GenerationEngine tuning (max_seq_len,
        # prompt_buckets, min_prompt_bucket, decode_impl, cache=
        # "slots"|"paged", block_size, num_blocks,
        # prefill_chunk_tokens, ...) passes through verbatim; unknown
        # keys fail loudly in the engine
        self.name = name
        self.version = int(version)
        self.model = model
        self.engine = GenerationEngine(
            model, num_slots=num_slots, max_queue=max_queue,
            default_timeout_ms=default_timeout_ms, **engine_opts)

    @property
    def metrics(self) -> GenerationMetrics:
        return self.engine.metrics

    def generate(self, prompt, **opts):
        return self.engine.generate(prompt, **opts)

    def stream(self, prompt, **opts):
        return self.engine.stream(prompt, **opts)

    def warmup(self, buckets: Optional[Sequence[int]] = None):
        return self.engine.warmup(buckets)

    def alive(self) -> bool:
        """Liveness (``/healthz``): the decode scheduler loop is not
        wedged (heartbeat watchdog in the engine)."""
        return self.engine.alive()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Reject new work (503 + Retry-After), let every in-flight
        generation finish, join the scheduler thread."""
        return self.engine.drain(timeout_s)

    def stop(self):
        self.engine.stop()

    def stats(self) -> Dict:
        s = self.metrics.snapshot()
        s["version"] = self.version
        s["model_class"] = type(self.model).__name__
        s["serving_mode"] = "generation"
        return s

    def summary(self) -> Dict:
        """Compact routing summary (see :meth:`ServedModel.summary`):
        for generation the live occupancy is ACTIVE KV-CACHE SLOTS —
        a request holds its slot for its whole decode lifetime, so
        slots are the capacity a router must balance."""
        m = self.metrics
        cap = m.num_slots
        active = m.active_slots
        return {"mode": "generation",
                "queue_depth": m.queue_depth,
                "queue_max": m.queue_max,
                "active": active,
                "capacity": cap,
                "occupancy": round(active / cap, 4) if cap else 0.0,
                "draining": self.engine.draining,
                "load": m.queue_depth + active,
                # shed total, so a fleet poller can aggregate per-
                # replica overload without parsing the full /stats
                "shed": m.shed}


class ModelRegistry:
    """register/get/unregister by name (+ version; default = latest)."""

    def __init__(self):
        self._models: Dict[str, Dict[int, ServedModel]] = {}
        self._lock = threading.Lock()

    def register(self, name: str, model,
                 version: Optional[int] = None, **opts) -> ServedModel:
        """Create the engine+batcher for ``model`` and route it at
        ``name``. ``version`` defaults to (latest + 1)."""
        return self._register(ServedModel, name, model, version, **opts)

    def register_generator(self, name: str, model,
                           version: Optional[int] = None,
                           **opts) -> ServedGenerator:
        """Create a continuous-batching generation engine for a causal
        LM and route it at ``/v1/models/<name>/generate``. Same
        name/version space as predict models — one name serves either
        mode, not both."""
        return self._register(ServedGenerator, name, model, version,
                              **opts)

    def _register(self, cls, name: str, model,
                  version: Optional[int] = None, **opts):
        if not name or not isinstance(name, str) or "/" in name \
                or "@" in name:
            # '/' breaks /v1/models/<name>/... routing (silent 404s);
            # '@' collides with the name@version keys stats() emits
            raise ValueError(f"invalid model name {name!r}: must be a "
                             "non-empty string without '/' or '@'")
        with self._lock:
            versions = self._models.setdefault(name, {})
            try:
                # one name serves ONE mode: silently flipping the
                # latest version from predict to generate (or back)
                # would 400 every existing client of the other route
                for existing in versions.values():
                    if type(existing) is not cls:
                        raise ValueError(
                            f"model {name!r} is already registered for "
                            f"{type(existing).__name__} serving — use a "
                            "different name for the other mode")
                if version is None:
                    version = max(versions) + 1 if versions else 1
                version = int(version)
                if version in versions:
                    raise ValueError(f"model {name!r} version {version} "
                                     "already registered")
                served = cls(name, version, model, **opts)
                versions[version] = served
                return served
            finally:
                # a failed construction must not leave an empty version
                # dict behind (it would break describe()/stats() forever)
                if not versions:
                    self._models.pop(name, None)

    def get(self, name: str, version: Optional[int] = None) -> ServedModel:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise ModelNotFound(f"unknown model {name!r}")
            if version is None:
                return versions[max(versions)]
            if int(version) not in versions:
                raise ModelNotFound(
                    f"model {name!r} has no version {version}")
            return versions[int(version)]

    def unregister(self, name: str, version: Optional[int] = None):
        """Remove (and stop) one version, or all versions of a name."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise ModelNotFound(f"unknown model {name!r}")
            if version is None:
                stopped = list(versions.values())
                del self._models[name]
            else:
                if int(version) not in versions:
                    raise ModelNotFound(
                        f"model {name!r} has no version {version}")
                stopped = [versions.pop(int(version))]
                if not versions:
                    del self._models[name]
        for served in stopped:
            served.stop()

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def describe(self) -> Dict:
        with self._lock:
            return {name: {"versions": sorted(vs),
                           "latest": max(vs)}
                    for name, vs in self._models.items()}

    def stats(self) -> Dict:
        """Latest version under the bare name; older versions that are
        still live (pinnable via request "version") under name@v, so
        their traffic stays observable."""
        with self._lock:
            items = []
            for name, vs in self._models.items():
                latest = max(vs)
                items.append((name, vs[latest]))
                items.extend((f"{name}@{v}", served)
                             for v, served in vs.items() if v != latest)
        return {key: served.stats() for key, served in items}

    def summary(self) -> Dict:
        """Per-model routing summaries, keyed like :meth:`stats`
        (latest under the bare name, older under name@v)."""
        with self._lock:
            items = []
            for name, vs in self._models.items():
                latest = max(vs)
                items.append((name, vs[latest]))
                items.extend((f"{name}@{v}", served)
                             for v, served in vs.items() if v != latest)
        return {key: served.summary() for key, served in items}

    def health(self) -> Dict[str, bool]:
        """Liveness per served model (``/healthz``), keyed like
        :meth:`stats` (latest under the bare name, older under
        name@v)."""
        with self._lock:
            items = []
            for name, vs in self._models.items():
                latest = max(vs)
                items.append((name, vs[latest]))
                items.extend((f"{name}@{v}", served)
                             for v, served in vs.items() if v != latest)
        return {key: served.alive() for key, served in items}

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Drain every served model CONCURRENTLY (sequential drains
        would stack their timeouts): each rejects new work with 503
        while its in-flight requests finish, then its scheduler thread
        joins. Models stay registered — `/stats` and `/healthz` remain
        queryable after the drain. Returns True when every model
        drained cleanly within ``timeout_s``."""
        with self._lock:
            served = [s for vs in self._models.values()
                      for s in vs.values()]
        results: Dict[int, bool] = {}

        def go(s):
            try:
                results[id(s)] = bool(s.drain(timeout_s))
            except Exception:  # noqa: BLE001 — a failed drain is dirty
                results[id(s)] = False
        threads = [threading.Thread(target=go, args=(s,), daemon=True)
                   for s in served]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout_s + 5.0)
        return all(results.get(id(s), False) for s in served)

    def stop(self):
        with self._lock:
            stopped = [s for vs in self._models.values()
                       for s in vs.values()]
            self._models.clear()
        for served in stopped:
            served.stop()
