"""Replica fleet tier: occupancy-aware routing, straggler hedging, and
zero-loss rolling restarts over N engine replicas.

One :class:`~.InferenceServer` is a replica, not a service. The
reference stack's distributed layer exists precisely to run one
logical workload across a churning fleet of workers (Spark training
master + Aeron parameter server, SURVEY §1); this module is the
serving-side equivalent: N in-process (or remote) ``InferenceServer``
replicas behind a :class:`FleetRouter`, hermetically testable on CPU
because the replicas already speak stdlib HTTP on loopback.

Layers::

    HTTP clients ──► FleetRouter ──► Replica (InferenceServer) x N
                        │               ▲
                        └── ReplicaFleet┘  (membership, health polls,
                                            cordon, rolling restart)

- :class:`ReplicaFleet` — membership + health. A poll loop reads each
  replica's ``GET /healthz`` and the compact ``summary`` block of
  ``GET /stats`` (live occupancy, queue depth, draining flag). A
  replica that fails ``eject_after`` consecutive polls — connection
  refused, or ``/healthz`` 503 because a scheduler loop is wedged —
  is EJECTED from routing; it is re-admitted automatically on the
  first clean poll. Draining replicas stay members (their in-flight
  work must finish) but stop receiving new work.
- :class:`FleetRouter` — request routing. Picks the eligible replica
  with the lowest occupancy score (router-local in-flight count plus
  the last-polled ``summary.load`` = queued + active rows/slots), NOT
  round-robin, so a replica bogged down by slow requests or direct
  traffic naturally stops attracting load. A 503 shed / draining
  answer or a connection failure is retried against another replica
  (the PR 4 ``Retry-After`` contract, finally honored by an actual
  peer); slow predicts are HEDGED: after ``hedge_after_ms`` with no
  response the same request is re-issued to a second replica and the
  first response wins, under a token-bucket retry budget so hedges
  can never amplify an overload (`The Tail at Scale`, PAPERS.md).
- Backpressure + circuit breaking (ISSUE 9): a 503 shed answer's
  ``Retry-After`` becomes a per-replica routing COOLDOWN (capped at
  ``cooldown_cap_s``) so the router stops hammering a replica that
  just said "back off" — instead of routing the very next request
  straight back at it. ``breaker_threshold`` CONSECUTIVE sheds trip a
  circuit breaker distinct from health ejection (the replica is alive
  and healthy, just overloaded): the breaker holds ``open`` for
  ``breaker_open_s``, then goes ``half_open`` and admits one probe
  request per window — a 2xx answer to a request dispatched AFTER
  the latest shed closes it (a 200 already in flight when the shed
  landed is stale evidence and changes nothing), another shed
  re-opens it. Counters: ``sheds``, ``cooldowns``, ``breaker_trips``,
  ``breaker_probes``, ``breaker_recoveries``, plus a ``goodput``
  ratio (responses/requests) in the snapshot.
- :meth:`ReplicaFleet.rolling_restart` — the fleet-wide extension of
  PR 4's single-replica zero-loss drain: one replica at a time is
  cordoned (router steers new work away), drained (in-flight work
  finishes), stopped, rebuilt via its ``factory``, health-checked,
  and re-admitted. Requests racing into the drain window get 503 +
  ``Retry-After`` from the replica and are transparently retried by
  the router against a live peer — the fleet as a whole loses zero
  accepted requests and, with deterministic seeds, returns
  bit-identical outputs to a restart-free run (test-asserted).

Everything is observable at the router's ``GET /stats``: per-replica
occupancy/state plus fleet counters (``requests``, ``responses``,
``hedges``/``hedges_won``/``hedge_budget_denied``, ``retries``,
``requests_lost``, ``ejections``, ``readmissions``, ``restarts``).

Docs: ``docs/serving.md`` "Running a fleet".
"""
from __future__ import annotations

import collections
import http.client
import json
import queue
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Set, Tuple
from urllib.parse import parse_qs

from ..faults import poll_until_idle
from ..profiler import Reservoir
from ..tracing import Tracer, new_request_id
from .engine import ServingError
from .metrics import prometheus_text

#: transport-level failures that justify trying another replica — the
#: predict path is stateless and generation is seed-deterministic, so
#: re-executing elsewhere is always semantically safe. NOTE: a socket
#: TIMEOUT (TimeoutError ⊂ OSError) is carved back out by the callers:
#: the replica is still WORKING on the request, so re-dispatching
#: would run it twice concurrently and penalize a healthy replica —
#: timeouts map to a terminal 504 instead
_RETRYABLE_EXC = (ConnectionError, OSError, http.client.HTTPException)


def _timeout_response(timeout_s: float):
    """Terminal (status, headers, body) for a router-side socket
    timeout: 504, never retried, never counted against the replica."""
    return (504, {}, json.dumps(
        {"error": f"no replica response within {timeout_s:g}s "
                  "(router socket timeout)"}).encode())

_JSON_HEADERS = {"Content-Type": "application/json"}


class FleetError(ServingError):
    """Fleet-level failure (no replica could take the request)."""


class NoReplicasError(FleetError):
    """No eligible replica is available (HTTP 503 + Retry-After)."""


def _get_json(host: str, port: int, path: str,
              timeout: float) -> Tuple[int, Dict]:
    """One GET on a fresh connection -> (status, parsed body or {})."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        raw = r.read()
        try:
            body = json.loads(raw) if raw else {}
        except ValueError:
            body = {}
        return r.status, body
    finally:
        conn.close()


class FleetMetrics:
    """Fleet-level counters (same threading discipline as
    :class:`~.metrics.ServingMetrics`: scalar counters via
    :meth:`inc`, never ``+=`` — HTTP handler threads, hedge arms, the
    poll loop, and rolling restarts all write here)."""

    def __init__(self, latency_window: int = 8192):
        self._lock = threading.Lock()
        self.requests = 0            # client requests entering the router
        self.responses = 0           # terminal 2xx returned
        self.client_errors = 0       # terminal 4xx passed through
        self.server_errors = 0       # terminal 5xx passed through
        self.routed = 0              # dispatch attempts to replicas
        self.retries = 0             # re-dispatches after 503/conn fail
        self.hedges = 0              # hedge arms launched
        self.hedges_won = 0          # hedge arm answered first
        self.hedge_budget_denied = 0  # hedge wanted, budget empty
        self.requests_lost = 0       # retryable failure, no replica left
        self.ejections = 0           # health-gated removals
        self.readmissions = 0        # recoveries back into routing
        self.restarts = 0            # rolling-restart cycles completed
        self.streams = 0             # streaming generations proxied
        self.sheds = 0               # 503 shed answers seen from replicas
        self.cooldowns = 0           # Retry-After cooldowns activated
        self.breaker_trips = 0       # closed -> open transitions
        self.breaker_probes = 0      # half-open probe requests admitted
        self.breaker_recoveries = 0  # open/half-open -> closed
        self.session_affinity_hits = 0  # session routed to its replica
        self.latency_ms = Reservoir(latency_window)

    def inc(self, field: str, n: int = 1):
        """Thread-safe counter increment."""
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> Dict:
        return {
            "requests": self.requests,
            "responses": self.responses,
            "client_errors": self.client_errors,
            "server_errors": self.server_errors,
            "routed": self.routed,
            "retries": self.retries,
            "hedges": self.hedges,
            "hedges_won": self.hedges_won,
            "hedge_budget_denied": self.hedge_budget_denied,
            "requests_lost": self.requests_lost,
            "ejections": self.ejections,
            "readmissions": self.readmissions,
            "restarts": self.restarts,
            "streams": self.streams,
            "sheds": self.sheds,
            "cooldowns": self.cooldowns,
            "breaker_trips": self.breaker_trips,
            "breaker_probes": self.breaker_probes,
            "breaker_recoveries": self.breaker_recoveries,
            "session_affinity_hits": self.session_affinity_hits,
            # share of accepted requests that came back 2xx — the
            # overload-robustness headline: under graceful shedding
            # this stays near 1.0 for ADMITTED work even at 2x load
            "goodput": round(self.responses / self.requests, 4)
            if self.requests else 1.0,
            "latency_ms": {k: round(v, 3) for k, v in
                           self.latency_ms.snapshot().items()},
        }


class Replica:
    """One fleet member: address + live routing state.

    In-process replicas carry their :class:`~.InferenceServer` in
    ``server`` and (for rolling restarts) a zero-arg ``factory`` that
    builds a fresh, warmed server. Remote replicas are just
    (host, port) — they participate in routing and health but cannot
    be restarted by :meth:`ReplicaFleet.rolling_restart`.
    """

    def __init__(self, replica_id: str, host: str, port: int,
                 server=None, factory: Optional[Callable[[], Any]] = None):
        self.id = replica_id
        self.host = host
        self.port = int(port)
        self.server = server
        self.factory = factory
        self._lock = threading.Lock()
        # membership state (poll loop + router failure notes mutate it)
        self.admitted = True      # health-gated: False = ejected
        self.cordoned = False     # operator/rolling-restart exclusion
        self.ready = True         # replica-side readiness (draining?)
        self.fails = 0            # consecutive failed polls/dispatches
        self.ejected_ever = False
        # routing state
        self.in_flight = 0        # router-tracked live dispatches
        self.routed = 0           # total dispatches sent here
        self.summary: Dict = {}   # last-polled /stats summary block
        self.last_poll: Optional[float] = None
        # backpressure state (distinct from health: the replica is
        # alive, it just told us to back off)
        self.cooldown_until = 0.0    # Retry-After routing exclusion
        self.shed_at = 0.0           # monotonic time of the last shed
        self.consecutive_sheds = 0   # 503 streak -> trips the breaker
        self.breaker_tripped = False
        self.breaker_until = 0.0     # open until; half-open after
        self.probe_at = 0.0          # last half-open probe launch

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def eligible(self) -> bool:
        """May receive NEW work right now (health/membership view —
        backpressure is layered on top, see
        :meth:`ReplicaFleet.routable`)."""
        return self.admitted and not self.cordoned and self.ready

    def breaker_state(self, now: Optional[float] = None) -> str:
        """``closed`` | ``open`` | ``half_open``."""
        if not self.breaker_tripped:
            return "closed"
        now = time.monotonic() if now is None else now
        return "open" if now < self.breaker_until else "half_open"

    def reset_backpressure(self):
        """Forget cooldown/breaker state — a rebuilt replica (rolling
        restart) starts with a clean slate; its old overload history
        belongs to the process that no longer exists. Caller must NOT
        hold ``_lock``."""
        with self._lock:
            self.cooldown_until = 0.0
            self.shed_at = 0.0
            self.consecutive_sheds = 0
            self.breaker_tripped = False
            self.breaker_until = 0.0
            self.probe_at = 0.0

    def score(self) -> int:
        """Occupancy score the router minimizes: the router's own
        live in-flight count (instantaneous) plus the replica's
        last-polled ``summary.load`` (queued + active rows/slots —
        includes traffic from other routers or direct clients). The
        two overlap while a poll is stale; the ordering they induce is
        what matters, not the absolute value."""
        return self.in_flight + int(self.summary.get("load", 0))

    def begin(self):
        with self._lock:
            self.in_flight += 1
            self.routed += 1

    def end(self):
        with self._lock:
            self.in_flight -= 1

    def snapshot(self) -> Dict:
        now = time.monotonic()
        with self._lock:
            return {
                "id": self.id,
                "address": self.address,
                "admitted": self.admitted,
                "cordoned": self.cordoned,
                "ready": self.ready,
                "eligible": self.eligible(),
                "fails": self.fails,
                "in_flight": self.in_flight,
                "requests_routed": self.routed,
                "score": self.in_flight + int(self.summary.get("load", 0)),
                "breaker": self.breaker_state(now),
                "cooling": now < self.cooldown_until,
                "consecutive_sheds": self.consecutive_sheds,
                "summary": self.summary,
            }


class ReplicaFleet:
    """Membership + health for a set of replicas.

    ``poll_interval_s`` drives the background health loop (pass
    ``None`` to disable it and call :meth:`poll_now` explicitly —
    deterministic tests do). ``eject_after`` consecutive failed polls
    (connection failure or a wedged ``/healthz``) eject a replica from
    routing; the first clean poll re-admits it.

    Backpressure knobs: ``breaker_threshold`` consecutive 503 sheds
    trip a replica's circuit breaker; it holds open ``breaker_open_s``
    then admits one half-open probe per window. A shed's Retry-After
    is honored as a routing cooldown, capped at ``cooldown_cap_s`` so
    a replica advertising a huge backoff cannot exile itself.
    """

    def __init__(self, poll_interval_s: Optional[float] = 0.25,
                 eject_after: int = 2, probe_timeout_s: float = 5.0,
                 breaker_threshold: int = 3, breaker_open_s: float = 1.0,
                 cooldown_cap_s: float = 5.0):
        self.metrics = FleetMetrics()
        self.eject_after = int(eject_after)
        self.probe_timeout_s = float(probe_timeout_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_open_s = float(breaker_open_s)
        self.cooldown_cap_s = float(cooldown_cap_s)
        self.poll_interval_s = poll_interval_s
        self._lock = threading.Lock()
        self._replicas: List[Replica] = []
        self._next_id = 0
        self._running = True
        self._poll_thread: Optional[threading.Thread] = None
        if poll_interval_s is not None:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True, name="fleet-health")
            self._poll_thread.start()

    # -- membership ----------------------------------------------------
    def add(self, server=None, host: Optional[str] = None,
            port: Optional[int] = None,
            factory: Optional[Callable[[], Any]] = None,
            replica_id: Optional[str] = None) -> Replica:
        """Register a replica: an in-process ``InferenceServer`` (pass
        ``server=``, plus ``factory=`` to make it restartable), or a
        remote one (pass ``host=``/``port=``)."""
        if server is not None:
            host, port = server.host, server.port
        if host is None or port is None:
            raise ValueError("pass server= or host=/port=")
        with self._lock:
            if replica_id is None:
                replica_id = f"r{self._next_id}"
                self._next_id += 1
            if any(r.id == replica_id for r in self._replicas):
                raise ValueError(f"replica id {replica_id!r} already "
                                 "registered")
            rep = Replica(replica_id, host, port, server=server,
                          factory=factory)
            self._replicas.append(rep)
            return rep

    def remove(self, replica_id: str) -> Replica:
        with self._lock:
            for i, r in enumerate(self._replicas):
                if r.id == replica_id:
                    return self._replicas.pop(i)
        raise KeyError(f"unknown replica {replica_id!r}")

    def get(self, replica_id: str) -> Replica:
        with self._lock:
            for r in self._replicas:
                if r.id == replica_id:
                    return r
        raise KeyError(f"unknown replica {replica_id!r}")

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    def eligible(self) -> List[Replica]:
        return [r for r in self.replicas() if r.eligible()]

    def cordon(self, replica_id: str):
        """Exclude a replica from NEW work (in-flight work finishes);
        the rolling restart's first move, also useful by hand."""
        self.get(replica_id).cordoned = True

    def uncordon(self, replica_id: str):
        self.get(replica_id).cordoned = False

    # -- health --------------------------------------------------------
    def _poll_loop(self):
        while self._running:
            try:
                self.poll_now()
            except Exception:   # noqa: BLE001 — health must not die
                pass
            time.sleep(self.poll_interval_s)

    def poll_now(self):
        """One synchronous health/occupancy sweep over every replica
        (the background loop calls this; tests and operators can too
        for a deterministic refresh)."""
        for rep in self.replicas():
            self._poll_replica(rep)

    def _poll_replica(self, rep: Replica):
        ok = False
        summary: Dict = {}
        try:
            hz, _ = _get_json(rep.host, rep.port, "/healthz",
                              self.probe_timeout_s)
            st, stats = _get_json(rep.host, rep.port, "/stats",
                                  self.probe_timeout_s)
            # a wedged scheduler (healthz 503) is as ejectable as a
            # dead socket; /stats failing means we can't route on it
            ok = hz == 200 and st == 200
            if ok:
                summary = stats.get("summary") or {}
        except _RETRYABLE_EXC:
            ok = False
        rep.last_poll = time.monotonic()
        if ok:
            readmit = False
            with rep._lock:
                rep.summary = summary
                rep.ready = bool(summary.get("ready", True))
                rep.fails = 0
                if not rep.admitted:
                    rep.admitted = True
                    readmit = True
            if readmit:
                self.metrics.inc("readmissions")
        elif not rep.cordoned:
            # a cordoned replica is EXPECTED to be dark (it is being
            # restarted); counting that window as an ejection would
            # turn every rolling restart into a fake health incident
            self.note_failure(rep)

    def note_failure(self, rep: Replica):
        """Record one failed contact (poll or live dispatch); ejects
        after ``eject_after`` consecutive failures. The router calls
        this on connection errors so ejection doesn't wait for the
        next poll tick. Cordoned replicas are exempt here too: a
        racer that picked the victim just before the cordon and then
        hit its dead port must not turn a rolling restart into a
        fake ejection."""
        if rep.cordoned:
            return
        eject = False
        with rep._lock:
            rep.fails += 1
            if rep.admitted and rep.fails >= self.eject_after:
                rep.admitted = False
                rep.ejected_ever = True
                eject = True
        if eject:
            self.metrics.inc("ejections")

    # -- backpressure / circuit breaking -------------------------------
    def routable(self, rep: Replica,
                 now: Optional[float] = None) -> bool:
        """May this replica receive a request RIGHT NOW? Eligibility
        (health/cordon/ready) AND not in a Retry-After cooldown AND
        the breaker admits traffic. ``half_open`` answers True only
        while the current window's probe slot is unclaimed — the
        router must then :meth:`claim_probe` before dispatching."""
        if not rep.eligible():
            return False
        now = time.monotonic() if now is None else now
        if now < rep.cooldown_until:
            return False
        state = rep.breaker_state(now)
        if state == "open":
            return False
        if state == "half_open":
            return now - rep.probe_at >= self.breaker_open_s
        return True

    def claim_probe(self, rep: Replica,
                    now: Optional[float] = None) -> bool:
        """Atomically claim the half-open probe slot (one probe per
        ``breaker_open_s`` window); False means another thread beat
        us to it and THIS request should pick elsewhere."""
        now = time.monotonic() if now is None else now
        with rep._lock:
            if now - rep.probe_at < self.breaker_open_s:
                return False
            rep.probe_at = now
        self.metrics.inc("breaker_probes")
        return True

    def note_shed(self, rep: Replica,
                  retry_after_s: Optional[float] = None):
        """A 503 shed came back from this replica: honor Retry-After
        as a routing cooldown (capped) and count one strike toward
        the breaker. A shed while the breaker is already tripped —
        a failed half-open probe — re-opens the window."""
        now = time.monotonic()
        try:
            cooldown = float(retry_after_s)
        except (TypeError, ValueError):
            cooldown = 1.0
        cooldown = min(max(cooldown, 0.0), self.cooldown_cap_s)
        tripped = False
        with rep._lock:
            was_cooling = now < rep.cooldown_until
            rep.cooldown_until = max(rep.cooldown_until, now + cooldown)
            rep.shed_at = now
            rep.consecutive_sheds += 1
            if rep.breaker_tripped:
                rep.breaker_until = now + self.breaker_open_s
            elif rep.consecutive_sheds >= self.breaker_threshold:
                rep.breaker_tripped = True
                rep.breaker_until = now + self.breaker_open_s
                tripped = True
        self.metrics.inc("sheds")
        if not was_cooling:
            self.metrics.inc("cooldowns")
        if tripped:
            self.metrics.inc("breaker_trips")

    def note_ok(self, rep: Replica,
                dispatched_at: Optional[float] = None):
        """A 2xx answer from this replica: the shed streak is broken;
        a tripped breaker closes (successful half-open probe); any
        residual cooldown is lifted — the replica is demonstrably
        serving again. ``dispatched_at`` (``time.monotonic()`` at
        send time) guards against stale evidence: a 200 for a request
        dispatched BEFORE the replica's latest shed was admitted
        before the overload signal and proves nothing — it must not
        cancel a fresh cooldown and route traffic straight back."""
        recovered = False
        with rep._lock:
            if dispatched_at is not None and dispatched_at < rep.shed_at:
                return
            rep.consecutive_sheds = 0
            rep.cooldown_until = 0.0
            if rep.breaker_tripped:
                rep.breaker_tripped = False
                rep.breaker_until = 0.0
                recovered = True
        if recovered:
            self.metrics.inc("breaker_recoveries")

    # -- rolling restart ----------------------------------------------
    def rolling_restart(self, drain_timeout_s: float = 30.0,
                        ready_timeout_s: float = 120.0) -> bool:
        """Restart every restartable replica ONE AT A TIME with zero
        accepted-request loss: cordon (router steers new work away,
        racers get 503 + Retry-After and are retried elsewhere), wait
        for router-tracked in-flight work to finish, ``drain()`` +
        ``stop()`` the server, rebuild it via ``factory`` (which
        should warm the new server before returning), wait until the
        new process answers ``/readyz`` and ``/healthz``, re-admit,
        uncordon, move on. Replicas without a ``factory`` (remote, or
        added without one) are skipped. Returns True when every
        restarted replica drained cleanly and came back ready within
        its budget."""
        ok_all = True
        for rep in self.replicas():
            if rep.factory is None:
                continue
            self.cordon(rep.id)
            try:
                # the router decrements in_flight only after a
                # replica's response is fully back, so this wait plus
                # the server-side drain covers every accepted request
                poll_until_idle(lambda: rep.in_flight == 0,
                                drain_timeout_s)
                clean = True
                if rep.server is not None:
                    clean = bool(rep.server.drain(drain_timeout_s))
                    rep.server.stop()
                new = rep.factory()
                with rep._lock:
                    rep.server = new
                    rep.host = new.host
                    rep.port = int(new.port)
                    rep.summary = {}
                ready = self._wait_ready(rep, ready_timeout_s)
                # the rebuilt process never shed anything: start it
                # with a clean cooldown/breaker slate
                rep.reset_backpressure()
                with rep._lock:
                    rep.fails = 0
                    # a replacement that never answered /readyz within
                    # its budget must NOT be force-admitted: leave it
                    # ejected (the poll loop re-admits the moment it
                    # comes good; without a poll loop the False return
                    # is the operator's signal)
                    rep.admitted = ready
                    rep.ready = ready
                    if not ready:
                        rep.ejected_ever = True
                self.metrics.inc("restarts")
                if not ready:
                    self.metrics.inc("ejections")
                ok_all = ok_all and clean and ready
            except Exception:   # noqa: BLE001 — a failed rebuild
                # (factory raise, drain blow-up) must not leave a
                # dead address looking eligible, and must not abort
                # the restarts of the replicas AFTER this one
                with rep._lock:
                    rep.admitted = False
                    rep.ready = False
                    rep.ejected_ever = True
                self.metrics.inc("ejections")
                ok_all = False
            finally:
                self.uncordon(rep.id)
        return ok_all

    def _wait_ready(self, rep: Replica, timeout_s: float) -> bool:
        def probe() -> bool:
            try:
                rz, _ = _get_json(rep.host, rep.port, "/readyz",
                                  self.probe_timeout_s)
                hz, _ = _get_json(rep.host, rep.port, "/healthz",
                                  self.probe_timeout_s)
                return rz == 200 and hz == 200
            except _RETRYABLE_EXC:
                return False
        return poll_until_idle(probe, timeout_s, quiet_obs=1)

    def snapshot(self) -> Dict:
        reps = [r.snapshot() for r in self.replicas()]
        s = self.metrics.snapshot()
        s["replicas"] = reps
        s["eligible_replicas"] = sum(1 for r in reps if r["eligible"])
        s["fleet_load"] = sum(r["score"] for r in reps)
        # replica-side shed totals (from the polled summaries) — the
        # fleet-wide view of admission-control pressure, including
        # sheds served to clients that bypassed this router
        s["fleet_shed"] = sum(int(r["summary"].get("shed", 0) or 0)
                              for r in reps)
        return s

    def stop(self, stop_replicas: bool = False):
        self._running = False
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
        if stop_replicas:
            for rep in self.replicas():
                if rep.server is not None:
                    rep.server.stop()


class _FleetStream:
    """Iterator over a proxied ndjson stream. :meth:`close` (also run
    by ``__del__`` and on exhaustion) closes the upstream connection —
    aborting the generation and freeing the backing replica's
    slot/blocks — and releases the router's in-flight count. A bare
    generator could leak the in-flight count if abandoned before the
    first ``next()``; this class cannot."""

    def __init__(self, rep: Replica, conn, resp):
        self._rep = rep
        self._conn = conn
        self._resp = resp
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> Dict:
        if self._closed:
            raise StopIteration
        try:
            line = self._resp.readline()
            while line and not line.strip():
                line = self._resp.readline()
        except Exception:
            self.close()
            raise
        if not line:
            self.close()
            raise StopIteration
        return json.loads(line)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._conn.close()
        self._rep.end()

    def __del__(self):
        self.close()


class _ConnPool:
    """Keep-alive HTTP connections to replicas, checked out per
    request (one connection is never shared by two threads at once).
    Bounded per address; a restarted replica usually changes port, and
    a stale keep-alive on the same port surfaces as a retryable error
    handled by the caller."""

    def __init__(self, timeout_s: float, max_per_key: int = 32):
        self._lock = threading.Lock()
        self._idle: Dict[Tuple[str, int], List] = {}
        self.timeout_s = float(timeout_s)
        self.max_per_key = int(max_per_key)

    def take(self, host: str, port: int):
        with self._lock:
            stack = self._idle.get((host, port))
            if stack:
                return stack.pop()
        return http.client.HTTPConnection(host, port,
                                          timeout=self.timeout_s)

    def give(self, host: str, port: int, conn):
        with self._lock:
            stack = self._idle.setdefault((host, port), [])
            if len(stack) < self.max_per_key:
                stack.append(conn)
                return
        conn.close()

    def prune(self, live_keys):
        """Close and drop idle connections to addresses no longer in
        the fleet — every rolling restart moves a replica to a fresh
        ephemeral port, and without pruning the old address' stack
        would strand up to ``max_per_key`` open sockets forever."""
        with self._lock:
            dead = [k for k in self._idle if k not in live_keys]
            stacks = [self._idle.pop(k) for k in dead]
        for stack in stacks:
            for conn in stack:
                conn.close()

    def close_all(self):
        with self._lock:
            stacks, self._idle = self._idle, {}
        for stack in stacks.values():
            for conn in stack:
                conn.close()


class FleetRouter:
    """Occupancy-aware request router over a :class:`ReplicaFleet`.

    Python surface: :meth:`post` (predict/generate JSON in, (status,
    body) out), :meth:`stream` (streamed generation as an iterator of
    parsed ndjson objects), :meth:`stats`. HTTP surface (optional,
    :meth:`serve`): the same route table as one replica — ``POST
    /predict``, ``/generate``, ``/v1/models/<name>/predict|generate``
    — plus fleet-level ``GET /stats``, ``/healthz``, ``/readyz``,
    and a proxied ``GET /v1/models``, so a fleet drops in wherever a
    single replica stood.

    Hedging (predict only — it is stateless, so duplicating work is
    always safe): when the chosen replica hasn't answered within
    ``hedge_after_ms``, the SAME request is issued to the
    next-best replica and the first response wins. A token bucket
    caps amplification: ``hedge_budget_burst`` tokens to start,
    refilled ``hedge_budget_ratio`` per completed request, one token
    per hedge — so hedges can never exceed ``burst + ratio *
    requests`` no matter how sick the fleet is. ``hedge_after_ms=None``
    (default) disables hedging.

    Shed retry: a 503 (queue full / draining) or a connection failure
    excludes that replica for this request and retries the next-best
    one, up to ``max_attempts`` (default: every currently-eligible
    replica once). Only transport-level and shed failures are
    retried; 400/404/500/504 are the request's own fate and pass
    through unchanged.

    ``hedge_generate=True`` extends hedging to non-streaming generate
    requests — generation is seed-deterministic, so a duplicated
    dispatch wastes decode steps but never changes the answer.
    ``cooldown_wait_s>0`` lets a request that found every replica in
    a Retry-After cooldown WAIT (bounded, once) for the nearest
    cooldown to lapse instead of failing straight to 503.

    Tracing (``tracing=True``, docs/observability.md): router-side
    spans — ``pick``, ``cooldown_wait``, ``dispatch``, ``retry``,
    ``hedge`` — are recorded under the propagated request id, so one
    trace stitches the router's view onto the winning replica's
    queue/admission/prefill/decode spans. Hedge arms share the trace
    id with distinct span ids; the losing arm is marked
    ``discarded``.
    """

    def __init__(self, fleet: ReplicaFleet,
                 hedge_after_ms: Optional[float] = None,
                 hedge_budget_ratio: float = 0.1,
                 hedge_budget_burst: float = 4.0,
                 max_attempts: Optional[int] = None,
                 timeout_s: float = 60.0,
                 hedge_generate: bool = False,
                 cooldown_wait_s: float = 0.0,
                 tracing: bool = False,
                 trace_ring: int = 256,
                 trace_slow_ms: float = 1000.0):
        self.fleet = fleet
        self.metrics = fleet.metrics
        self.hedge_after_ms = (None if hedge_after_ms is None
                               else float(hedge_after_ms))
        self.hedge_budget_ratio = float(hedge_budget_ratio)
        self.hedge_budget_burst = float(hedge_budget_burst)
        self.max_attempts = max_attempts
        self.timeout_s = float(timeout_s)
        self.hedge_generate = bool(hedge_generate)
        self.cooldown_wait_s = float(cooldown_wait_s)
        self.tracer = Tracer(enabled=bool(tracing), ring=trace_ring,
                             slow_ms=trace_slow_ms)
        self._log_stream = None
        self._log_lock = threading.Lock()
        self._budget_lock = threading.Lock()
        self._budget = self.hedge_budget_burst
        self._pool = _ConnPool(timeout_s)
        self._live_addrs: Set[Tuple[str, int]] = set()
        self._rr = 0               # tie-break rotation among equals
        self._rr_lock = threading.Lock()
        # session affinity: session_id -> replica id, LRU-bounded. A
        # session's KV blocks live on ONE replica (its session store),
        # so routing the next turn there is the difference between a
        # prefix hit and a full re-prefill. Advisory only: when the
        # mapped replica is unroutable the request falls back to the
        # normal pick and the session re-pins wherever it lands.
        self._affinity: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self._affinity_cap = 4096
        self._affinity_lock = threading.Lock()
        self.httpd = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        self._http_thread: Optional[threading.Thread] = None
        self._aio = None

    # -- replica selection --------------------------------------------
    def _pick(self, excluded: Set[str],
              prefer: Optional[str] = None) -> Optional[Replica]:
        reps = self.fleet.replicas()
        addrs = {(r.host, r.port) for r in reps}
        if addrs != self._live_addrs:
            # membership/port change (restart, eject+rebuild): drop
            # pooled keep-alives to addresses that no longer exist
            self._live_addrs = addrs
            self._pool.prune(addrs)
        now = time.monotonic()
        cands = [r for r in reps
                 if r.id not in excluded and self.fleet.routable(r, now)]
        if not cands:
            return None
        if prefer is not None:
            # session affinity: the preferred replica holds this
            # session's KV blocks — take it whenever it is routable,
            # bypassing the occupancy score (a warm prefix beats a
            # marginally shorter queue)
            for r in cands:
                if r.id != prefer:
                    continue
                if r.breaker_state(now) != "half_open" \
                        or self.fleet.claim_probe(r, now):
                    self.metrics.inc("session_affinity_hits")
                    return r
                break
        with self._rr_lock:
            self._rr += 1
            base = self._rr
        # min occupancy score; rotate among score ties so equal
        # replicas share load instead of the list head taking it all
        n = len(cands)
        best = min(range(n),
                   key=lambda i: (cands[i].score(), (i + base) % n))
        rep = cands[best]
        if rep.breaker_state(now) == "half_open" \
                and not self.fleet.claim_probe(rep, now):
            # another thread took this window's probe slot; this
            # request must look elsewhere (bounded: each recursion
            # excludes one replica)
            return self._pick(excluded | {rep.id})
        return rep

    # -- session affinity ---------------------------------------------
    def _affinity_get(self, session: Optional[str]) -> Optional[str]:
        if session is None:
            return None
        with self._affinity_lock:
            rid = self._affinity.get(session)
            if rid is not None:
                self._affinity.move_to_end(session)
            return rid

    def _affinity_note(self, session: Optional[str], rep_id: str):
        if session is None:
            return
        with self._affinity_lock:
            self._affinity[session] = rep_id
            self._affinity.move_to_end(session)
            while len(self._affinity) > self._affinity_cap:
                self._affinity.popitem(last=False)

    # -- hedge budget --------------------------------------------------
    def _take_budget(self) -> bool:
        with self._budget_lock:
            if self._budget >= 1.0:
                self._budget -= 1.0
                return True
        self.metrics.inc("hedge_budget_denied")
        return False

    def _refill_budget(self):
        with self._budget_lock:
            self._budget = min(self.hedge_budget_burst,
                               self._budget + self.hedge_budget_ratio)

    # -- transport -----------------------------------------------------
    def _roundtrip(self, rep: Replica, path: str, body: bytes,
                   headers: Dict = None):
        """One POST to one replica -> (status, headers, data). Retries
        exactly once on a stale keep-alive connection; raises a
        retryable exception when the replica is genuinely
        unreachable."""
        send = (_JSON_HEADERS if not headers
                else {**_JSON_HEADERS, **headers})
        for fresh in (False, True):
            conn = (http.client.HTTPConnection(rep.host, rep.port,
                                               timeout=self.timeout_s)
                    if fresh else self._pool.take(rep.host, rep.port))
            try:
                conn.request("POST", path, body=body,
                             headers=send)
                resp = conn.getresponse()
                data = resp.read()
            except _RETRYABLE_EXC as e:
                conn.close()
                # a timeout means the replica is still computing —
                # retrying on a fresh connection would double the work
                if fresh or isinstance(e, TimeoutError):
                    raise
                continue
            self._pool.give(rep.host, rep.port, conn)
            return resp.status, dict(resp.getheaders()), data
        raise ConnectionError("unreachable")   # not reached

    def _tracked(self, rep: Replica, path: str, body: bytes,
                 headers: Dict = None):
        rep.begin()
        self.metrics.inc("routed")
        try:
            return self._roundtrip(rep, path, body, headers)
        finally:
            rep.end()

    @staticmethod
    def _retryable(out) -> bool:
        """A result worth trying another replica for: transport
        failure, or an explicit shed/draining 503."""
        return isinstance(out, Exception) or out[0] == 503

    def _note(self, rep: Replica, status: int, hdrs: Dict,
              dispatched_at: Optional[float] = None):
        """Feed the backpressure loop from one replica answer: a 503
        becomes a Retry-After cooldown + breaker strike; a 2xx to a
        request dispatched AFTER the latest shed breaks the streak
        (and closes a tripped breaker). Anything else — 4xx, 500,
        504, or a 200 for a request already in flight when the shed
        landed — proves neither overload nor recovery and leaves the
        backpressure state alone."""
        if status == 503:
            self.fleet.note_shed(rep, hdrs.get("Retry-After"))
        elif 200 <= status < 300:
            self.fleet.note_ok(rep, dispatched_at)

    # -- dispatch ------------------------------------------------------
    def post(self, path: str, payload) -> Tuple[int, Dict]:
        """Route one JSON request; returns (status, parsed body).
        Retries sheds/connection failures against other replicas;
        hedges slow predicts. 503 with no replica left to try counts
        as ``requests_lost``. A generate payload carrying
        ``session_id`` is routed with session affinity — towards the
        replica whose session store pinned that conversation's KV
        blocks."""
        session = (payload.get("session_id")
                   if isinstance(payload, dict) else None)
        if not isinstance(session, str) or not session:
            session = None
        status, _hdrs, data = self.post_raw(path,
                                            json.dumps(payload).encode(),
                                            session=session)
        try:
            body = json.loads(data) if data else {}
        except ValueError:
            body = {"error": "unparseable replica response"}
        return status, body

    def post_raw(self, path: str, body: bytes, headers: Dict = None,
                 trace=None, session: Optional[str] = None):
        """Bytes-in/bytes-out dispatch (the HTTP front-end's path):
        returns (status, response headers, response bytes).
        ``headers`` are forwarded to the replica on top of the JSON
        content type — the front-end uses this so request-scoped
        classification (``X-Priority``) survives the proxy hop, and
        ``X-Request-Id`` stitches router and replica traces. When the
        router's tracer is on and no ``trace`` was passed (library
        callers), a trace is minted here under the forwarded request
        id."""
        owned = None
        if trace is None:
            trace = owned = self.tracer.begin(
                (headers or {}).get("X-Request-Id"))
        out = self._dispatch(path, body, headers, trace, session)
        if owned is not None:
            self.tracer.finish(owned, error=out[0] >= 500)
        return out

    def _dispatch(self, path: str, body: bytes, headers: Dict,
                  trace, session: Optional[str] = None):
        self.metrics.inc("requests")
        is_gen = (path.rstrip("/").endswith("/generate")
                  or path == "/generate")
        hedge = (self.hedge_after_ms is not None
                 and (self.hedge_generate or not is_gen))
        t0 = time.perf_counter()
        excluded: Set[str] = set()
        last = None
        attempts = 0
        waited = False
        prefer = self._affinity_get(session)
        max_attempts = self.max_attempts or max(1, len(self.fleet.eligible()))
        while attempts < max_attempts:
            t_pick = time.perf_counter()
            rep = self._pick(excluded, prefer=prefer)
            if rep is None:
                if waited or self.cooldown_wait_s <= 0:
                    break
                # nothing routable RIGHT NOW — but a replica merely in
                # a Retry-After cooldown will take work again shortly;
                # wait (bounded, once per request) instead of failing
                waited = True
                wait_s = self._cooldown_remaining(excluded)
                if wait_s is None:
                    break
                wait_s = min(wait_s, self.cooldown_wait_s)
                t_w = time.perf_counter()
                time.sleep(wait_s)
                if trace is not None:
                    trace.span("cooldown_wait", t_start=t_w,
                               t_end=time.perf_counter())
                continue
            if trace is not None:
                trace.span("pick", t_start=t_pick,
                           t_end=time.perf_counter(), replica=rep.id,
                           attempt=attempts + 1)
            attempts += 1
            if attempts > 1:
                self.metrics.inc("retries")
                if trace is not None:
                    trace.span("retry", attempt=attempts,
                               replica=rep.id).end()
            out = (self._attempt_hedged(rep, path, body, excluded,
                                        headers, trace)
                   if hedge else self._attempt_plain(rep, path, body,
                                                     excluded, headers,
                                                     trace))
            if self._retryable(out):
                last = out
                continue
            status, hdrs, data = out
            self._refill_budget()
            self.metrics.latency_ms.record(
                (time.perf_counter() - t0) * 1e3)
            if 200 <= status < 300:
                self.metrics.inc("responses")
                # the finished turn's blocks are pinned on THIS
                # replica: steer the session's next turn back here
                self._affinity_note(session, rep.id)
            elif status < 500:
                self.metrics.inc("client_errors")
            else:
                self.metrics.inc("server_errors")
            return status, hdrs, data
        # every eligible replica shed or failed: the request is LOST
        # from the fleet's point of view (the client may retry later)
        self._refill_budget()
        self.metrics.inc("requests_lost")
        if isinstance(last, tuple):
            status, hdrs, data = last
            hdrs.setdefault("Retry-After", "1")
            return status, hdrs, data
        return 503, {"Retry-After": "1"}, json.dumps(
            {"error": "no replica available"}).encode()

    def _cooldown_remaining(self, excluded: Set[str]) -> Optional[float]:
        """Seconds until the NEAREST cooled-down (but otherwise
        eligible) replica becomes routable again; None when no
        replica is merely cooling — waiting would not help."""
        now = time.monotonic()
        best = None
        for rep in self.fleet.replicas():
            if rep.id in excluded or not rep.eligible():
                continue
            left = rep.cooldown_until - now
            if left > 0 and (best is None or left < best):
                best = left
        return best

    def _attempt_plain(self, rep: Replica, path: str, body: bytes,
                       excluded: Set[str], headers: Dict = None,
                       trace=None):
        """Single-arm dispatch in the calling thread."""
        t_dispatch = time.monotonic()
        span = (trace.span("dispatch", replica=rep.id)
                if trace is not None else None)
        try:
            out = self._tracked(rep, path, body, headers)
        except _RETRYABLE_EXC as e:
            if isinstance(e, TimeoutError):
                # the replica is still working — re-dispatching would
                # run the request twice and smear a healthy replica
                if span is not None:
                    span.end(status=504, error="socket timeout")
                return _timeout_response(self.timeout_s)
            self.fleet.note_failure(rep)
            excluded.add(rep.id)
            if span is not None:
                span.end(error=f"{type(e).__name__}: {e}")
            return e
        self._note(rep, out[0], out[1], t_dispatch)
        if span is not None:
            span.end(status=out[0])
        if out[0] == 503:
            excluded.add(rep.id)
        return out

    def _attempt_hedged(self, rep: Replica, path: str, body: bytes,
                        excluded: Set[str], headers: Dict = None,
                        trace=None):
        """Primary dispatch with an optional hedge arm: wait
        ``hedge_after_ms`` for the primary; if silent, re-issue to the
        next-best replica (budget permitting) and take whichever
        answers first. Returns the winning (status, headers, data),
        or a retryable failure when every launched arm failed.

        Both arms record spans on the SAME trace (span ids are
        per-trace atomic, so the concurrent arms need no extra
        locking); after the race the losing arm's span is marked
        ``discarded`` — the waste the hedge budget bounds, visible
        per-request."""
        results: "queue.Queue" = queue.Queue()
        spans: Dict[str, Any] = {}

        def run(r: Replica, kind: str):
            t_dispatch = time.monotonic()
            span = None
            if trace is not None:
                span = trace.span(kind, replica=r.id)
                spans[r.id] = span
            try:
                out = self._tracked(r, path, body, headers)
                self._note(r, out[0], out[1], t_dispatch)
                if span is not None:
                    span.end(status=out[0])
            except _RETRYABLE_EXC as e:
                if isinstance(e, TimeoutError):
                    out = _timeout_response(self.timeout_s)
                else:
                    self.fleet.note_failure(r)
                    out = e
                if span is not None:
                    span.end(error=f"{type(e).__name__}: {e}")
            results.put((r, out))

        threading.Thread(target=run, args=(rep, "dispatch"),
                         daemon=True, name="fleet-primary").start()
        arms = 1
        hedged_to = None
        first = None
        try:
            first = results.get(timeout=self.hedge_after_ms / 1e3)
        except queue.Empty:
            h = self._pick(excluded | {rep.id})
            if h is not None and self._take_budget():
                self.metrics.inc("hedges")
                hedged_to = h
                threading.Thread(target=run, args=(h, "hedge"),
                                 daemon=True,
                                 name="fleet-hedge").start()
                arms += 1
        if first is None:
            first = results.get()
        r1, out1 = first
        winner = first
        if self._retryable(out1) and arms > 1:
            # first arrival failed retryably — the other arm may still
            # deliver; losing its answer would turn a hedge into a loss
            winner = results.get()
        rwin, out = winner
        if trace is not None and arms > 1:
            # mark the loser's span discarded (it may still be open —
            # the dump serializes open spans with a null duration)
            loser = rep if rwin is not rep else hedged_to
            lspan = spans.get(loser.id) if loser is not None else None
            if lspan is not None:
                lspan.attrs["discarded"] = True
        if self._retryable(out):
            excluded.add(r1.id)
            excluded.add(rwin.id)
            return out
        if rwin is not rep:
            self.metrics.inc("hedges_won")
        # the losing arm (if any) finishes in the background and its
        # response is discarded — that waste is exactly what the
        # budget bounds
        return out

    # -- streaming -----------------------------------------------------
    def open_stream(self, path: str, body: bytes, headers: Dict = None,
                    trace=None, session: Optional[str] = None):
        """Route a streaming generation: returns
        ``("stream", replica, conn, resp)`` with the response open
        (the caller MUST call ``conn.close()`` + ``replica.end()``
        when done — closing mid-stream is how a client disconnect
        propagates and frees the replica's slot/blocks), or
        ``("response", status, headers, data)`` for admission
        failures after retries."""
        self.metrics.inc("requests")
        excluded: Set[str] = set()
        last = None
        attempts = 0
        prefer = self._affinity_get(session)
        max_attempts = self.max_attempts or max(1, len(self.fleet.eligible()))
        while attempts < max_attempts:
            t_pick = time.perf_counter()
            rep = self._pick(excluded, prefer=prefer)
            if rep is None:
                break
            if trace is not None:
                trace.span("pick", t_start=t_pick,
                           t_end=time.perf_counter(), replica=rep.id,
                           attempt=attempts + 1, stream=True)
            attempts += 1
            if attempts > 1:
                self.metrics.inc("retries")
                if trace is not None:
                    trace.span("retry", attempt=attempts,
                               replica=rep.id).end()
            rep.begin()
            self.metrics.inc("routed")
            t_dispatch = time.monotonic()
            span = (trace.span("dispatch", replica=rep.id, stream=True)
                    if trace is not None else None)
            conn = http.client.HTTPConnection(rep.host, rep.port,
                                              timeout=self.timeout_s)
            try:
                conn.request("POST", path, body=body,
                             headers=(_JSON_HEADERS if not headers
                                      else {**_JSON_HEADERS, **headers}))
                resp = conn.getresponse()
            except _RETRYABLE_EXC as e:
                conn.close()
                rep.end()
                if span is not None:
                    span.end(error=f"{type(e).__name__}: {e}")
                if isinstance(e, TimeoutError):
                    st, hdrs, data = _timeout_response(self.timeout_s)
                    self.metrics.inc("server_errors")
                    return ("response", st, hdrs, data)
                self.fleet.note_failure(rep)
                excluded.add(rep.id)
                last = None
                continue
            if span is not None:
                # for a stream the span covers dispatch -> first byte
                # of response headers, not the whole generation
                span.end(status=resp.status)
            if resp.status != 200:
                data = resp.read()
                conn.close()
                rep.end()
                hdrs = dict(resp.getheaders())
                self._note(rep, resp.status, hdrs, t_dispatch)
                if resp.status == 503:
                    excluded.add(rep.id)
                    last = (resp.status, hdrs, data)
                    continue
                if 400 <= resp.status < 500:
                    self.metrics.inc("client_errors")
                else:
                    self.metrics.inc("server_errors")
                return ("response", resp.status,
                        dict(resp.getheaders()), data)
            self.fleet.note_ok(rep, t_dispatch)
            self.metrics.inc("streams")
            self._affinity_note(session, rep.id)
            return ("stream", rep, conn, resp)
        self.metrics.inc("requests_lost")
        if last is not None:
            st, hdrs, data = last
            hdrs.setdefault("Retry-After", "1")
            return ("response", st, hdrs, data)
        return ("response", 503, {"Retry-After": "1"},
                json.dumps({"error": "no replica available"}).encode())

    def stream(self, path: str, payload):
        """Streamed generation through the fleet: yields parsed ndjson
        objects. ``close()`` on the generator (or abandoning it)
        closes the upstream connection, which frees the backing
        replica's slot/blocks exactly like a direct client
        disconnect."""
        session = None
        if isinstance(payload, dict):
            payload = dict(payload, stream=True)
            sid = payload.get("session_id")
            if isinstance(sid, str) and sid:
                session = sid
        opened = self.open_stream(path, json.dumps(payload).encode(),
                                  session=session)
        if opened[0] == "response":
            _, status, _hdrs, data = opened
            try:
                body = json.loads(data) if data else {}
            except ValueError:
                body = {}
            msg = (f"stream admission failed ({status}): "
                   f"{body.get('error', '?')}")
            raise NoReplicasError(msg) if status == 503 \
                else FleetError(msg)
        _, rep, conn, resp = opened
        return _FleetStream(rep, conn, resp)

    # -- observability -------------------------------------------------
    def stats(self) -> Dict:
        """Fleet counters + per-replica state/occupancy — the fleet
        analogue of a replica's ``GET /stats``."""
        return {"fleet": self.fleet.snapshot()}

    def _access_log(self, entry: Dict):
        """One structured JSON access-log line (see :meth:`serve`'s
        ``log_requests``). Logging failures never fail a request."""
        stream = self._log_stream
        if stream is None:
            return
        try:
            line = json.dumps(entry, separators=(",", ":"))
            with self._log_lock:
                stream.write(line + "\n")
                stream.flush()
        except (OSError, ValueError):
            pass

    def healthy(self) -> bool:
        """Router liveness: at least one admitted replica."""
        return any(r.admitted for r in self.fleet.replicas())

    def ready(self) -> bool:
        """Router readiness: at least one eligible replica."""
        return bool(self.fleet.eligible())

    # -- HTTP front-end ------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0,
              max_body_bytes: int = 256 * 1024 * 1024,
              log_requests=False, backend: str = "aio",
              header_timeout_s: float = 10.0):
        """Start the fleet's own HTTP listener (same route table as a
        replica, fleet-level probes/stats) and return (host, port).
        ``log_requests`` (off by default) enables a structured JSON
        access log — ``True`` logs to stderr, any file-like object
        logs there (same format as the replica's).

        ``backend="aio"`` (default) serves off one event loop with a
        NATIVELY async streaming proxy: an open proxied stream is two
        socket buffers and a coroutine, so connection count — the
        router's actual scaling axis — no longer breeds blocked
        threads, and upstream keep-alives ride an async checkout pool
        (docs/serving.md "Front-end architecture").
        ``backend="thread"`` is the original thread-per-connection
        listener. Routes and proxy semantics are identical."""
        router = self
        self._log_stream = (sys.stderr if log_requests is True
                            else (log_requests or None))
        if backend == "aio":
            from .aio import AioRouterFrontend
            self._aio = AioRouterFrontend(
                self, host, port, max_body_bytes=max_body_bytes,
                header_timeout_s=header_timeout_s)
            self.host = self._aio.host
            self.port = self._aio.port
            return self.host, self.port
        if backend != "thread":
            raise ValueError(f"unknown backend {backend!r} "
                             "(use 'aio' or 'thread')")

        class _Server(ThreadingHTTPServer):
            request_queue_size = 128
            daemon_threads = True

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def log_request(self, code="-", size="-"):
                # one line per response — see InferenceServer's
                # identically-shaped override
                if router._log_stream is None:
                    return
                try:
                    status = int(code)
                except (TypeError, ValueError):
                    status = str(code)
                t0 = getattr(self, "_t0", None)
                entry = {"ts": round(time.time(), 6),
                         "method": self.command,
                         "path": self.path,
                         "status": status,
                         "latency_ms": round(
                             (time.perf_counter() - t0) * 1e3, 3)
                         if t0 is not None else None,
                         "request_id": getattr(self, "_rid", None),
                         "priority": getattr(self, "_prio", None)}
                shed = getattr(self, "_shed", None)
                if shed is not None:
                    entry["shed_reason"] = shed
                router._access_log(entry)

            def _json(self, obj, code=200, headers=None):
                body = (obj if isinstance(obj, bytes)
                        else json.dumps(obj).encode())
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    rid = getattr(self, "_rid", None)
                    if rid:
                        self.send_header("X-Request-Id", rid)
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    # the client gave up (its own timeout) while the
                    # dispatch ran — routine, not a router error, and
                    # must not traceback-spam stderr per occurrence
                    self.close_connection = True

            def _text(self, body: str, code=200):
                data = body.encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "text/plain; "
                                     "version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except OSError:
                    self.close_connection = True

            def do_GET(self):
                self._t0 = time.perf_counter()
                self._rid = self.headers.get("X-Request-Id")
                path, _, query = self.path.partition("?")
                try:
                    if path == "/stats":
                        self._json(router.stats())
                    elif path == "/metrics":
                        self._text(prometheus_text(router.stats()))
                    elif path == "/debug/traces":
                        q = parse_qs(query)
                        rid = (q.get("request_id") or q.get("id")
                               or [None])[0]
                        limit = int((q.get("limit") or [50])[0])
                        self._json({
                            "traces": router.tracer.dump(
                                request_id=rid, limit=limit),
                            "tracer": router.tracer.snapshot()})
                    elif path == "/healthz":
                        ok = router.healthy()
                        self._json({"status": "ok" if ok else
                                    "no replicas"}, 200 if ok else 503)
                    elif path == "/readyz":
                        if router.ready():
                            self._json({"ready": True})
                        else:
                            self._json({"ready": False,
                                        "reason": "no eligible replica"},
                                       503, headers={"Retry-After": "1"})
                    elif path in ("/v1/models", "/v1/models/"):
                        rep = router._pick(set())
                        if rep is None:
                            self._json({"error": "no replica available"},
                                       503, headers={"Retry-After": "1"})
                        else:
                            st, body = _get_json(
                                rep.host, rep.port, "/v1/models",
                                router.timeout_s)
                            self._json(body, st)
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as e:   # noqa: BLE001
                    self._json({"error": str(e)}, 500)

            def do_POST(self):
                self._t0 = time.perf_counter()
                # the front-end is where a request id is born (unless
                # the client brought one): the SAME id is forwarded to
                # whichever replicas this request touches, so the
                # router's spans and the winning replica's spans land
                # under one trace id
                self._rid = (self.headers.get("X-Request-Id")
                             or new_request_id())
                self._prio = self.headers.get("X-Priority")
                self._shed = None
                # same keep-alive body discipline as InferenceServer:
                # bad/oversized bodies must not desync or OOM
                if self.headers.get("Transfer-Encoding"):
                    self._json({"error": "Transfer-Encoding not "
                                "supported; send Content-Length"}, 501)
                    self.close_connection = True
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    n = -1
                if n < 0:
                    self._json({"error": "bad Content-Length"}, 400)
                    self.close_connection = True
                    return
                if n > max_body_bytes:
                    self._json({"error": "request body too large"}, 413)
                    self.close_connection = True
                    return
                raw = self.rfile.read(n)
                path, _, query = self.path.partition("?")
                # X-Priority carries the request's shed class — the
                # one client header with routing semantics; it must
                # survive the proxy hop or every fronted request
                # silently becomes interactive
                fwd = {"X-Request-Id": self._rid}
                prio = self.headers.get("X-Priority")
                if prio is not None:
                    fwd["X-Priority"] = prio
                # ?trace=1 on the QUERY (not the body — the router
                # must not pay a parse of predict bodies) forces a
                # trace even when the router tracer is off; the query
                # is NOT forwarded, so each tier opts in separately
                want_trace = bool(query
                                  and "trace=1" in query.split("&"))
                trace = router.tracer.begin(self._rid,
                                            force=want_trace)
                fspan = (trace.span("frontend", path=path)
                         if trace is not None else None)
                streaming = False
                session = None
                # only generate routes can stream — don't pay a json
                # parse of (possibly huge) predict bodies just to
                # sniff a flag they can't carry.  the same sniff pulls
                # session_id so the router can steer the turn to the
                # replica that pinned the session's KV blocks
                if path == "/generate" or \
                        path.rstrip("/").endswith("/generate"):
                    try:
                        req = json.loads(raw)
                        streaming = bool(isinstance(req, dict)
                                         and req.get("stream"))
                        if isinstance(req, dict):
                            sid = req.get("session_id")
                            if isinstance(sid, str) and sid:
                                session = sid
                    except ValueError:
                        pass   # replica answers 400; just forward
                if streaming:
                    self._proxy_stream(path, raw, fwd, trace, fspan,
                                       session=session)
                    return
                status, hdrs, data = router.post_raw(path, raw, fwd,
                                                     trace=trace,
                                                     session=session)
                if status in (503, 504):
                    self._shed = "overload"
                extra = {}
                if "Retry-After" in hdrs:
                    extra["Retry-After"] = hdrs["Retry-After"]
                if trace is not None:
                    fspan.end(status=status)
                    router.tracer.finish(trace, error=status >= 500)
                    if want_trace and status == 200:
                        # splice the router's spans into the replica's
                        # ?trace=1 timeline (or create one): the
                        # response carries the full cross-tier view
                        try:
                            body = json.loads(data)
                            if isinstance(body, dict):
                                body["router_trace"] = trace.to_dict()
                                data = json.dumps(body).encode()
                        except ValueError:
                            pass
                self._json(data, status, headers=extra)

            def _proxy_stream(self, path: str, raw: bytes,
                              fwd: Dict = None, trace=None,
                              fspan=None, session=None):
                opened = router.open_stream(path, raw, fwd,
                                            trace=trace,
                                            session=session)
                if trace is not None:
                    fspan.end(status=(opened[1]
                                      if opened[0] == "response"
                                      else 200), stream=True)
                    router.tracer.finish(
                        trace, error=(opened[0] == "response"
                                      and opened[1] >= 500))
                if opened[0] == "response":
                    _, status, hdrs, data = opened
                    extra = {}
                    if "Retry-After" in hdrs:
                        extra["Retry-After"] = hdrs["Retry-After"]
                    self._json(data, status, headers=extra)
                    return
                _, rep, conn, resp = opened
                try:
                    try:
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                    except OSError:
                        self.close_connection = True
                        return
                    # upstream READ and downstream WRITE failures are
                    # different events and must not be conflated: a
                    # dying replica (IncompleteRead — an HTTPException,
                    # NOT an OSError — or a read timeout) leaves a LIVE
                    # client that is owed the same in-band error chunk
                    # the replica-direct path delivers; a vanished
                    # client just needs the upstream closed (which
                    # aborts the generation and frees its slot/blocks)
                    err = None
                    while True:
                        try:
                            line = resp.readline()
                        except _RETRYABLE_EXC as e:
                            err = {"error": "replica stream failed: "
                                            f"{type(e).__name__}: {e}",
                                   "status": 500, "done": True}
                            break
                        if not line:
                            break
                        if not line.strip():
                            continue
                        try:
                            self.wfile.write(
                                f"{len(line):X}\r\n".encode()
                                + line + b"\r\n")
                            self.wfile.flush()
                        except OSError:
                            # downstream client vanished mid-stream
                            self.close_connection = True
                            return
                    try:
                        if err is not None:
                            data = (json.dumps(err) + "\n").encode()
                            self.wfile.write(
                                f"{len(data):X}\r\n".encode()
                                + data + b"\r\n")
                        self.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        self.close_connection = True
                finally:
                    conn.close()
                    rep.end()

        self.httpd = _Server((host, port), Handler)
        self.host = self.httpd.server_address[0]
        self.port = self.httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="fleet-http")
        self._http_thread.start()
        return self.host, self.port

    def stop(self):
        """Stop the router's HTTP listener (if started) and drop
        pooled connections. Replicas and the fleet poll loop are
        owned by :class:`ReplicaFleet` — stop them there."""
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None
        if self._aio is not None:
            self._aio.stop()
            self._aio = None
        self._pool.close_all()
        self._pool.close_all()
