"""Event-loop HTTP front-end for the serving plane (docs/serving.md
"Front-end architecture").

The thread-per-connection front-end (``http.server.ThreadingHTTPServer``)
spends one OS thread per OPEN connection — not per in-flight request.
A fleet front-end holding thousands of mostly-idle keep-alive and
streaming connections therefore burns thousands of threads that exist
only to block in ``readline()``, and the scheduler/stack cost of that
idle army is what collapses first under connection scale (the bench's
``connscale`` leg measures exactly this). This module rebuilds both
HTTP tiers on one ``asyncio`` selector loop:

- :class:`AioReplicaFrontend`: the :class:`~.InferenceServer` listener.
  Routing, body discipline (411/400/413 + close), keep-alive, chunked
  ndjson streaming, ``X-Request-Id`` / ``X-Priority`` propagation,
  ``?trace=1``, the access log and the probe routes are byte-compatible
  with the thread backend — the server-level methods (``_route``,
  ``_predict``, ``_generate_stream``, ``_healthz`` …) are shared, only
  the socket tier differs.
- :class:`AioRouterFrontend`: the :class:`~.fleet.FleetRouter`
  listener. Streaming proxies are NATIVELY async end to end — one open
  proxied stream is two socket buffers and a coroutine, not a thread —
  over an async upstream connection pool (:class:`_AioConnPool`)
  mirroring the blocking ``_ConnPool``'s checkout semantics.

Concurrency model: the event loop owns every socket. Work that blocks
on the engine (predict/generate admission, pulling the next token of a
stream, the router's retry/hedge dispatch) runs on a bounded
daemon-thread pool — so the THREAD cost of the process scales with
in-flight *blocking work* (bounded by engine slots + queue), never with
open connections. Slow-loris protection the thread backend never had
falls out of the same structure: request heads that do not complete
within ``header_timeout_s`` are dropped without a thread ever having
been committed to them.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import json
import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from ..tracing import new_request_id
from .batcher import DeadlineExceededError

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    408: "Request Timeout", 413: "Payload Too Large",
    431: "Request Header Fields Too Large", 500: "Internal Server Error",
    501: "Not Implemented", 503: "Service Unavailable",
    504: "Gateway Timeout",
}

#: header-read cap: a request head larger than this is a 431, and the
#: StreamReader limit bounds buffering before the head even parses
_MAX_HEAD_BYTES = 256 * 1024

_END = object()          # stream-iterator exhaustion sentinel


def _status_for(exc: BaseException) -> int:
    from . import _status_for as impl     # parent package, post-init
    return impl(exc)


class _DaemonExecutor:
    """Minimal thread pool of DAEMON threads (lazily grown, bounded).

    ``concurrent.futures.ThreadPoolExecutor`` workers are non-daemon
    and joined at interpreter exit — one worker still blocked on a
    slow engine call would hang process shutdown. Serving work is
    always deadline-bounded, but the front-end must not make exit
    correctness depend on that; daemon workers cannot.
    """

    def __init__(self, max_workers: int, name: str):
        self._q: _queue.SimpleQueue = _queue.SimpleQueue()
        self._lock = threading.Lock()
        self._max = int(max_workers)
        self._name = name
        self._workers = 0
        self._idle = 0
        self._down = False

    def submit(self, fn, *args) -> concurrent.futures.Future:
        f: concurrent.futures.Future = concurrent.futures.Future()
        if self._down:
            f.set_exception(RuntimeError("executor is shut down"))
            return f
        self._q.put((f, fn, args))
        with self._lock:
            if self._idle == 0 and self._workers < self._max:
                self._workers += 1
                n = self._workers
                threading.Thread(target=self._work, daemon=True,
                                 name=f"{self._name}-{n}").start()
        return f

    def _work(self):
        while True:
            with self._lock:
                self._idle += 1
            item = self._q.get()
            with self._lock:
                self._idle -= 1
            if item is None:
                with self._lock:
                    self._workers -= 1
                return
            f, fn, args = item
            if not f.set_running_or_notify_cancel():
                continue
            try:
                f.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 — future carries it
                f.set_exception(e)

    def shutdown(self):
        self._down = True
        with self._lock:
            n = self._workers
        for _ in range(n):
            self._q.put(None)


class _Headers:
    """Case-insensitive header lookup over the parsed request head."""

    __slots__ = ("_d",)

    def __init__(self, d: Dict[str, str]):
        self._d = d

    def get(self, name: str, default=None):
        return self._d.get(name.lower(), default)


class _Request:
    __slots__ = ("method", "target", "path", "query", "version",
                 "headers", "reader", "close")

    def __init__(self, method, target, version, headers, reader):
        self.method = method
        self.target = target
        self.path, _, self.query = target.partition("?")
        self.version = version
        self.headers = headers
        self.reader = reader
        conn = (headers.get("Connection") or "").lower()
        self.close = ("close" in conn
                      or (version == "HTTP/1.0"
                          and "keep-alive" not in conn))


class _Resp:
    """Per-request response writer + the state the access log reads."""

    __slots__ = ("_w", "rid", "prio", "shed", "status", "sent", "close",
                 "log_cb")

    def __init__(self, writer):
        self._w = writer
        self.rid: Optional[str] = None
        self.prio: Optional[str] = None
        self.shed: Optional[str] = None
        self.status: Optional[int] = None
        self.sent = False
        self.close = False
        self.log_cb = None

    async def _send(self, code: int, ctype: str, body: bytes,
                    headers: Optional[Dict[str, str]] = None,
                    chunked: bool = False):
        lines = [f"HTTP/1.1 {code} {_REASONS.get(code, 'Unknown')}",
                 f"Content-Type: {ctype}"]
        if chunked:
            lines.append("Transfer-Encoding: chunked")
        else:
            lines.append(f"Content-Length: {len(body)}")
            if self.rid:
                lines.append(f"X-Request-Id: {self.rid}")
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        self.status = code
        self.sent = True
        # access log fires at header-send time (like the thread
        # backend's send_response hook), so by the time a client can
        # read the response its log line is already written
        if self.log_cb is not None:
            cb, self.log_cb = self.log_cb, None
            cb()
        self._w.write(head + body)
        await self._w.drain()

    async def json(self, obj, code: int = 200,
                   headers: Optional[Dict[str, str]] = None):
        body = obj if isinstance(obj, bytes) else json.dumps(obj).encode()
        await self._send(code, "application/json", body, headers)

    async def text(self, s: str, code: int = 200):
        await self._send(code, "text/plain; version=0.0.4; charset=utf-8",
                         s.encode(), None)

    async def start_stream(self):
        await self._send(200, "application/x-ndjson", b"", None,
                         chunked=True)

    async def chunk(self, data: bytes):
        self._w.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        await self._w.drain()

    async def end_stream(self):
        self._w.write(b"0\r\n\r\n")
        await self._w.drain()


#: socket-level failures while talking to the downstream client —
#: asyncio surfaces resets as ConnectionError subclasses, but a
#: transport torn down mid-write can also raise bare OSError
_SOCK_EXC = (ConnectionError, OSError, asyncio.IncompleteReadError)


class _AioFrontend:
    """Shared event-loop listener: one daemon thread runs the loop, a
    bounded daemon pool runs blocking work. Subclasses provide the
    route tables (:meth:`handle_get` / :meth:`handle_post`) and the
    tier hooks (access log, request-id minting, disconnect counter).
    """

    def __init__(self, host: str, port: int, *, name: str,
                 max_body_bytes: int,
                 header_timeout_s: float = 10.0,
                 workers: int = 128):
        self.max_body_bytes = int(max_body_bytes)
        self.header_timeout_s = float(header_timeout_s)
        self._pool = _DaemonExecutor(workers, name + "-work")
        self._conns: set = set()
        self._server = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        self._loop = asyncio.new_event_loop()
        self._stopped = False
        started = threading.Event()
        boot_err: List[BaseException] = []

        def _run():
            loop = self._loop
            asyncio.set_event_loop(loop)
            try:
                self._server = loop.run_until_complete(
                    asyncio.start_server(self._serve_conn, host, port,
                                         limit=_MAX_HEAD_BYTES,
                                         backlog=512))
                addr = self._server.sockets[0].getsockname()
                self.host, self.port = addr[0], addr[1]
            except BaseException as e:  # noqa: BLE001 — report to ctor
                boot_err.append(e)
                started.set()
                return
            started.set()
            try:
                loop.run_forever()
            finally:
                tasks = asyncio.all_tasks(loop)
                for t in tasks:
                    t.cancel()
                try:
                    loop.run_until_complete(
                        asyncio.gather(*tasks, return_exceptions=True))
                except Exception:   # noqa: BLE001 — teardown best-effort
                    pass
                loop.close()

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name=name)
        self._thread.start()
        started.wait(10.0)
        if boot_err:
            raise boot_err[0]

    # -- tier hooks ----------------------------------------------------
    def _prepare_post(self, req: _Request, resp: _Resp):
        """Mint/propagate the request id before body discipline runs,
        so even a 413/400 reject echoes ``X-Request-Id``."""
        resp.rid = req.headers.get("X-Request-Id") or new_request_id()
        resp.prio = req.headers.get("X-Priority")

    def _oversize_msg(self) -> str:
        return "request body too large"

    def _access_log(self, entry: dict):   # pragma: no cover - overridden
        pass

    def _on_disconnect(self):
        pass

    async def handle_get(self, req: _Request, resp: _Resp):
        await resp.json({"error": "not found"}, 404)

    async def handle_post(self, req: _Request, resp: _Resp, raw: bytes):
        await resp.json({"error": "not found"}, 404)

    # -- blocking-work bridge ------------------------------------------
    async def _blocking(self, fn, *args):
        """Run ``fn`` on the daemon pool; await without holding the
        loop. Every engine touch goes through here."""
        return await asyncio.wrap_future(self._pool.submit(fn, *args))

    # -- connection loop -----------------------------------------------
    async def _serve_conn(self, reader, writer):
        self._conns.add(writer)
        try:
            while True:
                try:
                    head = await asyncio.wait_for(
                        reader.readuntil(b"\r\n\r\n"),
                        self.header_timeout_s)
                except (asyncio.TimeoutError, TimeoutError):
                    return            # slow-loris / idle past the cap
                except asyncio.LimitOverrunError:
                    await self._reject(writer, 431,
                                       "request head too large")
                    return
                except _SOCK_EXC:
                    return            # keep-alive peer went away
                req = self._parse_head(head, reader)
                if req is None:
                    await self._reject(writer, 400, "malformed request")
                    return
                t0 = time.perf_counter()
                resp = _Resp(writer)
                resp.rid = req.headers.get("X-Request-Id")
                resp.log_cb = (lambda r=req, rs=resp, t=t0:
                               self._log(r, rs, t))
                try:
                    if req.method == "GET":
                        await self.handle_get(req, resp)
                    elif req.method == "POST":
                        self._prepare_post(req, resp)
                        ok, raw = await self._read_body(req, resp)
                        if ok:
                            await self.handle_post(req, resp, raw)
                    else:
                        await resp.json(
                            {"error": "method not allowed"}, 501)
                        resp.close = True
                except _SOCK_EXC:
                    resp.close = True
                except Exception as e:  # noqa: BLE001 — last resort
                    if resp.sent:
                        resp.close = True
                    else:
                        try:
                            await resp.json({"error": str(e)}, 500)
                        except _SOCK_EXC:
                            resp.close = True
                if resp.close or req.close:
                    return
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:   # noqa: BLE001 — transport already dead
                pass

    @staticmethod
    def _parse_head(head: bytes, reader) -> Optional[_Request]:
        try:
            lines = head.decode("latin-1").split("\r\n")
            method, target, version = lines[0].split(" ", 2)
            hdrs: Dict[str, str] = {}
            for ln in lines[1:]:
                if not ln:
                    continue
                k, _, v = ln.partition(":")
                hdrs[k.strip().lower()] = v.strip()
        except ValueError:
            return None
        return _Request(method.upper(), target, version.strip(),
                        _Headers(hdrs), reader)

    async def _read_body(self, req: _Request,
                         resp: _Resp) -> Tuple[bool, bytes]:
        """Same keep-alive body discipline as the thread backend: an
        unread/unframed body would desync the next request on the
        socket, so every reject also closes the connection."""
        if req.headers.get("Transfer-Encoding"):
            await resp.json({"error": "Transfer-Encoding not "
                             "supported; send Content-Length"}, 501)
            resp.close = True
            return False, b""
        try:
            n = int(req.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            n = -1
        if n < 0:
            await resp.json({"error": "bad Content-Length"}, 400)
            resp.close = True
            return False, b""
        if n > self.max_body_bytes:
            await resp.json({"error": self._oversize_msg()}, 413)
            resp.close = True
            return False, b""
        raw = await req.reader.readexactly(n) if n else b""
        return True, raw

    async def _reject(self, writer, code: int, msg: str):
        body = json.dumps({"error": msg}).encode()
        try:
            writer.write(
                (f"HTTP/1.1 {code} {_REASONS.get(code, 'Unknown')}\r\n"
                 "Content-Type: application/json\r\n"
                 f"Content-Length: {len(body)}\r\n"
                 "Connection: close\r\n\r\n").encode() + body)
            await writer.drain()
        except _SOCK_EXC:
            pass

    def _log(self, req: _Request, resp: _Resp, t0: float):
        entry = {"ts": round(time.time(), 6),
                 "method": req.method,
                 "path": req.target,
                 "status": resp.status,
                 "latency_ms": round(
                     (time.perf_counter() - t0) * 1e3, 3),
                 "request_id": resp.rid,
                 "priority": resp.prio}
        if resp.shed is not None:
            entry["shed_reason"] = resp.shed
        self._access_log(entry)

    # -- lifecycle -----------------------------------------------------
    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        loop = self._loop

        async def _teardown():
            if self._server is not None:
                self._server.close()
                await self._server.wait_closed()
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:   # noqa: BLE001
                    pass

        try:
            fut = asyncio.run_coroutine_threadsafe(_teardown(), loop)
            fut.result(timeout=5.0)
        except Exception:   # noqa: BLE001 — loop already down
            pass
        try:
            loop.call_soon_threadsafe(loop.stop)
        except RuntimeError:
            pass
        self._thread.join(timeout=5.0)
        self._pool.shutdown()


# ---------------------------------------------------------------------
# replica tier
# ---------------------------------------------------------------------

class AioReplicaFrontend(_AioFrontend):
    """Event-loop listener for one :class:`~.InferenceServer` replica.
    Route table and semantics mirror the thread backend's handler; the
    server-level request methods are shared verbatim."""

    def __init__(self, server, host: str, port: int,
                 header_timeout_s: float = 10.0, workers: int = 128):
        self._srv = server
        super().__init__(host, port, name="serving-aio",
                         max_body_bytes=server.max_body_bytes,
                         header_timeout_s=header_timeout_s,
                         workers=workers)

    def _oversize_msg(self) -> str:
        return (f"request body too large (limit "
                f"{self._srv.max_body_bytes} bytes)")

    def _access_log(self, entry: dict):
        if self._srv._log_stream is not None:
            self._srv._access_log(entry)

    def _on_disconnect(self):
        self._srv._count_disconnect()

    async def handle_get(self, req: _Request, resp: _Resp):
        from .metrics import prometheus_text
        server = self._srv
        path, query = req.path, req.query
        try:
            if path == "/health":
                await resp.json(server._health())
            elif path == "/healthz":
                code, body = server._healthz()
                await resp.json(body, code)
            elif path == "/readyz":
                if server.ready():
                    await resp.json({"ready": True})
                else:
                    await resp.json({"ready": False,
                                     "reason": "draining"}, 503,
                                    headers={"Retry-After": "1"})
            elif path == "/stats":
                await resp.json(server.stats())
            elif path == "/metrics":
                await resp.text(prometheus_text(server.stats()))
            elif path == "/debug/traces":
                q = parse_qs(query)
                rid = (q.get("request_id") or q.get("id") or [None])[0]
                limit = int((q.get("limit") or [50])[0])
                await resp.json({
                    "traces": server.tracer.dump(request_id=rid,
                                                 limit=limit),
                    "tracer": server.tracer.snapshot()})
            elif path in ("/v1/models", "/v1/models/"):
                await resp.json(server.registry.describe())
            else:
                await resp.json({"error": "not found"}, 404)
        except _SOCK_EXC:
            raise
        except Exception as e:  # noqa: BLE001 — route-level 500
            if resp.sent:
                raise
            await resp.json({"error": str(e)}, 500)

    async def handle_post(self, req: _Request, resp: _Resp, raw: bytes):
        from .engine import ClientError
        server = self._srv
        path, query = req.path, req.query
        route = server._route(path)
        if route is None:
            await resp.json({"error": "not found"}, 404)
            return
        name, action = route
        if not server.ready():
            resp.shed = "draining"
            await resp.json({"error": "server is draining"}, 503,
                            headers={"Retry-After": "1"})
            return
        parsed = None
        result = None
        trace = None
        span = None
        want_trace = False
        try:
            try:
                parsed = json.loads(raw)
            except json.JSONDecodeError as e:
                raise ClientError(f"malformed JSON: {e}")
            prio_hdr = req.headers.get("X-Priority")
            if prio_hdr and isinstance(parsed, dict) \
                    and "priority" not in parsed:
                parsed["priority"] = prio_hdr
            if isinstance(parsed, dict):
                resp.prio = parsed.get("priority", resp.prio)
            want_trace = bool(
                (query and "trace=1" in query.split("&"))
                or (isinstance(parsed, dict)
                    and parsed.pop("trace", None)))
            trace = server.tracer.begin(resp.rid, force=want_trace)
            if trace is not None:
                span = trace.span("http", path=path, model=name,
                                  action=action)
            if action == "generate":
                if isinstance(parsed, dict) and parsed.get("stream"):
                    # admission runs on the pool (it may block on the
                    # engine queue lock) and raises BEFORE headers go
                    # out, so shed/4xx still map to status codes
                    it = await self._blocking(
                        server._generate_stream, name, parsed, trace)
                    await self._stream_ndjson(resp, it)
                    if trace is not None:
                        span.end(status=200, stream=True)
                        server.tracer.finish(trace)
                    return
                result = await self._blocking(
                    server._generate, name, parsed, trace)
            else:
                result = await self._blocking(
                    server._predict, name, parsed, trace)
        except _SOCK_EXC:
            raise
        except Exception as e:  # noqa: BLE001 — engine/client failure
            code = _status_for(e)
            if code in (503, 504):
                resp.shed = str(e)
            version = (parsed.get("version")
                       if isinstance(parsed, dict) else None)
            server._count_error(name, code, version)
            if trace is not None:
                span.end(status=code, error=str(e))
                server.tracer.finish(trace, error=code >= 500)
            try:
                await resp.json({"error": str(e)}, code,
                                headers=({"Retry-After": "1"}
                                         if code == 503 else None))
            except _SOCK_EXC:
                server._count_disconnect()
                resp.close = True
            return
        if trace is not None:
            span.end(status=200)
            server.tracer.finish(trace)
            if want_trace and isinstance(result, dict):
                result = dict(result)
                result["trace"] = trace.to_dict()
        try:
            await resp.json(result)
        except _SOCK_EXC:
            # client hung up while the request computed — routine once
            # routers time out and abandon sockets
            server._count_disconnect()
            resp.close = True

    async def _stream_ndjson(self, resp: _Resp, it):
        """Chunked ndjson: one object per token as the scheduler emits
        it, a terminal ``{"done": true}`` object, then the zero chunk.

        Generation streams are consumed EVENT-DRIVEN: the engine's
        ``stream_notify`` hook sets an ``asyncio.Event`` from the
        scheduler thread, and this coroutine drains the token queue
        with ``get_nowait`` — an idle open stream costs two socket
        buffers and a parked coroutine, never a pool worker. (The
        executor-pump fallback below exists only for iterators without
        the ``_TokenStream`` queue shape.) That zero-thread idle cost
        is what lets one replica hold thousands of open streams — the
        bench's ``connscale`` leg."""
        server = self._srv
        req = getattr(it, "_req", None)
        if req is None or getattr(req, "stream_q", None) is None:
            def pull():
                try:
                    return next(it)
                except StopIteration:
                    return _END

            async def anext_item():
                return await self._blocking(pull)
        else:
            loop = asyncio.get_running_loop()
            evt = asyncio.Event()
            req.stream_notify = lambda: loop.call_soon_threadsafe(evt.set)
            engine = it._engine

            async def anext_item():
                # event-driven mirror of _TokenStream.__next__: same
                # deadline budget, same timeout/abandon accounting,
                # same item protocol — but parked on evt, not a thread
                if it._done:
                    return _END
                while True:
                    # clear BEFORE the queue check: a push landing
                    # after the check re-sets evt, so the wait below
                    # can never sleep through an item already queued
                    evt.clear()
                    try:
                        kind, payload = req.stream_q.get_nowait()
                        break
                    except _queue.Empty:
                        budget = req.deadline - time.perf_counter() + 1.0
                        if budget <= 0:
                            it._done = True
                            req.abandoned = True
                            req.count_timeout_once(engine.metrics)
                            raise DeadlineExceededError(
                                "stream stalled past the deadline")
                        try:
                            await asyncio.wait_for(evt.wait(), budget)
                        except (asyncio.TimeoutError, TimeoutError):
                            pass  # loop re-checks queue, then budget
                if kind == "token":
                    i = it._i
                    it._i += 1
                    return {"token": int(payload), "index": i}
                it._done = True
                if kind == "done":
                    engine.metrics.inc("responses")
                    final = req.result()
                    final["done"] = True
                    return final
                raise payload  # "error"

        try:
            await resp.start_stream()
        except _SOCK_EXC:
            # client vanished before headers: abandon the generation
            # (frees its slot/blocks), never try a second response
            if hasattr(it, "close"):
                it.close()
            if req is not None:
                req.stream_notify = None
            server._count_disconnect()
            resp.close = True
            return
        try:
            try:
                while True:
                    item = await anext_item()
                    if item is _END:
                        break
                    await resp.chunk((json.dumps(item) + "\n").encode())
            except _SOCK_EXC:
                # client went away mid-stream: close the iterator NOW
                # (abandons the request, freeing its cache slot)
                if hasattr(it, "close"):
                    it.close()
                server._count_disconnect()
                resp.close = True
                return
            except Exception as e:  # noqa: BLE001 — headers are on
                # the wire; deliver the failure in-band
                await resp.chunk((json.dumps(
                    {"error": str(e), "status": _status_for(e),
                     "done": True}) + "\n").encode())
            await resp.end_stream()
        except _SOCK_EXC:
            server._count_disconnect()
            resp.close = True
        finally:
            if req is not None:
                req.stream_notify = None


# ---------------------------------------------------------------------
# router tier
# ---------------------------------------------------------------------

class _AioUpstream:
    """One async keep-alive connection to a replica, with a de-chunking
    line reader over the open response. Only ever touched from the
    router frontend's event loop (single thread — no locking)."""

    __slots__ = ("host", "port", "_r", "_w", "_chunked", "_remaining",
                 "_buf", "_eof", "clean")

    def __init__(self, host: str, port: int, reader, writer):
        self.host = host
        self.port = port
        self._r = reader
        self._w = writer
        self._chunked = False
        self._remaining = 0
        self._buf = b""
        self._eof = False
        self.clean = False       # response fully consumed -> reusable

    @classmethod
    async def connect(cls, host: str, port: int,
                      timeout_s: float) -> "_AioUpstream":
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout_s)
        return cls(host, port, reader, writer)

    async def request(self, path: str, body: bytes,
                      headers: Optional[Dict[str, str]],
                      timeout_s: float) -> Tuple[int, Dict[str, str]]:
        """Send one POST, read the response head -> (status, headers).
        Resets per-response reader state for pooled reuse."""
        self._buf = b""
        self._eof = False
        self.clean = False
        lines = [f"POST {path} HTTP/1.1",
                 f"Host: {self.host}:{self.port}",
                 "Content-Type: application/json",
                 f"Content-Length: {len(body)}"]
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        self._w.write(("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
                      + body)
        await asyncio.wait_for(self._w.drain(), timeout_s)
        head = await asyncio.wait_for(self._r.readuntil(b"\r\n\r\n"),
                                      timeout_s)
        try:
            hlines = head.decode("latin-1").split("\r\n")
            status = int(hlines[0].split(" ", 2)[1])
            hdrs: Dict[str, str] = {}
            for ln in hlines[1:]:
                if not ln:
                    continue
                k, _, v = ln.partition(":")
                hdrs[k.strip().lower()] = v.strip()
        except (ValueError, IndexError) as e:
            raise ConnectionError(f"bad upstream response head: {e}")
        self._chunked = ("chunked"
                         in hdrs.get("transfer-encoding", "").lower())
        if not self._chunked:
            try:
                self._remaining = int(hdrs.get("content-length", 0))
            except ValueError:
                raise ConnectionError("bad upstream Content-Length")
        return status, {k.title(): v for k, v in hdrs.items()}

    async def read_body(self, timeout_s: float) -> bytes:
        """Drain the whole response body (non-stream answers)."""
        out = []
        while True:
            line = await asyncio.wait_for(self._line(), timeout_s)
            if not line:
                return b"".join(out)
            out.append(line)

    async def readline(self) -> bytes:
        """Next line of the de-chunked response body; b'' at clean
        end. Raises on a connection torn mid-stream (the caller maps
        that to the in-band upstream-failure chunk)."""
        return await self._line()

    async def _line(self) -> bytes:
        while True:
            i = self._buf.find(b"\n")
            if i >= 0:
                line, self._buf = self._buf[:i + 1], self._buf[i + 1:]
                return line
            if self._eof:
                if self._buf:
                    line, self._buf = self._buf, b""
                    return line
                return b""
            await self._fill()

    async def _fill(self):
        if self._chunked:
            size_line = await self._r.readline()
            if not size_line:
                raise ConnectionError("upstream closed mid-stream")
            try:
                size = int(size_line.strip().split(b";")[0], 16)
            except ValueError:
                raise ConnectionError("bad upstream chunk framing")
            if size == 0:
                await self._r.readline()     # trailing CRLF
                self._eof = True
                self.clean = True
                return
            data = await self._r.readexactly(size + 2)
            self._buf += data[:-2]
        else:
            if self._remaining <= 0:
                self._eof = True
                self.clean = True
                return
            data = await self._r.read(min(65536, self._remaining))
            if not data:
                raise ConnectionError("upstream closed mid-body")
            self._remaining -= len(data)
            self._buf += data

    def close(self):
        try:
            self._w.close()
        except Exception:   # noqa: BLE001 — transport already dead
            pass


class _AioConnPool:
    """Async analogue of the router's blocking ``_ConnPool``: idle
    upstream connections checked out per stream, bounded per address,
    pruned on fleet membership change. Event-loop-thread only."""

    def __init__(self, max_per_key: int = 32):
        self._idle: Dict[Tuple[str, int], List[_AioUpstream]] = {}
        self.max_per_key = int(max_per_key)

    def take(self, host: str, port: int) -> Optional[_AioUpstream]:
        stack = self._idle.get((host, port))
        return stack.pop() if stack else None

    def give(self, up: _AioUpstream):
        stack = self._idle.setdefault((up.host, up.port), [])
        if len(stack) < self.max_per_key:
            stack.append(up)
        else:
            up.close()

    def prune(self, live_keys):
        dead = [k for k in self._idle if k not in live_keys]
        for k in dead:
            for up in self._idle.pop(k):
                up.close()

    def close_all(self):
        stacks, self._idle = self._idle, {}
        for stack in stacks.values():
            for up in stack:
                up.close()


class AioRouterFrontend(_AioFrontend):
    """Event-loop listener for a :class:`~.fleet.FleetRouter`. The
    streaming proxy path is natively async end to end (client socket,
    replica socket, relay) — holding an open proxied stream costs two
    buffers, never a thread. Non-streaming dispatch reuses the
    router's blocking retry/hedge machinery on the work pool."""

    def __init__(self, router, host: str, port: int,
                 max_body_bytes: int,
                 header_timeout_s: float = 10.0, workers: int = 128):
        self._router = router
        self._apool = _AioConnPool()
        self._live_addrs: set = set()
        super().__init__(host, port, name="fleet-aio",
                         max_body_bytes=max_body_bytes,
                         header_timeout_s=header_timeout_s,
                         workers=workers)

    def _access_log(self, entry: dict):
        if self._router._log_stream is not None:
            self._router._access_log(entry)

    async def handle_get(self, req: _Request, resp: _Resp):
        from .fleet import _get_json
        from .metrics import prometheus_text
        router = self._router
        path, query = req.path, req.query
        try:
            if path == "/stats":
                await resp.json(router.stats())
            elif path == "/metrics":
                await resp.text(prometheus_text(router.stats()))
            elif path == "/debug/traces":
                q = parse_qs(query)
                rid = (q.get("request_id") or q.get("id") or [None])[0]
                limit = int((q.get("limit") or [50])[0])
                await resp.json({
                    "traces": router.tracer.dump(request_id=rid,
                                                 limit=limit),
                    "tracer": router.tracer.snapshot()})
            elif path == "/healthz":
                ok = router.healthy()
                await resp.json({"status": "ok" if ok
                                 else "no replicas"},
                                200 if ok else 503)
            elif path == "/readyz":
                if router.ready():
                    await resp.json({"ready": True})
                else:
                    await resp.json({"ready": False,
                                     "reason": "no eligible replica"},
                                    503, headers={"Retry-After": "1"})
            elif path in ("/v1/models", "/v1/models/"):
                rep = router._pick(set())
                if rep is None:
                    await resp.json({"error": "no replica available"},
                                    503, headers={"Retry-After": "1"})
                else:
                    st, body = await self._blocking(
                        _get_json, rep.host, rep.port, "/v1/models",
                        router.timeout_s)
                    await resp.json(body, st)
            else:
                await resp.json({"error": "not found"}, 404)
        except _SOCK_EXC:
            raise
        except Exception as e:  # noqa: BLE001 — route-level 500
            if resp.sent:
                raise
            await resp.json({"error": str(e)}, 500)

    async def handle_post(self, req: _Request, resp: _Resp, raw: bytes):
        router = self._router
        path, query = req.path, req.query
        # X-Priority carries the request's shed class; X-Request-Id is
        # the cross-tier trace id — both must survive the proxy hop
        fwd = {"X-Request-Id": resp.rid}
        prio = req.headers.get("X-Priority")
        if prio is not None:
            fwd["X-Priority"] = prio
        want_trace = bool(query and "trace=1" in query.split("&"))
        trace = router.tracer.begin(resp.rid, force=want_trace)
        fspan = (trace.span("frontend", path=path)
                 if trace is not None else None)
        streaming = False
        session = None
        # only generate routes can stream — don't pay a JSON parse of
        # (possibly huge) predict bodies to sniff a flag they can't
        # carry; the same sniff pulls session_id for affinity routing
        if path == "/generate" or path.rstrip("/").endswith("/generate"):
            try:
                body = json.loads(raw)
                streaming = bool(isinstance(body, dict)
                                 and body.get("stream"))
                if isinstance(body, dict):
                    sid = body.get("session_id")
                    if isinstance(sid, str) and sid:
                        session = sid
            except ValueError:
                pass    # replica answers 400; just forward
        if streaming:
            await self._proxy_stream(req, resp, path, raw, fwd, trace,
                                     fspan, session)
            return
        status, hdrs, data = await self._blocking(
            lambda: router.post_raw(path, raw, fwd, trace=trace,
                                    session=session))
        if status in (503, 504):
            resp.shed = "overload"
        extra = {}
        if "Retry-After" in hdrs:
            extra["Retry-After"] = hdrs["Retry-After"]
        if trace is not None:
            fspan.end(status=status)
            router.tracer.finish(trace, error=status >= 500)
            if want_trace and status == 200:
                try:
                    body = json.loads(data)
                    if isinstance(body, dict):
                        body["router_trace"] = trace.to_dict()
                        data = json.dumps(body).encode()
                except ValueError:
                    pass
        try:
            await resp.json(data, status, headers=extra)
        except _SOCK_EXC:
            resp.close = True

    # -- streaming proxy (natively async) ------------------------------
    async def _open_stream(self, path: str, body: bytes,
                           headers: Dict[str, str], trace=None,
                           session: Optional[str] = None):
        """Async mirror of ``FleetRouter.open_stream``: same pick /
        retry / backpressure bookkeeping, but the upstream is an async
        pooled connection. Returns ``("stream", replica, upstream)``
        or ``("response", status, headers, data)``."""
        from .fleet import _timeout_response
        router = self._router
        router.metrics.inc("requests")
        excluded: set = set()
        last = None
        attempts = 0
        prefer = router._affinity_get(session)
        max_attempts = (router.max_attempts
                        or max(1, len(router.fleet.eligible())))
        # membership/port change: drop pooled keep-alives to addresses
        # that no longer exist (the blocking pool prunes in _pick)
        addrs = {(r.host, r.port) for r in router.fleet.replicas()}
        if addrs != self._live_addrs:
            self._live_addrs = addrs
            self._apool.prune(addrs)
        while attempts < max_attempts:
            t_pick = time.perf_counter()
            rep = router._pick(excluded, prefer=prefer)
            if rep is None:
                break
            if trace is not None:
                trace.span("pick", t_start=t_pick,
                           t_end=time.perf_counter(), replica=rep.id,
                           attempt=attempts + 1, stream=True)
            attempts += 1
            if attempts > 1:
                router.metrics.inc("retries")
                if trace is not None:
                    trace.span("retry", attempt=attempts,
                               replica=rep.id).end()
            rep.begin()
            router.metrics.inc("routed")
            t_dispatch = time.monotonic()
            span = (trace.span("dispatch", replica=rep.id, stream=True)
                    if trace is not None else None)
            up = None
            failure = None
            # a pooled keep-alive may be stale (replica restarted on
            # the same port): retry exactly once on a fresh connection
            # — mirroring the blocking _roundtrip discipline
            for fresh in (False, True):
                up = None if fresh else self._apool.take(rep.host,
                                                         rep.port)
                made_fresh = up is None
                try:
                    if up is None:
                        up = await _AioUpstream.connect(
                            rep.host, rep.port, router.timeout_s)
                    status, rhdrs = await up.request(
                        path, body, headers, router.timeout_s)
                    failure = None
                    break
                except (asyncio.TimeoutError, TimeoutError) as e:
                    if up is not None:
                        up.close()
                    failure = e
                    break
                except _SOCK_EXC as e:
                    if up is not None:
                        up.close()
                    failure = e
                    if made_fresh:
                        break
            if failure is not None:
                rep.end()
                if span is not None:
                    span.end(error=f"{type(failure).__name__}: "
                             f"{failure}")
                if isinstance(failure, (asyncio.TimeoutError,
                                        TimeoutError)):
                    st, hdrs, data = _timeout_response(router.timeout_s)
                    router.metrics.inc("server_errors")
                    return ("response", st, hdrs, data)
                router.fleet.note_failure(rep)
                excluded.add(rep.id)
                last = None
                continue
            if span is not None:
                # for a stream the span covers dispatch -> first byte
                # of response headers, not the whole generation
                span.end(status=status)
            if status != 200:
                try:
                    data = await up.read_body(router.timeout_s)
                except (asyncio.TimeoutError, TimeoutError, *_SOCK_EXC):
                    data = b""
                up.close()
                rep.end()
                router._note(rep, status, rhdrs, t_dispatch)
                if status == 503:
                    excluded.add(rep.id)
                    last = (status, rhdrs, data)
                    continue
                if 400 <= status < 500:
                    router.metrics.inc("client_errors")
                else:
                    router.metrics.inc("server_errors")
                return ("response", status, rhdrs, data)
            router.fleet.note_ok(rep, t_dispatch)
            router.metrics.inc("streams")
            router._affinity_note(session, rep.id)
            return ("stream", rep, up)
        router.metrics.inc("requests_lost")
        if last is not None:
            st, hdrs, data = last
            hdrs.setdefault("Retry-After", "1")
            return ("response", st, hdrs, data)
        return ("response", 503, {"Retry-After": "1"},
                json.dumps({"error": "no replica available"}).encode())

    async def _proxy_stream(self, req: _Request, resp: _Resp,
                            path: str, raw: bytes,
                            fwd: Dict[str, str], trace, fspan,
                            session: Optional[str]):
        router = self._router
        opened = await self._open_stream(path, raw, fwd, trace=trace,
                                         session=session)
        if trace is not None:
            fspan.end(status=(opened[1] if opened[0] == "response"
                              else 200), stream=True)
            router.tracer.finish(
                trace, error=(opened[0] == "response"
                              and opened[1] >= 500))
        if opened[0] == "response":
            _, status, hdrs, data = opened
            extra = {}
            if "Retry-After" in hdrs:
                extra["Retry-After"] = hdrs["Retry-After"]
            try:
                await resp.json(data, status, headers=extra)
            except _SOCK_EXC:
                resp.close = True
            return
        _, rep, up = opened
        try:
            try:
                await resp.start_stream()
            except _SOCK_EXC:
                resp.close = True
                return
            # upstream READ and downstream WRITE failures are distinct
            # events: a dying replica leaves a LIVE client owed the
            # same in-band error chunk the replica-direct path
            # delivers; a vanished client just needs the upstream
            # closed (aborting the generation, freeing slot/blocks)
            err = None
            while True:
                try:
                    line = await asyncio.wait_for(up.readline(),
                                                  router.timeout_s)
                except (asyncio.TimeoutError, TimeoutError,
                        *_SOCK_EXC) as e:
                    err = {"error": "replica stream failed: "
                                    f"{type(e).__name__}: {e}",
                           "status": 500, "done": True}
                    break
                if not line:
                    break
                if not line.strip():
                    continue
                try:
                    await resp.chunk(line)
                except _SOCK_EXC:
                    # downstream client vanished mid-stream
                    resp.close = True
                    return
            try:
                if err is not None:
                    await resp.chunk((json.dumps(err) + "\n").encode())
                await resp.end_stream()
            except _SOCK_EXC:
                resp.close = True
        finally:
            # clean end on a keep-alive upstream -> back to the pool;
            # anything else closes (aborting the generation upstream)
            if up.clean and not self._stopped:
                self._apool.give(up)
            else:
                up.close()
            rep.end()

    def stop(self):
        super().stop()
        self._apool.close_all()
