"""Model serving + compiled-program export.

Ref: the reference's serving surface — `libnd4j/server/GraphServer.cpp`
(gRPC + FlatBuffers inference server), the KNN REST server
(`deeplearning4j-nearestneighbor-server`), and datavec's
spark-inference REST endpoints (L7 inventory).

TPU-native shape:
- :class:`InferenceServer`: one stdlib HTTP endpoint serving any model
  with an `output(x)` method (MultiLayerNetwork, ComputationGraph) or a
  SameDiff (named-placeholder feed). JSON in/out; the compiled forward
  is cached across requests exactly like the C++ server caches its
  FlatBuffers graph.
- :func:`export_stablehlo`: serialize a SameDiff (or any jittable
  fn+args) to StableHLO text — the portable compiled-graph artifact
  replacing the reference's FlatBuffers graph format (SURVEY.md §2.1:
  "N5 -> StableHLO module serialization").
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np


def export_stablehlo(fn_or_samediff, example_args=None,
                     outputs: Optional[Sequence[str]] = None,
                     placeholders: Optional[Dict[str, Any]] = None) -> str:
    """StableHLO text for a jittable fn or a SameDiff graph.

    SameDiff: pass `outputs` (names) and `placeholders` (example arrays
    fixing shapes). Function: pass `example_args`.
    """
    from ..autodiff.samediff import SameDiff
    if isinstance(fn_or_samediff, SameDiff):
        sd = fn_or_samediff
        outs = tuple(outputs or sd._loss_variables)
        if not outs:
            raise ValueError("pass outputs= for SameDiff export")
        gfn = sd._build(outs)
        vals = sd._filter_values(sd._exec_values(placeholders or {}), gfn)
        rng = jax.random.PRNGKey(sd.seed)
        lowered = jax.jit(lambda v, r: gfn(v, r)).lower(vals, rng)
    else:
        lowered = jax.jit(fn_or_samediff).lower(*(example_args or ()))
    return lowered.as_text()


class InferenceServer:
    """HTTP JSON inference endpoint (ref role: GraphServer.cpp).

    POST /predict           {"inputs": [[...]]} -> {"outputs": [[...]]}
    POST /predict (SameDiff) {"inputs": {"x": [[...]]},
                              "outputs": ["pred"]}
    GET  /health            {"status": "ok", "model": "..."}
    """

    def __init__(self, model, port: int = 0,
                 default_outputs: Optional[Sequence[str]] = None):
        self.model = model
        self.default_outputs = list(default_outputs or [])
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._json({"status": "ok",
                                "model": type(server.model).__name__})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                if self.path != "/predict":
                    self._json({"error": "not found"}, 404)
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    out = server._predict(req)
                    self._json(out)
                except Exception as e:  # noqa: BLE001 — surface to client
                    self._json({"error": str(e)}, 400)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _predict(self, req: dict) -> dict:
        inputs = req["inputs"]
        from ..autodiff.samediff import SameDiff
        if isinstance(self.model, SameDiff):
            feed = {k: np.asarray(v, np.float32)
                    for k, v in inputs.items()}
            outs = req.get("outputs") or self.default_outputs
            if not outs:
                raise ValueError("SameDiff serving needs 'outputs'")
            res = self.model.output(feed, outs)
            return {"outputs": {k: np.asarray(v).tolist()
                                for k, v in res.items()}}
        x = np.asarray(inputs, np.float32)
        y = np.asarray(self.model.output(x))
        return {"outputs": y.tolist()}

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
