"""Model serving + compiled-program export.

Ref: the reference's serving surface — `libnd4j/server/GraphServer.cpp`
(gRPC + FlatBuffers inference server that caches the compiled graph
across requests), the KNN REST server
(`deeplearning4j-nearestneighbor-server`), and datavec's
spark-inference REST endpoints (L7 inventory).

TPU-native shape — a real inference runtime, not one call per request:

- :class:`~.engine.InferenceEngine`: pads request batches into
  power-of-two buckets and keeps a bounded LRU of AOT-compiled
  executables per (bucket, signature), with `warmup()` so steady-state
  traffic never recompiles (the GraphServer compiled-graph cache,
  generalized across batch shapes).
- :class:`~.batcher.MicroBatcher`: coalesces concurrent requests into
  one device call under a max_batch_size / max_latency_ms policy, with
  per-request deadlines and a bounded queue that sheds load (503)
  instead of growing without limit (TF Serving BatchingSession /
  Clipper adaptive batching, PAPERS.md).
- :class:`~.registry.ModelRegistry`: named, versioned multi-model
  hosting, routed at ``/v1/models/<name>/predict``.
- :class:`InferenceServer`: the thin stdlib-HTTP front-end over
  registry + batcher. The legacy single-model constructor
  (``InferenceServer(model, port=0)``) still works and routes through
  the full runtime.
- :func:`export_stablehlo`: serialize a SameDiff (or any jittable
  fn+args) to StableHLO text — the portable compiled-graph artifact
  replacing the reference's FlatBuffers graph format (SURVEY.md §2.1).

HTTP surface::

    POST /predict                      default model
    POST /v1/models/<name>/predict     named model (latest version)
    POST /generate                     default generator
    POST /v1/models/<name>/generate    continuous-batching generation
                                       ({"stream": true} -> chunked
                                       newline-delimited JSON tokens;
                                       {"session_id": "..."} pins the
                                       turn's KV blocks for prefix
                                       reuse on the next turn — paged
                                       backend, docs/generation.md)
    GET  /v1/models                    registry listing
    GET  /stats                        serving metrics per model, plus
                                       a compact top-level "summary"
                                       (per-model live occupancy /
                                       queue depth / draining flag)
                                       for routers and load balancers
    GET  /metrics                      the same counters as Prometheus
                                       text exposition (scrapable)
    GET  /debug/traces                 bounded ring of recent / slow /
                                       errored request traces; filter
                                       with ?request_id=<id>
    GET  /health                       legacy summary (always 200)
    GET  /healthz                      liveness: 503 when any engine
                                       loop is wedged (stall watchdog)
    GET  /readyz                       readiness: 503 + Retry-After
                                       while draining

Observability (docs/observability.md): every request carries an
``X-Request-Id`` (accepted from the caller or minted here, echoed on
the response); with ``tracing=True`` — or per-request via ``?trace=1``
/ a ``"trace": 1`` body field, which also embeds the timeline in the
response — the request records admission / queue / prefill / decode
spans retained at ``/debug/traces``. ``log_requests=`` emits one
structured JSON access-log line per HTTP request.

Status codes: 400 malformed request (client), 404 unknown route/model,
500 internal failure (incl. quarantined poison requests), 503 load
shed (queue full) or draining — always with ``Retry-After``, 504
deadline exceeded.

Priority classes (docs/serving.md "Overload and admission control"):
every predict/generate request may carry ``"priority": "interactive"``
(default) or ``"batch"`` — as a JSON field or the ``X-Priority``
request header (the field wins when both are present). Under pressure
batch-class work is shed first (503) so interactive p99 holds, and
deadline-aware admission sheds requests whose budget is already blown
before they burn a device step.

Fault tolerance (:mod:`.faults`, docs/serving.md "Operating the
server"): supervised engine loops retry transient step faults with
bounded backoff and rebuild cache-corrupting failures by
recompute-recovery (no accepted request is ever lost); poison requests
(non-finite logits) are quarantined alone; ``drain()`` — wirable to
SIGTERM via :meth:`InferenceServer.install_signal_handlers` — flips
readiness off, finishes in-flight work, then joins the scheduler
threads. ``faults.{retries,recoveries,quarantined,drains}`` counters
surface per model at ``GET /stats``.

Fleet tier (:mod:`.fleet`, docs/serving.md "Running a fleet"): N
replicas of this server go behind a :class:`~.fleet.FleetRouter` —
occupancy-aware routing on the ``/stats`` summary, health-gated
membership via ``/healthz``/``/readyz``, straggler hedging under a
token-bucket retry budget, and :meth:`~.fleet.ReplicaFleet.
rolling_restart` extending the single-replica zero-loss drain
guarantee fleet-wide.

Generation (see :mod:`.generation`): causal LMs registered via
``register_generator`` decode token-by-token under iteration-level
scheduling against a static-shape KV cache — requests join and leave
the device batch every decode step, so short generations never wait
on long ones and the compiled executables never change shape. Two
cache backends: dense per-slot panels (``cache="slots"``) or the
paged block pool (``cache="paged"``, :mod:`.paging`) with
all-or-nothing block admission and chunked prefill, so memory scales
with ACTUAL sequence lengths and long prompts never stall the decode
loop for more than one chunk.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Sequence
from urllib.parse import parse_qs

import jax
import numpy as np

from ..tracing import Tracer, new_request_id
from .batcher import (DeadlineExceededError, DrainingError, MicroBatcher,
                      QueueFullError)
from .engine import ClientError, InferenceEngine, ServingError, next_bucket
from .faults import (CorruptedStateFault, FaultInjector,
                     PoisonRequestError, TransientFault)
from .fleet import (FleetError, FleetMetrics, FleetRouter,
                    NoReplicasError, Replica, ReplicaFleet)
from .generation import GenerationEngine
from .kvcache import KVCache, SlotTable
from .metrics import (GenerationMetrics, ServingMetrics,
                      profiler_sections, prometheus_text)
from .offload import DiskRing, HostBlockStore, HostRun
from .paging import BlockAllocator, BlockTable, PagedKVCache
from .registry import (ModelNotFound, ModelRegistry, ServedGenerator,
                       ServedModel)

__all__ = [
    "InferenceServer", "InferenceEngine", "MicroBatcher", "ModelRegistry",
    "ModelNotFound", "ServedModel", "ServedGenerator", "GenerationEngine",
    "GenerationMetrics", "KVCache", "SlotTable", "PagedKVCache",
    "BlockAllocator", "BlockTable", "ServingMetrics",
    "HostBlockStore", "HostRun", "DiskRing",
    "ClientError", "ServingError", "QueueFullError",
    "DeadlineExceededError", "DrainingError", "FaultInjector",
    "TransientFault", "CorruptedStateFault", "PoisonRequestError",
    "ReplicaFleet", "FleetRouter", "Replica", "FleetMetrics",
    "FleetError", "NoReplicasError",
    "next_bucket", "export_stablehlo", "Tracer", "prometheus_text",
]


def export_stablehlo(fn_or_samediff, example_args=None,
                     outputs: Optional[Sequence[str]] = None,
                     placeholders: Optional[Dict[str, Any]] = None) -> str:
    """StableHLO text for a jittable fn or a SameDiff graph.

    SameDiff: pass `outputs` (names) and `placeholders` (example arrays
    fixing shapes). Function: pass `example_args`.
    """
    from ..autodiff.samediff import SameDiff
    if isinstance(fn_or_samediff, SameDiff):
        sd = fn_or_samediff
        outs = tuple(outputs or sd._loss_variables)
        if not outs:
            raise ValueError("pass outputs= for SameDiff export")
        gfn = sd._build(outs)
        vals = sd._filter_values(sd._exec_values(placeholders or {}), gfn)
        rng = jax.random.PRNGKey(sd.seed)
        lowered = jax.jit(lambda v, r: gfn(v, r)).lower(vals, rng)
    else:
        lowered = jax.jit(fn_or_samediff).lower(*(example_args or ()))
    return lowered.as_text()


class _HTTPServer(ThreadingHTTPServer):
    # the stdlib default backlog of 5 drops SYNs under concurrent-client
    # load (clients then stall ~1s in retransmit — a fake p99); size it
    # for the serving queue instead
    request_queue_size = 128
    daemon_threads = True


def _status_for(exc: BaseException) -> int:
    if isinstance(exc, ModelNotFound):
        return 404
    if isinstance(exc, QueueFullError):
        return 503
    if isinstance(exc, DeadlineExceededError):
        return 504
    if isinstance(exc, ClientError):
        return 400
    return 500


class InferenceServer:
    """HTTP JSON inference front-end over registry + batcher (ref role:
    GraphServer.cpp).

    Single-model (legacy, still supported)::

        server = InferenceServer(model, port=0)

    Multi-model::

        server = InferenceServer(port=0)
        server.register("mnist", model_a)
        server.register("ranker", model_b, default_outputs=["score"])

    ``host`` defaults to loopback; pass ``host="0.0.0.0"`` to bind
    externally for multi-host deployments.

    ``http_backend`` selects the socket tier (docs/serving.md
    "Front-end architecture"): ``"aio"`` (default) serves every
    connection off one event loop — open connections cost a socket
    buffer, not a thread, so thousands of idle keep-alive or
    streaming clients don't breed thousands of blocked threads — with
    engine-blocking work on a bounded daemon pool and a
    ``http_header_timeout_s`` slow-loris cap the thread tier never
    had. ``"thread"`` is the original thread-per-connection
    ``ThreadingHTTPServer``. Routes, status codes, streaming framing,
    headers and the access log are identical across backends.
    """

    DEFAULT_MODEL = "default"

    def __init__(self, model=None, port: int = 0,
                 default_outputs: Optional[Sequence[str]] = None,
                 host: str = "127.0.0.1",
                 registry: Optional[ModelRegistry] = None,
                 batching: bool = True,
                 max_batch_size: int = 64,
                 max_latency_ms: float = 5.0,
                 max_queue: int = 256,
                 default_timeout_ms: float = 30_000.0,
                 warmup_buckets: Optional[Sequence[int]] = None,
                 warmup_example=None,
                 max_body_bytes: int = 256 * 1024 * 1024,
                 tracing: bool = False,
                 trace_ring: int = 256,
                 trace_slow_ms: float = 1000.0,
                 log_requests=False,
                 http_backend: str = "aio",
                 http_header_timeout_s: float = 10.0):
        self.max_body_bytes = int(max_body_bytes)
        self.registry = registry or ModelRegistry()
        self._owns_registry = registry is None
        self._ready = True            # flips off when drain() starts
        self._prev_handlers: Dict[int, Any] = {}
        self._signal_drain: Optional[threading.Thread] = None
        # request tracing (docs/observability.md): disabled by default
        # — Tracer.begin then returns None and every instrumented path
        # skips span work on a single attribute check. ?trace=1 still
        # traces one request through a disabled tracer.
        self.tracer = Tracer(enabled=bool(tracing), ring=trace_ring,
                             slow_ms=trace_slow_ms)
        # structured access log: False = off, True = stderr, else any
        # writable text stream (one JSON object per line)
        self._log_stream = (sys.stderr if log_requests is True
                            else (log_requests or None))
        self._log_lock = threading.Lock()
        # dead-socket writes swallowed by the handler (clients/routers
        # that timed out and hung up): invisible before this counter
        self.client_disconnects = 0
        self._disc_lock = threading.Lock()
        self._opts = dict(batching=batching, max_batch_size=max_batch_size,
                          max_latency_ms=max_latency_ms,
                          max_queue=max_queue,
                          default_timeout_ms=default_timeout_ms)
        self.model = model  # legacy attribute
        if model is not None:
            served = self.register(self.DEFAULT_MODEL, model,
                                   default_outputs=default_outputs)
            if warmup_buckets:
                served.warmup(warmup_buckets, example=warmup_example)
        server = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive: serving clients send many small requests, and
            # per-request TCP setup would dominate the batched path
            # (every response carries Content-Length, so 1.1 is safe)
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def log_request(self, code="-", size="-"):
                # send_response() calls this once per response — the
                # single choke point every success/error/stream path
                # goes through, so the access log is one line per
                # request with no per-branch bookkeeping
                if server._log_stream is None:
                    return
                try:
                    status = int(code)
                except (TypeError, ValueError):
                    status = str(code)
                t0 = getattr(self, "_t0", None)
                entry = {"ts": round(time.time(), 6),
                         "method": self.command,
                         "path": self.path,
                         "status": status,
                         "latency_ms": round(
                             (time.perf_counter() - t0) * 1e3, 3)
                         if t0 is not None else None,
                         "request_id": getattr(self, "_rid", None),
                         "priority": getattr(self, "_prio", None)}
                shed = getattr(self, "_shed", None)
                if shed is not None:
                    entry["shed_reason"] = shed
                server._access_log(entry)

            def _json(self, obj, code=200, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                rid = getattr(self, "_rid", None)
                if rid:
                    self.send_header("X-Request-Id", rid)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _text(self, body: str, code=200):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain; "
                                 "version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._t0 = time.perf_counter()
                self._rid = self.headers.get("X-Request-Id")
                path, _, query = self.path.partition("?")
                try:
                    if path == "/health":
                        self._json(server._health())
                    elif path == "/healthz":
                        code, body = server._healthz()
                        self._json(body, code)
                    elif path == "/readyz":
                        if server.ready():
                            self._json({"ready": True})
                        else:
                            self._json({"ready": False,
                                        "reason": "draining"}, 503,
                                       headers={"Retry-After": "1"})
                    elif path == "/stats":
                        self._json(server.stats())
                    elif path == "/metrics":
                        self._text(prometheus_text(server.stats()))
                    elif path == "/debug/traces":
                        q = parse_qs(query)
                        rid = (q.get("request_id") or q.get("id")
                               or [None])[0]
                        limit = int((q.get("limit") or [50])[0])
                        self._json({
                            "traces": server.tracer.dump(
                                request_id=rid, limit=limit),
                            "tracer": server.tracer.snapshot()})
                    elif path in ("/v1/models", "/v1/models/"):
                        self._json(server.registry.describe())
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as e:  # noqa: BLE001
                    self._json({"error": str(e)}, 500)

            def do_POST(self):
                self._t0 = time.perf_counter()
                # mint a request id unless the caller (router, client)
                # already tagged one — the id is the trace id, echoed
                # back as X-Request-Id and stitched across tiers
                self._rid = (self.headers.get("X-Request-Id")
                             or new_request_id())
                self._prio = self.headers.get("X-Priority")
                self._shed = None
                # drain the body first: on a keep-alive (1.1) connection
                # an unread body would be parsed as the next request
                # line, desyncing the socket. Bad/negative lengths are a
                # 400, never an unhandled exception or an
                # until-EOF read (a hung handler thread).
                if self.headers.get("Transfer-Encoding"):
                    # chunked framing isn't parsed here; without the
                    # body drained the keep-alive socket would desync
                    self._json({"error": "Transfer-Encoding not "
                                "supported; send Content-Length"}, 501)
                    self.close_connection = True
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    n = -1
                if n < 0:
                    self._json({"error": "bad Content-Length"}, 400)
                    self.close_connection = True  # body length unknown
                    return
                if n > server.max_body_bytes:
                    # one oversized request must not OOM the process —
                    # the queue bounds count rows, this bounds bytes
                    self._json({"error": "request body too large "
                                f"(limit {server.max_body_bytes} "
                                "bytes)"}, 413)
                    self.close_connection = True  # body left unread
                    return
                raw = self.rfile.read(n)
                path, _, query = self.path.partition("?")
                route = server._route(path)
                if route is None:
                    self._json({"error": "not found"}, 404)
                    return
                name, action = route
                if not server.ready():
                    # draining: shed BEFORE touching the registry so
                    # half-drained engines never see new work; clients
                    # retry against another replica after Retry-After
                    self._shed = "draining"
                    self._json({"error": "server is draining"}, 503,
                               headers={"Retry-After": "1"})
                    return
                req = None
                result = None
                trace = None
                span = None
                try:
                    try:
                        req = json.loads(raw)
                    except json.JSONDecodeError as e:
                        raise ClientError(f"malformed JSON: {e}")
                    # the X-Priority header maps to the "priority"
                    # field (routers/gateways tag traffic classes
                    # without rewriting bodies); the body field wins
                    prio_hdr = self.headers.get("X-Priority")
                    if prio_hdr and isinstance(req, dict) \
                            and "priority" not in req:
                        req["priority"] = prio_hdr
                    if isinstance(req, dict):
                        self._prio = req.get("priority", self._prio)
                    # ?trace=1 (or "trace": 1 in the body) forces a
                    # per-request trace even when the tracer is off;
                    # the field is popped so validators never see it
                    want_trace = bool(
                        (query and "trace=1" in query.split("&"))
                        or (isinstance(req, dict)
                            and req.pop("trace", None)))
                    trace = server.tracer.begin(self._rid,
                                                force=want_trace)
                    if trace is not None:
                        span = trace.span("http", path=path,
                                          model=name, action=action)
                    if action == "generate":
                        if isinstance(req, dict) and req.get("stream"):
                            # admission errors raise HERE (before any
                            # header goes out), so they still map to
                            # real status codes; mid-stream failures
                            # become a terminal error chunk instead
                            it = server._generate_stream(name, req,
                                                         trace=trace)
                            self._stream_ndjson(it)
                            if trace is not None:
                                span.end(status=200, stream=True)
                                server.tracer.finish(trace)
                            return
                        result = server._generate(name, req,
                                                  trace=trace)
                    else:
                        result = server._predict(name, req,
                                                 trace=trace)
                except Exception as e:  # noqa: BLE001
                    code = _status_for(e)
                    if code in (503, 504):
                        self._shed = str(e)
                    version = (req.get("version")
                               if isinstance(req, dict) else None)
                    server._count_error(name, code, version)
                    if trace is not None:
                        span.end(status=code, error=str(e))
                        server.tracer.finish(trace,
                                             error=code >= 500)
                    try:
                        self._json({"error": str(e)}, code,
                                   headers=({"Retry-After": "1"}
                                            if code == 503 else None))
                    except OSError:
                        server._count_disconnect()
                        self.close_connection = True
                    return
                if trace is not None:
                    span.end(status=200)
                    server.tracer.finish(trace)
                    if want_trace and isinstance(result, dict):
                        result = dict(result)
                        result["trace"] = trace.to_dict()
                try:
                    self._json(result)
                except OSError:
                    # the client hung up while the (possibly slow)
                    # request computed — routine once routers time out
                    # and abandon sockets, not a server error; a
                    # traceback per occurrence would spam stderr
                    server._count_disconnect()
                    self.close_connection = True

            def _stream_ndjson(self, it):
                """Chunked transfer-encoded newline-delimited JSON: one
                object per generated token as the scheduler emits it,
                a terminal ``{"done": true, ...}`` object, then the
                zero chunk. The keep-alive socket stays in sync —
                chunked framing is self-delimiting."""
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                except OSError:
                    # client vanished before the headers went out:
                    # abandon the generation (frees its slot) and do
                    # NOT fall through to a second response attempt
                    if hasattr(it, "close"):
                        it.close()
                    server._count_disconnect()
                    self.close_connection = True
                    return

                def chunk(obj):
                    data = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(data):X}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()
                try:
                    try:
                        for item in it:
                            chunk(item)
                    except OSError:
                        # client went away mid-stream: routine, not a
                        # server error — close the iterator NOW (its
                        # cleanup abandons the request, freeing its
                        # cache slot) and drop the connection quietly
                        if hasattr(it, "close"):
                            it.close()
                        server._count_disconnect()
                        self.close_connection = True
                        return
                    except Exception as e:  # noqa: BLE001 — headers
                        # are already on the wire; deliver in-band
                        chunk({"error": str(e),
                               "status": _status_for(e), "done": True})
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    # the error/terminal chunk hit the dead socket too;
                    # never fall through to a second HTTP response
                    server._count_disconnect()
                    self.close_connection = True

        self.http_backend = http_backend
        self._aio = None
        self.httpd = None
        self._thread = None
        if http_backend == "thread":
            self.httpd = _HTTPServer((host, port), Handler)
            self.host = self.httpd.server_address[0]
            self.port = self.httpd.server_address[1]
            self._thread = threading.Thread(
                target=self.httpd.serve_forever, daemon=True)
            self._thread.start()
        elif http_backend == "aio":
            from .aio import AioReplicaFrontend
            self._aio = AioReplicaFrontend(
                self, host, port,
                header_timeout_s=http_header_timeout_s)
            self.host = self._aio.host
            self.port = self._aio.port
        else:
            raise ValueError(f"unknown http_backend {http_backend!r} "
                             "(use 'aio' or 'thread')")

    # -- model management ----------------------------------------------
    def register(self, name: str, model, **opts) -> ServedModel:
        """Register a model under ``name`` (engine + batcher built from
        the server's batching policy unless overridden in ``opts``)."""
        merged = dict(self._opts)
        merged.update(opts)
        return self.registry.register(name, model, **merged)

    def unregister(self, name: str, version: Optional[int] = None):
        self.registry.unregister(name, version)

    def served(self, name: str = DEFAULT_MODEL,
               version: Optional[int] = None) -> ServedModel:
        return self.registry.get(name, version)

    def register_generator(self, name: str, model, **opts) -> ServedGenerator:
        """Register a causal LM for continuous-batching generation at
        ``/v1/models/<name>/generate`` (queue bound and default
        timeout inherit the server's batching policy unless
        overridden)."""
        merged = {"max_queue": self._opts["max_queue"],
                  "default_timeout_ms": self._opts["default_timeout_ms"]}
        merged.update(opts)
        return self.registry.register_generator(name, model, **merged)

    # -- request handling ----------------------------------------------
    def _route(self, path: str):
        """Map a POST path to (model name, action); None = 404."""
        if path == "/predict":
            return self.DEFAULT_MODEL, "predict"
        if path == "/generate":
            return self.DEFAULT_MODEL, "generate"
        parts = [p for p in path.split("/") if p]
        if len(parts) == 4 and parts[:2] == ["v1", "models"] \
                and parts[3] in ("predict", "generate"):
            return parts[2], parts[3]
        return None

    def _predict(self, name: str, req, trace=None) -> dict:
        if not isinstance(req, dict):
            raise ClientError("request body must be a JSON object")
        if "inputs" not in req:
            raise ClientError("missing 'inputs'")
        version = req.get("version")
        if version is not None and (not isinstance(version, int)
                                    or isinstance(version, bool)):
            raise ClientError("'version' must be an integer")
        served = self.registry.get(name, version)
        if not hasattr(served, "predict"):
            raise ClientError(
                f"model {name!r} is a generation model — POST to "
                f"/v1/models/{name}/generate instead")
        outputs = req.get("outputs")
        if outputs is not None and not isinstance(outputs, (list, tuple)):
            raise ClientError("'outputs' must be a list of names")
        timeout_ms = req.get("timeout_ms")
        if timeout_ms is not None and (
                not isinstance(timeout_ms, (int, float))
                or isinstance(timeout_ms, bool)):
            raise ClientError("'timeout_ms' must be a number")
        priority = req.get("priority", "interactive")
        if not isinstance(priority, str):
            raise ClientError("'priority' must be a string")
        res = served.predict(req["inputs"], outputs, timeout_ms=timeout_ms,
                             priority=priority, trace=trace)
        if isinstance(res, dict):
            return {"outputs": {k: np.asarray(v).tolist()
                                for k, v in res.items()}}
        if isinstance(res, list):
            return {"outputs": [np.asarray(v).tolist() for v in res]}
        return {"outputs": np.asarray(res).tolist()}

    def _gen_opts(self, name: str, req):
        """Parse + validate a generate payload into (served, prompt,
        engine kwargs). Raises :class:`ClientError` on bad fields."""
        if not isinstance(req, dict):
            raise ClientError("request body must be a JSON object")
        if "prompt" not in req:
            raise ClientError("missing 'prompt' (a list of token ids)")
        version = req.get("version")
        if version is not None and not isinstance(version, int):
            raise ClientError("'version' must be an integer")
        served = self.registry.get(name, version)
        if not hasattr(served, "generate"):
            raise ClientError(
                f"model {name!r} is a predict model — POST to "
                f"/v1/models/{name}/predict instead")
        opts = {}
        for key, types in (("max_tokens", int), ("temperature",
                                                 (int, float)),
                           ("top_k", int), ("seed", int),
                           ("eos_id", int),
                           ("timeout_ms", (int, float))):
            if key in req and req[key] is not None:
                if not isinstance(req[key], types) or isinstance(
                        req[key], bool):
                    raise ClientError(f"{key!r} must be a number")
                opts[key] = req[key]
        priority = req.get("priority")
        if priority is not None:
            if not isinstance(priority, str):
                raise ClientError("'priority' must be a string")
            opts["priority"] = priority
        session_id = req.get("session_id")
        if session_id is not None:
            # length/backend validation stays in the engine — it owns
            # the session store; here only the JSON type is checked
            if not isinstance(session_id, str):
                raise ClientError("'session_id' must be a string")
            opts["session_id"] = session_id
        return served, req["prompt"], opts

    def _generate(self, name: str, req, trace=None) -> dict:
        served, prompt, opts = self._gen_opts(name, req)
        return served.generate(prompt, trace=trace, **opts)

    def _generate_stream(self, name: str, req, trace=None):
        served, prompt, opts = self._gen_opts(name, req)
        return served.stream(prompt, trace=trace, **opts)

    def _count_disconnect(self):
        """Count a swallowed dead-socket write (client hung up while a
        response or stream chunk was in flight). Routine under router
        timeouts/hedging, but a rate spike means clients are giving up
        before replies arrive — surfaced in ``summary()``."""
        with self._disc_lock:
            self.client_disconnects += 1

    def _access_log(self, entry: dict):
        """Emit one structured JSON access-log line (off unless the
        server was built with ``log_requests=``). Logging failures
        never take down a request handler."""
        stream = self._log_stream
        if stream is None:
            return
        try:
            line = json.dumps(entry, separators=(",", ":"))
            with self._log_lock:
                stream.write(line + "\n")
                stream.flush()
        except (OSError, ValueError):
            pass

    def _count_error(self, name: str, code: int, version=None):
        try:
            m = self.registry.get(
                name, version if isinstance(version, int) else None).metrics
        except Exception:  # noqa: BLE001 — unknown model has no metrics
            return
        if code == 400:
            m.inc("client_errors")
        elif code >= 500 and code not in (503, 504) \
                and not isinstance(m, GenerationMetrics):
            # generation 5xx are already counted at the engine
            # (GenerationEngine._fail) so direct-API users see them
            # too; counting here as well would double them
            m.inc("server_errors")

    def _health(self) -> dict:
        d = {"status": "ok", "models": self.registry.names()}
        if self.model is not None:
            d["model"] = type(self.model).__name__  # legacy field
        return d

    # -- lifecycle (docs/serving.md "Operating the server") ------------
    def ready(self) -> bool:
        """Readiness: True until :meth:`drain` starts. ``/readyz``
        mirrors this (200 vs 503 + Retry-After) so load balancers pull
        the replica before its in-flight work finishes."""
        return self._ready

    def _healthz(self):
        """Liveness: (status code, body). 503 only when some engine's
        scheduler loop is WEDGED — thread dead or heartbeat stale past
        its stall watchdog. Draining/stopped engines are alive (that's
        readiness's job), so a restart isn't provoked mid-drain."""
        models = self.registry.health()
        ok = all(models.values())
        return (200 if ok else 503), {
            "status": "ok" if ok else "stalled",
            "models": models}

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown, phase 1: flip readiness off (``/readyz``
        -> 503, new POSTs -> 503 + Retry-After), drain every engine
        (in-flight requests finish, scheduler threads join). The HTTP
        listener stays up so ``/stats``, ``/healthz`` and in-flight
        streaming responses keep flowing; call :meth:`stop` (phase 2)
        to tear it down. Returns True when everything drained within
        ``timeout_s``."""
        self._ready = False
        return self.registry.drain(timeout_s)

    def install_signal_handlers(self, signals=(signal.SIGTERM,),
                                drain_timeout_s: float = 30.0,
                                reraise: bool = True) -> bool:
        """Wire graceful drain to SIGTERM (the platform's preemption
        notice — same contract as
        :class:`~..parallel.elastic.PreemptionHandler` for training):
        on signal, drain + stop, then chain the previous handler (or
        re-deliver the default action so the process actually exits).
        Signal handlers are a main-thread-only facility; elsewhere
        this degrades to a no-op and returns False.

        The handler itself only flips readiness and hands off: Python
        runs it on the main thread between bytecodes, so the main
        thread may at that instant hold the very registry/batcher
        locks ``drain()`` needs — blocking in the handler would
        deadlock the process on a lock its own thread holds. The
        blocking drain + stop run on a dedicated thread. Chaining
        works by RESTORING the previous disposition in the handler
        (``signal.signal`` is itself main-thread-only) and having the
        worker re-deliver the signal after the drain: CPython then
        runs the previous handler on the main thread, the context it
        is entitled to (e.g. ``PreemptionHandler`` re-arms SIG_DFL,
        legal only there). A side effect is the usual graceful-then-
        forceful contract: a second signal during the drain takes the
        previous/default action immediately."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def _handle(signum, frame):
            self._ready = False       # lock-free; /readyz flips now
            if self._signal_drain is not None \
                    and self._signal_drain.is_alive():
                return                # drain already in flight
            prev = self._prev_handlers.get(signum)
            if reraise and prev is not None:
                signal.signal(signum, prev)

            def _drain_and_exit():
                self.drain(drain_timeout_s)
                self.stop()
                if reraise and prev is not None \
                        and prev != signal.SIG_IGN:
                    os.kill(os.getpid(), signum)
            # non-daemon: interpreter exit waits for the (time-bounded)
            # drain instead of killing it mid-flight
            self._signal_drain = threading.Thread(
                target=_drain_and_exit, name="serving-signal-drain",
                daemon=False)
            self._signal_drain.start()
        for s in signals:
            self._prev_handlers[s] = signal.getsignal(s)
            signal.signal(s, _handle)
        return True

    def stats(self) -> dict:
        return {"summary": self.summary(),
                "models": self.registry.stats(),
                "profiler": profiler_sections()}

    def summary(self) -> dict:
        """Compact machine-readable routing summary, also embedded as
        the ``summary`` key of ``GET /stats``: per-model live
        occupancy / queue depth / draining flag plus a server-level
        ``load`` total — what :class:`~.fleet.FleetRouter` (or any
        external load balancer) reads to pick a replica without
        parsing nested histogram snapshots."""
        models = self.registry.summary()
        return {"ready": self.ready(),
                "draining": not self.ready(),
                "load": sum(m["load"] for m in models.values()),
                # server-level shed total: a fleet poller aggregates
                # these into per-replica overload counters
                "shed": sum(m.get("shed", 0) for m in models.values()),
                "client_disconnects": self.client_disconnects,
                "models": models}

    def stop(self):
        # readiness off FIRST: handler threads still in flight when the
        # listener stops would otherwise race the registry teardown and
        # answer 404 ("unknown model") — a lie that a router would pass
        # through as terminal. Shedding 503 + Retry-After instead keeps
        # even a hard (drain-less) stop retryable upstream.
        self._ready = False
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
        if self._aio is not None:
            self._aio.stop()
        if self._owns_registry:
            self.registry.stop()
