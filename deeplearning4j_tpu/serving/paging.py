"""Paged KV-cache memory manager: block-granular allocation for the
generation runtime (vLLM/PagedAttention, SOSP '23 — PAPERS.md).

The slot cache (:mod:`.kvcache`) preallocates ``max_seq_len`` tokens of
K/V per slot, so memory scales with the WORST-CASE sequence length:
a slot serving an 8-token completion pins the same bytes as one serving
a 500-token one. Here the unit of allocation is a BLOCK of
``block_size`` token positions inside one shared pool per layer::

    K, V : [num_blocks, n_heads, block_size, head_dim]

A sequence owns ceil((prompt + max_tokens) / block_size) blocks — its
ACTUAL worst case, not the engine's — and a block table maps its
logical positions to pool blocks. The pool arrays never change shape,
so the compiled decode executable never changes either; "which block
belongs to whom" is host-side bookkeeping, exactly like the slot
table's "which slot belongs to whom", one granularity finer.

Invariants, shared with the slot cache and test-asserted:

- **No zeroing on reuse.** A freed block re-enters the free list with
  its stale K/V intact; the next owner's writes overwrite the prefix
  it uses and the per-sequence length masks everything beyond. There
  is never a zeroing pass between occupants.
- **Block 0 is the null block.** It is never allocated to a request.
  Padded block-table entries point at it, so (a) gathers through
  padding read garbage that the length mask discards, and (b) writes
  from padded lanes (inactive decode slots, the padded tail of a
  prefill chunk past a request's allocation) land in memory nobody
  ever unmasks.
- **No over-commit.** :meth:`BlockAllocator.alloc` is all-or-nothing:
  a request's full worst-case block count is claimed at admission or
  the request stays queued — the engine never admits work it could be
  unable to finish (the alternative, swapping/preemption, trades that
  guarantee for recompute; see docs/generation.md).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

#: Block index reserved as the write/read target for padded table
#: entries. Never handed out by the allocator.
NULL_BLOCK = 0


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` positions."""
    return -(-int(tokens) // int(block_size))


def pow2_bucket(n: int, cap: Optional[int] = None) -> int:
    """Smallest power of two >= n (>= 1), optionally clamped to
    ``cap`` — the block-table padding rule that keeps the set of
    prefill executables finite and AOT-warmable. Delegates to the
    serving engine's :func:`~.engine.next_bucket` so the paged and
    dense bucket policies can never silently diverge."""
    from .engine import next_bucket
    return next_bucket(max(int(n), 1), 1,
                       (1 << 30) if cap is None else cap)


class BlockAllocator:
    """Free-list allocator over the pool's block indices.

    Block 0 (:data:`NULL_BLOCK`) is reserved; ``capacity`` counts only
    allocatable blocks. Allocation is all-or-nothing and LIFO, so a
    just-freed (cache-warm) block is reused first — same policy as the
    slot table's free list."""

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             f"reserved null block), got {num_blocks}")
        self._free = list(range(self.num_blocks - 1, NULL_BLOCK, -1))
        # mirror of _free for O(1) double-free checks: free() runs on
        # the scheduler thread at every retirement, and a linear scan
        # of the free list there would tax every stream's ITL
        self._free_set = set(self._free)
        self.peak_used = 0

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.capacity - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` blocks, or None (claim NOTHING) if fewer than
        ``n`` are free — the no-over-commit contract."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(blocks)
        self.peak_used = max(self.peak_used, self.used_count)
        return blocks

    def free(self, blocks: Sequence[int]):
        """Return blocks to the free list. No zeroing — stale contents
        stay masked by the next owner's length."""
        for b in blocks:
            b = int(b)
            if b == NULL_BLOCK or not 0 < b < self.num_blocks:
                raise ValueError(f"block {b} is not allocatable")
            if b in self._free_set:
                raise ValueError(f"double free of block {b}")
        self._free.extend(int(b) for b in blocks)
        self._free_set.update(int(b) for b in blocks)

    def stats(self) -> dict:
        return {"total": self.capacity, "free": self.free_count,
                "used": self.used_count, "peak_used": self.peak_used}


class BlockTable:
    """One request's logical-position → pool-block mapping (host-side
    int32). ``padded(n)`` emits the device-facing row, padded with
    :data:`NULL_BLOCK` to a caller-chosen length (a pow2 bucket for
    prefill executables; the engine-wide max for the decode batch), so
    executable shapes depend on the BUCKET, never the request."""

    def __init__(self, blocks: Sequence[int], block_size: int):
        self.blocks = [int(b) for b in blocks]
        self.block_size = int(block_size)

    def __len__(self) -> int:
        return len(self.blocks)

    @property
    def capacity_tokens(self) -> int:
        return len(self.blocks) * self.block_size

    def padded(self, n: int) -> np.ndarray:
        if n < len(self.blocks):
            raise ValueError(f"cannot pad {len(self.blocks)} blocks "
                             f"into a table of {n}")
        out = np.full(n, NULL_BLOCK, np.int32)
        out[:len(self.blocks)] = self.blocks
        return out


class PagedKVCache:
    """Per-layer pooled K/V blocks, the paged sibling of
    :class:`~.kvcache.KVCache`: same pytree-threaded-through-donated-
    executables lifecycle, but the leading axis is POOL BLOCKS shared
    by every sequence instead of per-sequence slots.

    ``layer_shapes`` are per-layer ``(n_heads, block_size, head_dim)``
    — i.e. ``model.cache_shapes(block_size)``."""

    def __init__(self, layer_shapes: Sequence[Tuple[int, int, int]],
                 num_blocks: int, dtype=jnp.float32):
        self.num_blocks = int(num_blocks)
        self.layer_shapes = [tuple(s) for s in layer_shapes]
        self.block_size = int(self.layer_shapes[0][1])
        self.dtype = dtype
        self.ks: List[jnp.ndarray] = [
            jnp.zeros((self.num_blocks,) + s, dtype)
            for s in self.layer_shapes]
        self.vs: List[jnp.ndarray] = [
            jnp.zeros((self.num_blocks,) + s, dtype)
            for s in self.layer_shapes]

    def nbytes(self) -> int:
        """Device bytes the pool pins: ``num_blocks * block_size * H *
        Dh * 2 (K+V) * layers * itemsize`` — the number to budget
        against HBM (docs/generation.md has the sizing guidance)."""
        return int(sum(2 * int(np.prod((self.num_blocks,) + s))
                       * jnp.dtype(self.dtype).itemsize
                       for s in self.layer_shapes))

    def block_nbytes(self) -> int:
        """Bytes one block pins across all layers (K+V)."""
        return self.nbytes() // self.num_blocks
