"""Paged KV-cache memory manager: block-granular allocation for the
generation runtime (vLLM/PagedAttention, SOSP '23 — PAPERS.md).

The slot cache (:mod:`.kvcache`) preallocates ``max_seq_len`` tokens of
K/V per slot, so memory scales with the WORST-CASE sequence length:
a slot serving an 8-token completion pins the same bytes as one serving
a 500-token one. Here the unit of allocation is a BLOCK of
``block_size`` token positions inside one shared pool per layer::

    K, V : [num_blocks, n_heads, block_size, head_dim]

A sequence owns ceil((prompt + max_tokens) / block_size) blocks — its
ACTUAL worst case, not the engine's — and a block table maps its
logical positions to pool blocks. The pool arrays never change shape,
so the compiled decode executable never changes either; "which block
belongs to whom" is host-side bookkeeping, exactly like the slot
table's "which slot belongs to whom", one granularity finer.

Invariants, shared with the slot cache and test-asserted:

- **No zeroing on reuse.** A freed block re-enters the free list with
  its stale K/V intact; the next owner's writes overwrite the prefix
  it uses and the per-sequence length masks everything beyond. There
  is never a zeroing pass between occupants.
- **Block 0 is the null block.** It is never allocated to a request.
  Padded block-table entries point at it, so (a) gathers through
  padding read garbage that the length mask discards, and (b) writes
  from padded lanes (inactive decode slots, the padded tail of a
  prefill chunk past a request's allocation) land in memory nobody
  ever unmasks.
- **No over-commit.** :meth:`BlockAllocator.alloc` is all-or-nothing:
  a request's full worst-case block count is claimed at admission or
  the request stays queued — the engine never admits work it could be
  unable to finish (the alternative, swapping/preemption, trades that
  guarantee for recompute; see docs/generation.md).
- **Shared blocks are immutable.** A block referenced by more than
  one owner (another request's table, the prefix index, a session
  pin) is never written in place: a writer gets a copy-on-write
  duplicate first (`GenerationEngine._cow` copies it into a fresh
  block and swaps the writer's table entry), so readers observe
  bit-identical content for the block's whole shared lifetime.

Prefix sharing (vLLM block sharing + RadixAttention-style reuse,
PAPERS.md) layers three pieces on the allocator: per-block REFCOUNTS
(:meth:`BlockAllocator.share` / a decrementing :meth:`~BlockAllocator.
free`), a :class:`PrefixIndex` mapping chained content hashes of full
prompt blocks to pool blocks, and a :class:`SessionStore` pinning a
finished request's prefix+generated blocks under a client-provided
``session_id`` so the next turn re-prefills only its new suffix.
"""
from __future__ import annotations

import collections
import hashlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..kernels.kv_quant import (canonical_kv_dtype, kv_bytes_per_token,
                                kv_gather_rows, kv_nbytes,
                                kv_scatter_rows, kv_zeros)

#: Block index reserved as the write/read target for padded table
#: entries. Never handed out by the allocator.
NULL_BLOCK = 0


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` positions."""
    return -(-int(tokens) // int(block_size))


def pow2_bucket(n: int, cap: Optional[int] = None) -> int:
    """Smallest power of two >= n (>= 1), optionally clamped to
    ``cap`` — the block-table padding rule that keeps the set of
    prefill executables finite and AOT-warmable. Delegates to the
    serving engine's :func:`~.engine.next_bucket` so the paged and
    dense bucket policies can never silently diverge."""
    from .engine import next_bucket
    return next_bucket(max(int(n), 1), 1,
                       (1 << 30) if cap is None else cap)


class BlockAllocator:
    """Free-list allocator over the pool's block indices.

    Block 0 (:data:`NULL_BLOCK`) is reserved; ``capacity`` counts only
    allocatable blocks. Allocation is all-or-nothing and LIFO, so a
    just-freed (cache-warm) block is reused first — same policy as the
    slot table's free list.

    Blocks are REFCOUNTED so prefix sharing can hand one physical
    block to several owners: :meth:`alloc` sets each block's count to
    1, :meth:`share` bumps it for every additional owner, and
    :meth:`free` decrements — the block re-enters the free list only
    when its last owner releases it. ``used_count`` keeps counting
    UNIQUE blocks (physical pool occupancy), which is what peak/
    fragmentation accounting must reflect under sharing."""

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             f"reserved null block), got {num_blocks}")
        self._free = list(range(self.num_blocks - 1, NULL_BLOCK, -1))
        # mirror of _free for O(1) double-free checks: free() runs on
        # the scheduler thread at every retirement, and a linear scan
        # of the free list there would tax every stream's ITL
        self._free_set = set(self._free)
        self._refs: Dict[int, int] = {}
        self.peak_used = 0

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.capacity - len(self._free)

    @property
    def shared_count(self) -> int:
        """Unique blocks currently held by more than one owner."""
        return sum(1 for c in self._refs.values() if c > 1)

    def ref(self, block: int) -> int:
        """Current refcount of ``block`` (0 if free/unallocated)."""
        return self._refs.get(int(block), 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` blocks (each at refcount 1), or None (claim
        NOTHING) if fewer than ``n`` are free — the no-over-commit
        contract."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(blocks)
        for b in blocks:
            self._refs[b] = 1
        self.peak_used = max(self.peak_used, self.used_count)
        return blocks

    def share(self, blocks: Sequence[int]):
        """Add one owner to each (already-allocated) block. Raises if
        any block is free — sharing can never resurrect a block, so the
        caller's ordering bug (e.g. freeing matched blocks via eviction
        before pinning them) surfaces as an error, not aliasing."""
        for b in blocks:
            b = int(b)
            if self._refs.get(b, 0) < 1:
                raise ValueError(f"cannot share unallocated block {b}")
        for b in blocks:
            self._refs[int(b)] += 1

    def free(self, blocks: Sequence[int]):
        """Drop one owner per block; a block re-enters the free list
        only at refcount 0. No zeroing — stale contents stay masked by
        the next owner's length. Validates the WHOLE batch before
        mutating anything so a bad call can't half-free."""
        counted: Dict[int, int] = {}
        for b in blocks:
            b = int(b)
            if b == NULL_BLOCK or not 0 < b < self.num_blocks:
                raise ValueError(f"block {b} is not allocatable")
            if b in self._free_set:
                raise ValueError(f"double free of block {b}")
            counted[b] = counted.get(b, 0) + 1
            if counted[b] > self._refs.get(b, 0):
                raise ValueError(f"double free of block {b}")
        released = []
        for b in blocks:
            b = int(b)
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                released.append(b)
        self._free.extend(released)
        self._free_set.update(released)

    def stats(self) -> dict:
        return {"total": self.capacity, "free": self.free_count,
                "used": self.used_count, "peak_used": self.peak_used,
                "shared": self.shared_count}


class BlockTable:
    """One request's logical-position → pool-block mapping (host-side
    int32). ``padded(n)`` emits the device-facing row, padded with
    :data:`NULL_BLOCK` to a caller-chosen length (a pow2 bucket for
    prefill executables; the engine-wide max for the decode batch), so
    executable shapes depend on the BUCKET, never the request."""

    def __init__(self, blocks: Sequence[int], block_size: int):
        self.blocks = [int(b) for b in blocks]
        self.block_size = int(block_size)

    def __len__(self) -> int:
        return len(self.blocks)

    @property
    def capacity_tokens(self) -> int:
        return len(self.blocks) * self.block_size

    def padded(self, n: int) -> np.ndarray:
        if n < len(self.blocks):
            raise ValueError(f"cannot pad {len(self.blocks)} blocks "
                             f"into a table of {n}")
        out = np.full(n, NULL_BLOCK, np.int32)
        out[:len(self.blocks)] = self.blocks
        return out


class PagedKVCache:
    """Per-layer pooled K/V blocks, the paged sibling of
    :class:`~.kvcache.KVCache`: same pytree-threaded-through-donated-
    executables lifecycle, but the leading axis is POOL BLOCKS shared
    by every sequence instead of per-sequence slots.

    ``layer_shapes`` are per-layer ``(n_heads, block_size, head_dim)``
    — i.e. ``model.cache_shapes(block_size)``.

    ``kv_dtype`` selects the storage precision (ROADMAP item 3):
    ``"f32"`` (exact, default), ``"bf16"``, or ``"int8"`` — per-layer
    pools become
    :class:`~deeplearning4j_tpu.kernels.kv_quant.QuantArray` pytrees
    with a ``[num_blocks, H, block_size]`` f32 scale sidecar, i.e.
    per-block-per-head scales indexed by block id (the block is the
    quantization granule). Copy-on-write and the no-zeroing-on-reuse
    contract carry over unchanged: a block copy copies its scale row,
    a recycled block's stale (quantized) tail stays masked by the next
    owner's length."""

    def __init__(self, layer_shapes: Sequence[Tuple[int, int, int]],
                 num_blocks: int, kv_dtype: str = "f32"):
        self.num_blocks = int(num_blocks)
        self.layer_shapes = [tuple(s) for s in layer_shapes]
        self.block_size = int(self.layer_shapes[0][1])
        self.kv_dtype = canonical_kv_dtype(kv_dtype)
        self.ks: List = [
            kv_zeros((self.num_blocks,) + s, self.kv_dtype)
            for s in self.layer_shapes]
        self.vs: List = [
            kv_zeros((self.num_blocks,) + s, self.kv_dtype)
            for s in self.layer_shapes]

    def nbytes(self) -> int:
        """Device bytes the pool pins: ``num_blocks * block_size * H *
        Dh * 2 (K+V) * layers * itemsize``, plus the f32 scale
        sidecars for int8 — the number to budget against HBM
        (docs/generation.md has the sizing guidance)."""
        return int(sum(2 * kv_nbytes((self.num_blocks,) + s,
                                     self.kv_dtype)
                       for s in self.layer_shapes))

    def block_nbytes(self) -> int:
        """Bytes one block pins across all layers (K+V, sidecar
        included)."""
        return self.nbytes() // self.num_blocks

    def scale_nbytes(self) -> int:
        """Bytes of the f32 scale sidecars alone (0 unless int8)."""
        if self.kv_dtype != "int8":
            return 0
        return int(sum(2 * int(np.prod((self.num_blocks,) + s[:-1]))
                       * 4 for s in self.layer_shapes))

    def bytes_per_token(self) -> int:
        """K+V bytes one token position costs across all layers at the
        pool dtype — the per-session sizing unit for both the device
        pool AND the host tier below it (a demoted run stores the same
        bytes per token; see docs/generation.md "Hierarchical KV
        tier")."""
        return kv_bytes_per_token(self.layer_shapes, self.kv_dtype)


def export_block_run(kcs, vcs, idx):
    """Pure fn: gather pool rows ``idx`` out of every layer's K and V
    pool — the device half of a demotion. Traced into one executable
    per pow2 idx bucket by the engine (pools NOT donated: a failed
    demotion must leave the device tier untouched)."""
    return ([kv_gather_rows(k, idx) for k in kcs],
            [kv_gather_rows(v, idx) for v in vcs])


def import_block_run(kcs, vcs, k_rows, v_rows, idx):
    """Pure fn: scatter gathered runs back into pool rows ``idx`` —
    the device half of a restore. Padded idx entries point at
    :data:`NULL_BLOCK` so junk writes land where nothing is ever read.
    The engine compiles this with pools DONATED (a restore writes in
    place), so a real failure here is a
    :class:`~deeplearning4j_tpu.faults.CorruptedStateFault`."""
    return ([kv_scatter_rows(k, r, idx) for k, r in zip(kcs, k_rows)],
            [kv_scatter_rows(v, r, idx) for v, r in zip(vcs, v_rows)])


def chain_hashes(tokens: Sequence[int], block_size: int) -> List[bytes]:
    """Chained content hash per FULL block of ``tokens``:
    ``h_i = blake2b(h_{i-1} || tokens[i*Bs:(i+1)*Bs])``.

    Chaining makes each digest identify the block's content AND its
    whole prefix, so two requests share block i only when their first
    ``(i+1)*block_size`` tokens are identical — the property that
    lets the engine reuse the block's K/V verbatim (K/V are pure
    per-position projections of the prefix). Partial tail blocks are
    never hashed: their content is still growing, so they are only
    shareable via session pins + copy-on-write."""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    bs = int(block_size)
    out: List[bytes] = []
    prev = b""
    for i in range(len(toks) // bs):
        h = hashlib.blake2b(prev + toks[i * bs:(i + 1) * bs].tobytes(),
                            digest_size=16).digest()
        out.append(h)
        prev = h
    return out


class PrefixIndex:
    """LRU map from chained block hash → pool block, the cross-request
    half of prefix sharing (RadixAttention's radix tree flattened to a
    hash map — chained digests already encode the path, PAPERS.md).

    The index OWNS one reference per registered block (the engine
    ``share()``s on register, ``free()``s on evict), so an indexed
    block survives the registering request and stays bit-stable for
    future matches. Pure bookkeeping: no allocator calls happen here —
    every method returns the block ids whose ownership changed and the
    caller settles refcounts, keeping one thread (the scheduler) in
    charge of allocator state."""

    def __init__(self, capacity: int = 1024):
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._entries: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def blocks(self) -> Iterator[int]:
        """All indexed blocks, eviction order first."""
        return iter(self._entries.values())

    def match(self, hashes: Sequence[bytes]) -> List[int]:
        """Longest indexed chain prefix of ``hashes`` → its blocks.
        Matched entries are LRU-touched (a shared system prompt stays
        hot no matter how old its registration is)."""
        out: List[int] = []
        for h in hashes:
            b = self._entries.get(h)
            if b is None:
                break
            self._entries.move_to_end(h)
            out.append(b)
        return out

    def register(self, digest: bytes, block: int) -> bool:
        """Insert ``digest → block``; True iff the entry is NEW (the
        caller then owns transferring a reference to the index). An
        existing entry is kept — its block already holds the content —
        and merely LRU-touched."""
        if digest in self._entries:
            self._entries.move_to_end(digest)
            return False
        self._entries[digest] = int(block)
        return True

    def evict_lru(self) -> Optional[int]:
        """Drop the least-recently-matched entry; returns its block
        (caller frees the index's reference) or None when empty."""
        if not self._entries:
            return None
        _, block = self._entries.popitem(last=False)
        return block

    def evict_lru_entry(self) -> Optional[Tuple[bytes, int]]:
        """Like :meth:`evict_lru` but returns ``(digest, block)`` so a
        demoting caller can key the host copy by the chained digest
        (the engine's demote-on-evict path needs the identity, not
        just the block to free)."""
        if not self._entries:
            return None
        return self._entries.popitem(last=False)

    def evict_over_capacity(self) -> List[int]:
        """Evict LRU entries until within capacity; returns their
        blocks for the caller to free."""
        out: List[int] = []
        while len(self._entries) > self.capacity:
            out.append(self._entries.popitem(last=False)[1])
        return out

    def clear(self) -> List[int]:
        """Drop every entry; returns all previously indexed blocks."""
        out = list(self._entries.values())
        self._entries.clear()
        return out


class Session:
    """One pinned conversation: the K/V-valid token prefix (prompt +
    generated tokens whose K/V were actually written) and the blocks
    holding it. Held by :class:`SessionStore`. ``session_id`` is
    stamped by :meth:`SessionStore.put` so a displaced/evicted Session
    still knows which conversation it belongs to — the demote-on-evict
    path keys the host-tier copy by it."""
    __slots__ = ("tokens", "blocks", "session_id")

    def __init__(self, tokens: np.ndarray, blocks: List[int],
                 session_id: Optional[str] = None):
        self.tokens = tokens
        self.blocks = blocks
        self.session_id = session_id


class SessionStore:
    """LRU map ``session_id`` → :class:`Session`, the persistent half
    of prefix sharing: a finished turn's blocks stay pinned (the store
    owns one reference per block) so the next turn of the same
    conversation re-prefills only its new suffix.

    Like :class:`PrefixIndex` this is pure bookkeeping — methods
    return displaced :class:`Session` objects and the caller frees
    their blocks."""

    def __init__(self, capacity: int = 64):
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._entries: "collections.OrderedDict[str, Session]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, session_id: str) -> bool:
        return session_id in self._entries

    def ids(self) -> List[str]:
        return list(self._entries.keys())

    def get(self, session_id: str) -> Optional[Session]:
        """LRU-touching lookup."""
        sess = self._entries.get(session_id)
        if sess is not None:
            self._entries.move_to_end(session_id)
        return sess

    def put(self, session_id: str, tokens: np.ndarray,
            blocks: List[int]) -> List[Session]:
        """Pin a finished turn, displacing (a) the session's previous
        pin if any and (b) LRU entries past capacity. Returns every
        displaced Session; the caller frees their blocks."""
        displaced: List[Session] = []
        old = self._entries.pop(session_id, None)
        if old is not None:
            displaced.append(old)
        self._entries[session_id] = Session(tokens, blocks, session_id)
        while len(self._entries) > self.capacity:
            displaced.append(self._entries.popitem(last=False)[1])
        return displaced

    def evict_lru(self) -> Optional[Session]:
        """Drop the least-recently-used session; caller frees its
        blocks. None when empty."""
        if not self._entries:
            return None
        return self._entries.popitem(last=False)[1]

    def clear(self) -> List[Session]:
        out = list(self._entries.values())
        self._entries.clear()
        return out

    def iter_pins(self) -> Iterator[Tuple[List[int], int]]:
        """(blocks, n_valid_tokens) per live session — the inputs the
        engine's kv_tokens_live gauge needs."""
        for sess in self._entries.values():
            yield sess.blocks, len(sess.tokens)
