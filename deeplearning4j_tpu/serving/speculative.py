"""Speculative decoding: draft-model propose, chunk-verified accept.

Decode is latency-bound, not compute-bound: every generated token costs
one full device round-trip whose matmuls barely occupy the chip. The
classic fix (Leviathan et al. 2023; Chen et al. 2023 — PAPERS.md) is to
let a cheap DRAFT model guess k tokens ahead and have the target model
score all k guesses in ONE batched forward — sequential target calls
collapse into one call whenever the draft guesses right, and the
machinery degrades to plain decode (one committed token per round)
whenever it guesses wrong.

This module builds the three pure device functions the engine
(:mod:`.generation`) compiles and schedules; the engine owns all
bookkeeping (eligibility, cursor commit, COW, fault ladder):

- **prime**: a draft prefill — write the draft's K/V for a lane's whole
  committed prefix into its slim dense cache. Runs once per admission
  (and per recovery re-admission) at decode-entry, because with prefix
  sharing the TARGET may have skipped prefill entirely while the draft,
  which shares nothing, still needs its own state.
- **propose**: k greedy draft decode steps, unrolled IN-GRAPH over the
  full slot batch — one device call proposes for every lane at once,
  which is what keeps the per-round dispatch overhead at (1 draft +
  per-lane verify) instead of (k drafts + ...).
- **verify**: the target scores ``[current_token, d_1..d_k]`` — k+1
  rows — in one causal pass, samples a target token at EVERY row with
  the engine's exact decode sampling math (same
  ``fold_in(PRNGKey(seed), step)`` uniforms, same top-k/temperature
  core), and computes the accepted run length in-graph.

**The identity contract.** Row ``i`` of a verify span sees exactly the
keys a plain decode step ``i`` would see, and samples with exactly the
fold a plain decode step ``i`` would fold — so the target sample
``tgt_i`` at each row IS the token non-speculative decode would have
emitted. Acceptance is exact-match: draft token ``d_{i+1}`` is accepted
iff it EQUALS ``tgt_i``; the first mismatching row's own target sample
is the correction token, and an all-accepted round's last row yields a
bonus token for free. Every emitted token is therefore a target sample
from the request's own PRNG stream — output is bit-identical to
non-speculative decode at EVERY temperature, not merely
distribution-exact (which a min(1, p/q) acceptance rule would give; an
exact-match rule trades a little accept rate for replayable streams,
which the recompute-recovery contract already relies on).

**Rollback is cursor-only.** A rejected tail's K/V was already written
past the accepted length, and stays there: the engine commits
``pos``/``step`` forward by the accepted run only, and the
no-zeroing-on-reuse invariant (:mod:`.kvcache`, :mod:`.paging`) masks
everything beyond the cursor until a later accepted write overwrites
it. No device work is spent undoing anything.

The paged verify is literally the chunked-prefill runtime-offset
kernel (``forward_prefill_chunk``) with sampling bolted on — it rides
the same (bucket, table-bucket) executable grid the chunk ladder
warms. The slots verify uses the dense sibling ``forward_verify``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .paging import pow2_bucket


def verify_bucket(k: int) -> int:
    """Device width of a verify span: the current token plus k draft
    proposals, padded to the pow2 bucket ladder so verify executables
    stay warmable. Padded rows write masked junk — same contract as a
    prefill chunk's padded tail."""
    return pow2_bucket(int(k) + 1)


def make_prime_fn(draft):
    """Draft prefill into the draft's dense slot cache: the engine's
    ``_prefill_fn`` minus sampling (the draft never emits — it only
    holds state to propose from). Returns ``prime(params, kcs, vcs,
    tokens [1, B], length, slot) -> (ok, kcs, vcs)`` where ``ok`` is
    the finite-logits guard over the valid rows."""

    def prime(params, kcs, vcs, tokens, length, slot):
        bucket = tokens.shape[1]
        key_mask = (jnp.arange(bucket)[None] < length).astype(
            jnp.float32)
        logits, ks, vs = draft.forward_prefill(params, tokens, key_mask)
        ok = jnp.all(jnp.where(
            (jnp.arange(bucket) < length)[None, :, None],
            jnp.isfinite(logits), True))
        kcs = [jax.lax.dynamic_update_slice(kc, k, (slot, 0, 0, 0))
               for kc, k in zip(kcs, ks)]
        vcs = [jax.lax.dynamic_update_slice(vc, v, (slot, 0, 0, 0))
               for vc, v in zip(vcs, vs)]
        return ok, kcs, vcs
    return prime


def make_propose_fn(draft, k: int, impl: str = "auto"):
    """k greedy draft decode steps unrolled in-graph over the slot
    batch. Greedy on purpose: proposals only SEED verification — the
    target's own sampling decides what is emitted, so the draft's job
    is to maximize the chance of matching the target's choice, and at
    the temperatures where speculation pays (low), argmax is that
    maximizer. Returns ``propose(params, kcs, vcs, tokens [S],
    pos [S]) -> (proposals [S, k], ok [S], kcs, vcs)`` with ``ok``
    the per-lane finite-logits guard ANDed across all k steps (a NaN
    anywhere in a lane's draft chain disqualifies that lane's round —
    the engine then falls back to plain decode for it, never failing
    the request)."""
    k = int(k)

    def propose(params, kcs, vcs, tokens, pos):
        t, p = tokens, pos
        ok = jnp.ones(tokens.shape[0], bool)
        props = []
        for _ in range(k):
            logits, kcs, vcs = draft.forward_decode(params, t, p, kcs,
                                                    vcs, impl)
            ok = ok & jnp.all(jnp.isfinite(logits), axis=-1)
            t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            p = p + 1
            props.append(t)
        return jnp.stack(props, axis=1), ok, kcs, vcs
    return propose


def _verify_tail(logits, tokens, vlen, seed, step0, temp, top_k):
    """Shared in-graph accept/sample tail: target-sample every row
    with the engine's decode sampling math, then count the leading
    run of draft rows that MATCH the target's choice.

    Row ``i`` samples with ``fold_in(PRNGKey(seed), step0 + i)`` — the
    exact uniforms plain decode steps would burn — via the engine's
    ``_sample_batch``. Accept mask: draft token ``tokens[0, i+1]``
    matches target sample ``tgt_i``, limited to the ``vlen - 1`` real
    draft rows; the accepted length is the cumprod-sum of the leading
    run. Returns (tgt [C], n_accepted, ok)."""
    from .generation import _sample_batch
    C = tokens.shape[1]
    rows = jnp.arange(C)
    ok = jnp.all(jnp.where((rows < vlen)[:, None],
                           jnp.isfinite(logits), True))
    tgt = _sample_batch(
        logits,
        jnp.broadcast_to(jnp.asarray(temp, jnp.float32), (C,)),
        jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (C,)),
        jnp.broadcast_to(jnp.asarray(seed, jnp.uint32), (C,)),
        step0 + rows.astype(jnp.int32))
    match = (tgt[:-1] == tokens[0, 1:]) & (rows[:-1] < vlen - 1)
    n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
    return tgt, n_acc, ok


def make_verify_paged_fn(model):
    """Paged verification: ``forward_prefill_chunk`` — the warmed
    runtime-offset chunk kernel, unchanged — plus the shared
    accept/sample tail. Returns ``verify(params, kcs, vcs,
    tokens [1, C], p0, vlen, table, seed, step0, temp, top_k) ->
    (tgt [C], n_accepted, ok, kcs, vcs)``."""

    def verify(params, kcs, vcs, tokens, p0, vlen, table, seed, step0,
               temp, top_k):
        logits, kcs, vcs = model.forward_prefill_chunk(
            params, tokens, p0, vlen, kcs, vcs, table)
        tgt, n_acc, ok = _verify_tail(logits, tokens, vlen, seed,
                                      step0, temp, top_k)
        return tgt, n_acc, ok, kcs, vcs
    return verify


def make_verify_slots_fn(model):
    """Dense-backend verification: ``forward_verify`` (the slot-cache
    sibling of the chunk kernel) plus the shared accept/sample tail.
    Returns ``verify(params, kcs, vcs, tokens [1, C], p0, vlen, slot,
    seed, step0, temp, top_k) -> (tgt [C], n_accepted, ok, kcs,
    vcs)``."""

    def verify(params, kcs, vcs, tokens, p0, vlen, slot, seed, step0,
               temp, top_k):
        logits, kcs, vcs = model.forward_verify(
            params, tokens, p0, vlen, kcs, vcs, slot)
        tgt, n_acc, ok = _verify_tail(logits, tokens, vlen, seed,
                                      step0, temp, top_k)
        return tgt, n_acc, ok, kcs, vcs
    return verify
