"""Static-shape slot-managed KV cache for continuous-batching decode.

The vLLM/PagedAttention insight (PAPERS.md), applied at slot rather
than block granularity: preallocate the cache ONCE as per-layer
``[num_slots, n_heads, max_seq_len, head_dim]`` arrays, and let
sequences claim/release SLOTS while the array shapes — and therefore
the compiled decode executable — never change. A sequence that
finishes frees its slot immediately; the next queued request's prefill
overwrites the slot's prefix and the unwritten tail stays masked by
the per-slot length, so no zeroing pass is ever needed between
occupants.

Host-side bookkeeping (which slot belongs to which request, each
slot's write position, sampling params) lives in :class:`SlotTable` as
small numpy arrays that ship to the device once per decode step — the
device never sees request identity, only the dense slot batch.

**The no-zeroing-on-reuse invariant** (test-asserted in
``tests/test_paged_generation.py::TestNoZeroingInvariant``): a freed
slot is handed to its next occupant with the previous occupant's K/V
intact. Correctness rests entirely on the attention LENGTH mask — the
decode kernels (`kernels/decode_attention.py`,
`kernels/paged_attention.py`) mask every key position ``>= length``,
so the stale tail beyond the new occupant's ``seq_len`` is
mathematically invisible, and prefill overwrites exactly the prefix
the new occupant will unmask. Nothing in the engine may ever rely on
cache contents beyond the live length, and no code path zeroes on
free/alloc (a zeroing pass would cost a full cache write per
admission for no semantic gain). The SAME contract carries to the
paged backend one granularity finer: a recycled BLOCK keeps its stale
contents, masked by the owning sequence's length
(`serving/paging.py`).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..kernels.kv_quant import (canonical_kv_dtype, kv_bytes_per_token,
                                kv_nbytes, kv_zeros)


class KVCache:
    """Per-layer K/V slot arrays, held as a pytree the compiled
    prefill/decode executables thread through (functionally: each call
    returns the updated arrays, which replace these).

    ``kv_dtype`` selects the storage precision (ROADMAP item 3):
    ``"f32"`` (exact, default), ``"bf16"`` (half the bytes), or
    ``"int8"`` (quarter the bytes — each per-layer array becomes a
    :class:`~deeplearning4j_tpu.kernels.kv_quant.QuantArray` with a
    per-position per-head f32 scale sidecar; still a pytree, so the
    executables and donation tuples are unchanged)."""

    def __init__(self, layer_shapes: Sequence[Tuple[int, int, int]],
                 num_slots: int, kv_dtype: str = "f32"):
        self.num_slots = int(num_slots)
        self.layer_shapes = [tuple(s) for s in layer_shapes]
        self.kv_dtype = canonical_kv_dtype(kv_dtype)
        self.ks: List = [kv_zeros((self.num_slots,) + s, self.kv_dtype)
                         for s in self.layer_shapes]
        self.vs: List = [kv_zeros((self.num_slots,) + s, self.kv_dtype)
                         for s in self.layer_shapes]

    def nbytes(self) -> int:
        """Device bytes the cache pins (int8 scale sidecars included)
        — the number to budget num_slots * max_seq_len against HBM."""
        return int(sum(2 * kv_nbytes((self.num_slots,) + s,
                                     self.kv_dtype)
                       for s in self.layer_shapes))

    def scale_nbytes(self) -> int:
        """Bytes of the f32 scale sidecars alone (0 unless int8)."""
        if self.kv_dtype != "int8":
            return 0
        return int(sum(2 * int(np.prod((self.num_slots,) + s[:-1])) * 4
                       for s in self.layer_shapes))

    def bytes_per_token(self) -> int:
        """K+V bytes one token position costs across all layers at the
        cache dtype — the sizing unit shared by the slot cache, the
        paged pool, and the host/disk tier below it
        (docs/generation.md "Hierarchical KV tier")."""
        return kv_bytes_per_token(self.layer_shapes, self.kv_dtype)


class SlotTable:
    """Host-side slot bookkeeping: free-list allocation plus the dense
    per-slot arrays (current token, write position, sampling params)
    that feed the decode executable each step. Inactive slots carry
    benign values (pos 0, temp 0) — they ride the batch as masked
    lanes and their lanes' outputs are simply never read."""

    def __init__(self, num_slots: int):
        self.num_slots = int(num_slots)
        self._free = list(range(self.num_slots - 1, -1, -1))
        self.requests: List[Optional[object]] = [None] * self.num_slots
        self.token = np.zeros(self.num_slots, np.int32)
        self.pos = np.zeros(self.num_slots, np.int32)
        self.step = np.zeros(self.num_slots, np.int32)
        self.seed = np.zeros(self.num_slots, np.uint32)
        self.temp = np.zeros(self.num_slots, np.float32)
        self.top_k = np.zeros(self.num_slots, np.int32)
        # fused in-graph termination (ISSUE 14): the decode executable
        # computes per-lane done = hit-EOS | hit-max_tokens itself, so
        # retirement needs no extra host reads. eos = -1 means "no EOS
        # id" (sampled tokens are always >= 0, so -1 never matches);
        # max_steps is the request's max_tokens, 0 for inactive lanes
        # (their done flags are never read)
        self.eos = np.full(self.num_slots, -1, np.int32)
        self.max_steps = np.zeros(self.num_slots, np.int32)
        # speculative decoding (serving/speculative.py): slots with
        # spec_ok=False fall back to plain one-token decode — set at
        # draft-prime time, cleared on free() and on per-lane draft
        # failure (a draft NaN must never fail the request)
        self.spec_ok = np.zeros(self.num_slots, bool)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return self.num_slots - len(self._free)

    def active_slots(self) -> List[int]:
        return [s for s in range(self.num_slots)
                if self.requests[s] is not None]

    def alloc(self, request) -> Optional[int]:
        """Claim a free slot for ``request`` (None when full)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self.requests[slot] = request
        return slot

    def free(self, slot: int):
        """Release a slot. No cache zeroing: the next occupant's
        prefill overwrites the prefix and its length masks the tail."""
        if self.requests[slot] is None:
            raise ValueError(f"slot {slot} is already free")
        self.requests[slot] = None
        self.token[slot] = 0
        self.pos[slot] = 0
        self.step[slot] = 0
        self.seed[slot] = 0
        self.temp[slot] = 0.0
        self.top_k[slot] = 0
        self.eos[slot] = -1
        self.max_steps[slot] = 0
        self.spec_ok[slot] = False
        self._free.append(slot)

    def commit(self, slot: int, token: int, n_accepted: int):
        """Settle a slot's decode cursor after a speculative round:
        advance by the accepted run (``n_accepted`` tokens emitted,
        ``token`` the last of them) and leave everything the device
        wrote PAST the accepted length behind the cursor — the
        rejected tail needs no explicit rollback because pos/step are
        the only commit pointers; stale K/V beyond them is masked by
        every reader and re-written by the next accepted step, the
        same no-zeroing contract that covers slot reuse."""
        if self.requests[slot] is None:
            raise ValueError(f"slot {slot} is free")
        if n_accepted < 1:
            raise ValueError(f"speculative round must commit >= 1 "
                             f"token, got {n_accepted}")
        self.token[slot] = token
        self.pos[slot] += n_accepted
        self.step[slot] += n_accepted
