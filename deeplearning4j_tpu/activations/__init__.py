"""Activation catalog — 21 activations matching the reference set.

Ref: nd4j-api `org/nd4j/linalg/activations/impl/Activation*.java` (21 impls)
and the `Activation` enum in `org/nd4j/linalg/activations/Activation.java`.

TPU-first: every activation is a pure jnp function; backprop comes from JAX
autodiff (the reference hand-writes each `backprop()`); XLA fuses these into
the surrounding matmul/conv epilogue so they cost ~0 extra HBM traffic.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


class Activation:
    """Base activation. Subclasses are stateless & hashable (usable as
    static jit arguments and JSON-serializable by ``name``)."""

    #: canonical lowercase name (matches reference ``Activation`` enum names)
    name: str = "identity"

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    # -- serde ---------------------------------------------------------
    def to_json(self) -> dict:
        return {"@class": self.name}

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items()))))

    def __repr__(self):
        return f"Activation({self.name})"


class Identity(Activation):
    name = "identity"

    def __call__(self, x):
        return x


class Sigmoid(Activation):
    name = "sigmoid"

    def __call__(self, x):
        return jax.nn.sigmoid(x)


class Tanh(Activation):
    name = "tanh"

    def __call__(self, x):
        return jnp.tanh(x)


class ReLU(Activation):
    name = "relu"

    def __call__(self, x):
        return jax.nn.relu(x)


class ReLU6(Activation):
    name = "relu6"

    def __call__(self, x):
        return jax.nn.relu6(x)


class LeakyReLU(Activation):
    """Ref: ActivationLReLU.java (default alpha 0.01)."""

    name = "leakyrelu"

    def __init__(self, alpha: float = 0.01):
        self.alpha = float(alpha)

    def __call__(self, x):
        return jax.nn.leaky_relu(x, self.alpha)

    def to_json(self):
        return {"@class": self.name, "alpha": self.alpha}


class ELU(Activation):
    """Ref: ActivationELU.java (default alpha 1.0)."""

    name = "elu"

    def __init__(self, alpha: float = 1.0):
        self.alpha = float(alpha)

    def __call__(self, x):
        return jax.nn.elu(x, self.alpha)

    def to_json(self):
        return {"@class": self.name, "alpha": self.alpha}


class SELU(Activation):
    name = "selu"

    def __call__(self, x):
        return jax.nn.selu(x)


class GELU(Activation):
    """Ref: ActivationGELU.java — tanh approximation by default there;
    we use the exact erf form (XLA lowers both efficiently on TPU)."""

    name = "gelu"

    def __init__(self, precise: bool = True):
        self.precise = bool(precise)

    def __call__(self, x):
        return jax.nn.gelu(x, approximate=not self.precise)

    def to_json(self):
        return {"@class": self.name, "precise": self.precise}


class Swish(Activation):
    name = "swish"

    def __call__(self, x):
        return jax.nn.swish(x)


class Softmax(Activation):
    """Softmax over the last axis (reference applies over dim 1 of NCHW-style
    2d activations, which is the feature/last axis in our NC layout)."""

    name = "softmax"

    def __call__(self, x):
        return jax.nn.softmax(x, axis=-1)


class SoftPlus(Activation):
    name = "softplus"

    def __call__(self, x):
        return jax.nn.softplus(x)


class SoftSign(Activation):
    name = "softsign"

    def __call__(self, x):
        return jax.nn.soft_sign(x)


class HardSigmoid(Activation):
    """Ref: ActivationHardSigmoid.java — clip(0.2*x + 0.5, 0, 1)."""

    name = "hardsigmoid"

    def __call__(self, x):
        return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class HardTanh(Activation):
    name = "hardtanh"

    def __call__(self, x):
        return jnp.clip(x, -1.0, 1.0)


class Cube(Activation):
    name = "cube"

    def __call__(self, x):
        return x * x * x


class RationalTanh(Activation):
    """Ref: ActivationRationalTanh.java —
    1.7159 * tanh_approx(2x/3) with the rational tanh approximation
    f(x) = clip_{-1,1}( 1.7159 * sgn(y)*(1 - 1/(1+|y|+y^2+1.41645*y^4)) )."""

    name = "rationaltanh"

    def __call__(self, x):
        y = x * (2.0 / 3.0)
        a = jnp.abs(y)
        approx = jnp.sign(y) * (1.0 - 1.0 / (1.0 + a + y * y + 1.41645 * (y ** 4)))
        return jnp.clip(1.7159 * approx, -1.0, 1.0)


class RectifiedTanh(Activation):
    """Ref: ActivationRectifiedTanh.java — max(0, tanh(x))."""

    name = "rectifiedtanh"

    def __call__(self, x):
        return jnp.maximum(0.0, jnp.tanh(x))


class ThresholdedReLU(Activation):
    """Ref: ActivationThresholdedReLU.java — x if x > theta else 0."""

    name = "thresholdedrelu"

    def __init__(self, theta: float = 1.0):
        self.theta = float(theta)

    def __call__(self, x):
        return jnp.where(x > self.theta, x, jnp.zeros_like(x))

    def to_json(self):
        return {"@class": self.name, "theta": self.theta}


class PReLU(Activation):
    """Parametric ReLU. The learnable alpha lives in the owning layer's
    params (ref: ActivationPReLU.java holds an alpha INDArray); call with
    the alpha array via :meth:`apply_with_alpha`."""

    name = "prelu"

    def __call__(self, x):  # default alpha 0.01 when used standalone
        return jax.nn.leaky_relu(x, 0.01)

    @staticmethod
    def apply_with_alpha(x, alpha):
        return jnp.where(x >= 0, x, alpha * x)


class RReLU(Activation):
    """Randomized leaky ReLU (ref: ActivationRReLU.java, l=1/8, u=1/3).
    Train mode samples alpha ~ U(l,u) (pass an rng key); eval uses the
    mean (l+u)/2."""

    name = "rrelu"

    def __init__(self, l: float = 1.0 / 8.0, u: float = 1.0 / 3.0):
        self.l = float(l)
        self.u = float(u)

    def __call__(self, x, rng: Optional[jax.Array] = None, train: bool = False):
        if train and rng is not None:
            alpha = jax.random.uniform(rng, x.shape, x.dtype, self.l, self.u)
        else:
            alpha = (self.l + self.u) / 2.0
        return jnp.where(x >= 0, x, alpha * x)

    def to_json(self):
        return {"@class": self.name, "l": self.l, "u": self.u}


class Mish(Activation):
    """x * tanh(softplus(x)) — present in later reference versions; cheap
    on TPU and used by some YOLO variants."""

    name = "mish"

    def __call__(self, x):
        return x * jnp.tanh(jax.nn.softplus(x))


_REGISTRY: Dict[str, type] = {}
for _cls in list(globals().values()):
    if isinstance(_cls, type) and issubclass(_cls, Activation) and _cls is not Activation:
        _REGISTRY[_cls.name] = _cls


def get(spec) -> Activation:
    """Resolve an activation from an Activation instance, a name string
    (reference enum style, case-insensitive), or a dict from to_json()."""
    if isinstance(spec, Activation):
        return spec
    if callable(spec) and not isinstance(spec, str):
        fn = spec

        class _Wrapped(Activation):
            name = getattr(spec, "__name__", "custom")

            def __call__(self, x):
                return fn(x)

        return _Wrapped()
    if isinstance(spec, dict):
        d = dict(spec)
        name = d.pop("@class")
        return _REGISTRY[name](**d)
    name = str(spec).lower().replace("_", "")
    if name == "lrelu":
        name = "leakyrelu"
    if name not in _REGISTRY:
        raise ValueError(f"Unknown activation: {spec!r}. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()


def names():
    return sorted(_REGISTRY)
