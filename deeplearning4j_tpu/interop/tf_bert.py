"""Frozen-BERT GraphDef builder (real TensorFlow as oracle).

The reference validates its import axis by running a real frozen
BERT-MRPC graph through the TF importer and fine-tuning it
(`/root/reference/nd4j/nd4j-backends/nd4j-tests/src/test/java/org/nd4j/imports/TFGraphs/BERTGraphTest.java:29`).
This image has no egress, so instead of downloading the Google
checkpoint we *generate* a BERT graph of any size with in-process
TensorFlow (the same dependency the reference's `nd4j-tensorflow`
GraphRunner uses), freeze it, and keep TF's own outputs as the golden
expectations. Architecture matches the BERT encoder stack: learned
token/position/segment embeddings, post-LN transformer blocks with
erf-GELU, tanh pooler over [CLS], classifier head.

Used by tests/fixtures/gen_tfgraphs.py (corpus case `bert_mini`), the
BERT fine-tune test, and bench.py's BERT samples/sec line.
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def bert_config(preset: str = "mini") -> Dict[str, int]:
    """Named sizes. `base` mirrors google-research BERT-base (L=12,
    H=768, A=12); `mini`/`tiny` are the small grid sizes from the
    public BERT-miniatures release."""
    presets = {
        "tiny": dict(L=2, H=128, A=2),
        "mini": dict(L=4, H=256, A=4),
        "small": dict(L=4, H=512, A=8),
        "medium": dict(L=8, H=512, A=8),
        "base": dict(L=12, H=768, A=12),
    }
    return dict(presets[preset])


def build_frozen_bert(vocab: int = 1000, seq_len: int = 128,
                      n_classes: int = 2, preset: str = "mini",
                      seed: int = 0) -> Tuple[bytes, dict]:
    """Build + freeze a BERT classifier graph with real TF.

    Returns (graphdef_bytes, meta) where meta has input placeholder
    names ('ids', 'mask'), the output node name, and sizes. Outputs are
    class probabilities [batch, n_classes].
    """
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    cfg = bert_config(preset)
    L, H, A = cfg["L"], cfg["H"], cfg["A"]
    T, V = seq_len, vocab
    rs = np.random.RandomState(seed)

    def W(*shape, s=0.02):
        return tf.constant(rs.randn(*shape).astype(np.float32) * s)

    p: Dict[str, object] = {
        "tok_emb": W(V, H), "pos_emb": W(T, H), "seg_emb": W(2, H),
        "emb_ln_g": tf.constant(np.ones(H, np.float32)),
        "emb_ln_b": tf.constant(np.zeros(H, np.float32)),
        "pool_w": W(H, H), "pool_b": W(H),
        "cls_w": W(H, n_classes), "cls_b": W(n_classes),
    }
    for l in range(L):
        for n in ("q", "k", "v", "o"):
            p[f"l{l}_{n}_w"] = W(H, H)
            p[f"l{l}_{n}_b"] = W(H)
        p[f"l{l}_ff1_w"] = W(H, 4 * H)
        p[f"l{l}_ff1_b"] = W(4 * H)
        p[f"l{l}_ff2_w"] = W(4 * H, H)
        p[f"l{l}_ff2_b"] = W(H)
        for ln in ("ln1", "ln2"):
            p[f"l{l}_{ln}_g"] = tf.constant(np.ones(H, np.float32))
            p[f"l{l}_{ln}_b"] = tf.constant(np.zeros(H, np.float32))

    def layer_norm(x, g, b):
        mean = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(x, mean),
                             axis=-1, keepdims=True)
        return (x - mean) * tf.math.rsqrt(var + 1e-12) * g + b

    @tf.function
    def bert(ids, mask):
        x = (tf.gather(p["tok_emb"], ids) + p["pos_emb"]
             + tf.gather(p["seg_emb"], tf.zeros_like(ids)))
        x = layer_norm(x, p["emb_ln_g"], p["emb_ln_b"])
        amask = (1.0 - tf.cast(mask, tf.float32)[:, None, None, :]) * -1e4
        for l in range(L):
            q = tf.matmul(x, p[f"l{l}_q_w"]) + p[f"l{l}_q_b"]
            k = tf.matmul(x, p[f"l{l}_k_w"]) + p[f"l{l}_k_b"]
            v = tf.matmul(x, p[f"l{l}_v_w"]) + p[f"l{l}_v_b"]

            def heads(t):
                t = tf.reshape(t, [-1, T, A, H // A])
                return tf.transpose(t, [0, 2, 1, 3])

            scores = tf.matmul(heads(q), heads(k), transpose_b=True) \
                / np.float32(np.sqrt(H // A))
            probs = tf.nn.softmax(scores + amask, axis=-1)
            ctx = tf.transpose(tf.matmul(probs, heads(v)), [0, 2, 1, 3])
            ctx = tf.reshape(ctx, [-1, T, H])
            att = tf.matmul(ctx, p[f"l{l}_o_w"]) + p[f"l{l}_o_b"]
            x = layer_norm(x + att, p[f"l{l}_ln1_g"], p[f"l{l}_ln1_b"])
            h = tf.nn.gelu(tf.matmul(x, p[f"l{l}_ff1_w"])
                           + p[f"l{l}_ff1_b"], approximate=False)
            h = tf.matmul(h, p[f"l{l}_ff2_w"]) + p[f"l{l}_ff2_b"]
            x = layer_norm(x + h, p[f"l{l}_ln2_g"], p[f"l{l}_ln2_b"])
        cls = tf.gather(x, 0, axis=1)
        pooled = tf.tanh(tf.matmul(cls, p["pool_w"]) + p["pool_b"])
        return tf.nn.softmax(tf.matmul(pooled, p["cls_w"]) + p["cls_b"])

    cf = bert.get_concrete_function(
        tf.TensorSpec([None, T], tf.int32, name="ids"),
        tf.TensorSpec([None, T], tf.int32, name="mask"))
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    out_node = gd.node[-1].name
    meta = {"inputs": ["ids", "mask"], "output": out_node,
            "seq_len": T, "vocab": V, "n_classes": n_classes, **cfg}
    return gd.SerializeToString(), meta


def reference_outputs(graph_bytes: bytes, feeds: Dict[str, np.ndarray],
                      out_node: str) -> np.ndarray:
    """Run the frozen graph with real TF (the oracle)."""
    import tensorflow as tf
    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(graph_bytes)

    def run(ids, mask):
        out, = tf.graph_util.import_graph_def(
            gd, input_map={"ids": ids, "mask": mask},
            return_elements=[f"{out_node}:0"])
        return out

    fn = tf.compat.v1.wrap_function(
        run, [tf.TensorSpec(feeds["ids"].shape, tf.int32),
              tf.TensorSpec(feeds["mask"].shape, tf.int32)])
    return fn(tf.constant(feeds["ids"]),
              tf.constant(feeds["mask"])).numpy()
