"""Framework interop (ref: `nd4j/nd4j-tensorflow` — `GraphRunner.java`
runs real TF graphs in-process via libtensorflow).

`GraphRunner` executes a frozen TF GraphDef with the installed
TensorFlow runtime — the escape hatch for graphs whose ops exceed the
native importer's coverage (`modelimport.TFGraphMapper`), and the
cross-check oracle the importer is tested against.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class GraphRunner:
    """Ref: `tensorflow/conversion/graphrunner/GraphRunner.java` — load a
    GraphDef once, run it many times. TF import is deferred so the
    framework has no hard TF dependency."""

    def __init__(self, source, input_names: Sequence[str],
                 output_names: Sequence[str]):
        import tensorflow as tf  # deferred heavy import
        self._tf = tf
        if isinstance(source, (bytes, bytearray)):
            data = bytes(source)
        else:
            with open(source, "rb") as f:
                data = f.read()
        graph_def = tf.compat.v1.GraphDef()
        graph_def.ParseFromString(data)
        self.input_names = list(input_names)
        self.output_names = list(output_names)
        self._graph = tf.Graph()
        with self._graph.as_default():
            tf.import_graph_def(graph_def, name="")
        self._sess = tf.compat.v1.Session(graph=self._graph)

    def run(self, inputs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        feed = {f"{k}:0": np.asarray(v) for k, v in inputs.items()}
        fetches = [f"{n}:0" for n in self.output_names]
        outs = self._sess.run(fetches, feed_dict=feed)
        return dict(zip(self.output_names, outs))

    def close(self):
        self._sess.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
