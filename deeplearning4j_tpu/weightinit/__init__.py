"""Weight initialization schemes.

Ref: deeplearning4j-nn `org/deeplearning4j/nn/weights/WeightInit.java` enum +
`WeightInitUtil.java` (fanIn/fanOut based scaling), and nd4j `weightinit/impl/`.

TPU-first: all draws go through jax.random with explicit keys (counter-based
PRNG), so initialization is deterministic and reproducible across meshes —
unlike the reference's stateful NativeRandom.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def init_weights(key: jax.Array, shape: Sequence[int], fan_in: float, fan_out: float,
                 scheme: str = "xavier", dtype=jnp.float32,
                 distribution: Optional[dict] = None) -> jnp.ndarray:
    """Create a weight array per the named scheme.

    Scheme names match the reference WeightInit enum (lowercased).
    `distribution` is used by the DISTRIBUTION scheme:
    {"type": "normal"|"uniform"|"truncated_normal"|"constant", ...params}.
    """
    shape = tuple(int(s) for s in shape)
    s = scheme.lower()
    if s == "zero":
        return jnp.zeros(shape, dtype)
    if s == "ones":
        return jnp.ones(shape, dtype)
    if s == "constant":
        return jnp.full(shape, (distribution or {}).get("value", 0.0), dtype)
    if s == "normal" or s == "lecun_normal":
        # ref: N(0, 1/sqrt(fanIn))
        return jax.random.normal(key, shape, dtype) / math.sqrt(max(fan_in, 1.0))
    if s == "uniform":
        a = 1.0 / math.sqrt(max(fan_in, 1.0))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "xavier":
        # ref WeightInitUtil: N(0, 2/(fanIn+fanOut))
        std = math.sqrt(2.0 / max(fan_in + fan_out, 1.0))
        return std * jax.random.normal(key, shape, dtype)
    if s == "xavier_uniform":
        a = math.sqrt(6.0 / max(fan_in + fan_out, 1.0))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "xavier_fan_in":
        std = math.sqrt(1.0 / max(fan_in, 1.0))
        return std * jax.random.normal(key, shape, dtype)
    if s == "xavier_legacy":
        std = math.sqrt(1.0 / max(fan_in + fan_out, 1.0))
        return std * jax.random.normal(key, shape, dtype)
    if s == "relu":
        # He init: N(0, 2/fanIn)
        std = math.sqrt(2.0 / max(fan_in, 1.0))
        return std * jax.random.normal(key, shape, dtype)
    if s == "relu_uniform":
        a = math.sqrt(6.0 / max(fan_in, 1.0))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "sigmoid_uniform":
        a = 4.0 * math.sqrt(6.0 / max(fan_in + fan_out, 1.0))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "lecun_uniform":
        a = math.sqrt(3.0 / max(fan_in, 1.0))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s in ("var_scaling_normal_fan_in", "var_scaling_normal_fan_out",
             "var_scaling_normal_fan_avg", "var_scaling_uniform_fan_in",
             "var_scaling_uniform_fan_out", "var_scaling_uniform_fan_avg"):
        fan = {"in": fan_in, "out": fan_out, "avg": (fan_in + fan_out) / 2.0}[s.rsplit("_", 1)[-1]]
        if "normal" in s:
            std = math.sqrt(1.0 / max(fan, 1.0))
            return std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
        a = math.sqrt(3.0 / max(fan, 1.0))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("identity init requires square 2d shape")
        return jnp.eye(shape[0], dtype=dtype)
    if s == "distribution":
        d = distribution or {}
        t = d.get("type", "normal")
        if t == "normal" or t == "gaussian":
            return d.get("mean", 0.0) + d.get("std", 1.0) * jax.random.normal(key, shape, dtype)
        if t == "uniform":
            return jax.random.uniform(key, shape, dtype, d.get("lower", -1.0), d.get("upper", 1.0))
        if t == "truncated_normal":
            return d.get("mean", 0.0) + d.get("std", 1.0) * jax.random.truncated_normal(
                key, -2.0, 2.0, shape, dtype)
        if t == "constant":
            return jnp.full(shape, d.get("value", 0.0), dtype)
        raise ValueError(f"Unknown distribution type {t!r}")
    raise ValueError(f"Unknown weight init scheme: {scheme!r}")


SCHEMES = [
    "zero", "ones", "constant", "normal", "lecun_normal", "uniform", "xavier",
    "xavier_uniform", "xavier_fan_in", "xavier_legacy", "relu", "relu_uniform",
    "sigmoid_uniform", "lecun_uniform", "identity", "distribution",
    "var_scaling_normal_fan_in", "var_scaling_normal_fan_out",
    "var_scaling_normal_fan_avg", "var_scaling_uniform_fan_in",
    "var_scaling_uniform_fan_out", "var_scaling_uniform_fan_avg",
]
