"""Reinforcement learning — the rl4j layer (ref: D16, ~7.8k LoC).

Ref: `rl4j/.../learning/sync/qlearning/discrete/QLearningDiscrete.java:115`
(trainStep: eps-greedy act, replay buffer, target net, TD update),
`learning/async/a3c/**` (async advantage actor-critic),
`policy/{EpsGreedy,Policy}.java`, `mdp/MDP.java`, and the gym
integration (`gym-java-client`).

TPU-first redesign notes:
- DQN's Q-network IS a framework MultiLayerNetwork (mse head); the TD
  step batches replay samples into one jitted update.
- The reference's ASYNC A3C exists to keep 2015-era CPUs busy with
  lock-free stale gradients; the TPU-shaped equivalent is the
  synchronous batched advantage actor-critic over vectorized
  environments (one jitted update per rollout — no staleness, full MXU
  batches). The class keeps the A3C name for capability parity and
  documents the redesign.
"""
from .mdp import MDP, CartPole, GridWorld
from .policy import BoltzmannPolicy, EpsGreedy, GreedyPolicy, play
from .qlearning import QLearningConfiguration, QLearningDiscrete
from .a3c import A3C, A3CConfiguration
from .gym import GymClient, GymClientError, GymEnv

__all__ = ["MDP", "CartPole", "GridWorld", "QLearningDiscrete",
           "QLearningConfiguration", "A3C", "A3CConfiguration",
           "EpsGreedy", "GreedyPolicy", "BoltzmannPolicy", "play",
           "GymClient", "GymClientError", "GymEnv"]
