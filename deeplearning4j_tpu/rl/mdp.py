"""MDP interface + built-in environments.

Ref: `rl4j-api/.../mdp/MDP.java` (reset/step/isDone/getActionSpace) and
the gym bindings; CartPole matches the classic control dynamics the
reference exercises through gym-java-client, implemented natively so no
gym dependency is needed.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np


class MDP:
    """Ref: MDP.java — the environment SPI."""

    obs_size: int
    n_actions: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        """Returns (observation, reward, done)."""
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def close(self):
        pass


class CartPole(MDP):
    """Classic cart-pole balancing (Barto-Sutton-Anderson dynamics, the
    same task the reference's gym examples target)."""

    obs_size = 4
    n_actions = 2

    def __init__(self, max_steps: int = 200, seed: int = 0):
        self.max_steps = max_steps
        self._rng = np.random.RandomState(seed)
        self._state: Optional[np.ndarray] = None
        self._steps = 0
        self._done = True
        # physics constants (classic control)
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.length = 0.5
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * math.pi / 360
        self.x_threshold = 2.4

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, 4)
        self._steps = 0
        self._done = False
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.force_mag if action == 1 else -self.force_mag
        costh, sinth = math.cos(theta), math.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot ** 2 * sinth) \
            / total_mass
        thetaacc = (self.gravity * sinth - costh * temp) / (
            self.length * (4.0 / 3.0
                           - self.masspole * costh ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costh / total_mass
        x += self.tau * x_dot
        x_dot += self.tau * xacc
        theta += self.tau * theta_dot
        theta_dot += self.tau * thetaacc
        self._state = np.asarray([x, x_dot, theta, theta_dot])
        self._steps += 1
        self._done = bool(
            abs(x) > self.x_threshold
            or abs(theta) > self.theta_threshold
            or self._steps >= self.max_steps)
        return self._state.astype(np.float32), 1.0, self._done

    def is_done(self) -> bool:
        return self._done


class GridWorld(MDP):
    """Deterministic NxN grid: start top-left, +1 at bottom-right,
    -0.01 per step (a fast-converging correctness env, the role of the
    reference's toy MDPs in `rl4j-core` tests)."""

    n_actions = 4  # up, down, left, right

    def __init__(self, size: int = 4, max_steps: int = 50):
        self.size = size
        self.obs_size = size * size
        self.max_steps = max_steps
        self._pos = (0, 0)
        self._steps = 0
        self._done = True

    def _obs(self) -> np.ndarray:
        o = np.zeros(self.size * self.size, np.float32)
        o[self._pos[0] * self.size + self._pos[1]] = 1.0
        return o

    def reset(self):
        self._pos = (0, 0)
        self._steps = 0
        self._done = False
        return self._obs()

    def step(self, action: int):
        r, c = self._pos
        if action == 0:
            r = max(0, r - 1)
        elif action == 1:
            r = min(self.size - 1, r + 1)
        elif action == 2:
            c = max(0, c - 1)
        else:
            c = min(self.size - 1, c + 1)
        self._pos = (r, c)
        self._steps += 1
        at_goal = self._pos == (self.size - 1, self.size - 1)
        self._done = at_goal or self._steps >= self.max_steps
        reward = 1.0 if at_goal else -0.01
        return self._obs(), reward, self._done

    def is_done(self):
        return self._done
