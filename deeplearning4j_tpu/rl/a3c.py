"""Advantage actor-critic (ref: `rl4j/.../learning/async/a3c/**` —
A3CDiscrete, ActorCriticSeparate/Combined, n-step advantage updates).

TPU-first redesign (see package docstring): the reference spreads async
workers across CPU threads pushing stale gradients at a shared model
(Mnih 2016's hardware workaround). Here N environments step in lockstep
on the host and every rollout trains in ONE jitted update — synchronous
batched A2C, which is the same estimator with batch parallelism moved
from threads into the MXU batch dimension. Policy + value heads share a
trunk; loss = policy gradient + c_v * value MSE - c_e * entropy, exactly
the reference's ActorCriticCombined objective.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List

import jax
import jax.numpy as jnp
import numpy as np

from .. import learning
from ..weightinit import init_weights
from .mdp import MDP


@dataclass
class A3CConfiguration:
    """Ref: A3CDiscrete.A3CConfiguration (gamma, nstep, updaterConfig,
    entropy/value coefficients)."""
    seed: int = 0
    gamma: float = 0.99
    n_step: int = 16
    n_envs: int = 8
    hidden: int = 64
    learning_rate: float = 7e-3
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    max_grad_norm: float = 0.5


class A3C:
    """Batched advantage actor-critic over `n_envs` copies of the MDP."""

    def __init__(self, mdp_factory: Callable[[int], MDP],
                 config: A3CConfiguration):
        self.conf = config
        self.envs = [mdp_factory(i) for i in range(config.n_envs)]
        self.obs_size = self.envs[0].obs_size
        self.n_actions = self.envs[0].n_actions
        key = jax.random.PRNGKey(config.seed)
        k1, k2, k3, self._key = jax.random.split(key, 4)
        H = config.hidden
        self.params = {
            "w1": init_weights(k1, (self.obs_size, H), self.obs_size, H,
                               "xavier"),
            "b1": jnp.zeros(H),
            "wp": init_weights(k2, (H, self.n_actions), H, self.n_actions,
                               "xavier") * 0.1,
            "bp": jnp.zeros(self.n_actions),
            "wv": init_weights(k3, (H, 1), H, 1, "xavier") * 0.1,
            "bv": jnp.zeros(1),
        }
        self.updater = learning.Adam(config.learning_rate)
        self.opt_state = self.updater.init_state(self.params)
        self._step_no = 0
        self._update = self._build_update()
        self.episode_rewards: List[float] = []
        self._running = np.zeros(config.n_envs)
        self._obs = np.stack([e.reset() for e in self.envs])

    # -- model ---------------------------------------------------------
    @staticmethod
    def _forward(params, obs):
        h = jnp.tanh(obs @ params["w1"] + params["b1"])
        logits = h @ params["wp"] + params["bp"]
        value = (h @ params["wv"] + params["bv"])[..., 0]
        return logits, value

    def _build_update(self):
        conf = self.conf
        updater = self.updater

        def loss_fn(params, obs, actions, returns):
            logits, value = A3C._forward(params, obs)
            logp = jax.nn.log_softmax(logits)
            p = jax.nn.softmax(logits)
            adv = returns - value
            pg = -(jnp.take_along_axis(
                logp, actions[:, None], 1)[:, 0]
                * jax.lax.stop_gradient(adv)).mean()
            v_loss = (adv ** 2).mean()
            entropy = -(p * logp).sum(-1).mean()
            return (pg + conf.value_coef * v_loss
                    - conf.entropy_coef * entropy)

        def update(params, opt_state, step_no, obs, actions, returns):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs,
                                                      actions, returns)
            gnorm = jnp.sqrt(sum(jnp.sum(g ** 2)
                                 for g in jax.tree_util.tree_leaves(grads)))
            scale = jnp.minimum(1.0, conf.max_grad_norm / (gnorm + 1e-8))
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            opt_state, updates = updater.apply(opt_state, grads, step_no)
            params = jax.tree_util.tree_map(lambda p, u: p - u, params,
                                            updates)
            return params, opt_state, loss

        return jax.jit(update, donate_argnums=(0, 1))

    def _policy_probs(self, obs_batch: np.ndarray) -> np.ndarray:
        logits, _ = A3C._forward(self.params, jnp.asarray(obs_batch))
        return np.asarray(jax.nn.softmax(logits))

    # -- training ------------------------------------------------------
    def train(self, updates: int = 100) -> List[float]:
        """Run `updates` rollout+update cycles (each = n_step * n_envs
        environment transitions, one jitted gradient step)."""
        conf = self.conf
        rng = np.random.RandomState(conf.seed)
        for _ in range(updates):
            obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
            for t in range(conf.n_step):
                probs = self._policy_probs(self._obs)
                actions = np.asarray(
                    [rng.choice(self.n_actions, p=probs[i])
                     for i in range(conf.n_envs)])
                obs_buf.append(self._obs.copy())
                step_out = []
                for i, env in enumerate(self.envs):
                    o2, r, d = env.step(int(actions[i]))
                    self._running[i] += r
                    if d:
                        self.episode_rewards.append(self._running[i])
                        self._running[i] = 0.0
                        o2 = env.reset()
                    step_out.append((o2, r, d))
                self._obs = np.stack([s[0] for s in step_out])
                act_buf.append(actions)
                rew_buf.append([s[1] for s in step_out])
                done_buf.append([s[2] for s in step_out])
            # n-step bootstrapped returns (ref: async nstep accumulation)
            _, boot = A3C._forward(self.params, jnp.asarray(self._obs))
            returns = np.zeros((conf.n_step, conf.n_envs), np.float32)
            run = np.asarray(boot)
            rew = np.asarray(rew_buf, np.float32)
            done = np.asarray(done_buf, np.float32)
            for t in reversed(range(conf.n_step)):
                run = rew[t] + conf.gamma * run * (1.0 - done[t])
                returns[t] = run
            obs = np.concatenate(obs_buf).astype(np.float32)
            acts = np.concatenate(act_buf).astype(np.int32)
            rets = returns.reshape(-1)
            self.params, self.opt_state, _ = self._update(
                self.params, self.opt_state, self._step_no,
                jnp.asarray(obs), jnp.asarray(acts), jnp.asarray(rets))
            self._step_no += 1
        return self.episode_rewards

    def get_policy(self):
        from .policy import GreedyPolicy

        def q_like(obs):
            logits, _ = A3C._forward(self.params, jnp.asarray(obs[None]))
            return np.asarray(logits)[0]
        return GreedyPolicy(q_like)
