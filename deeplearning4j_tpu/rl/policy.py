"""Action policies (ref: `rl4j-core/.../policy/{Policy,EpsGreedy,
DQNPolicy,BoltzmannPolicy}.java`)."""
from __future__ import annotations

from typing import Callable

import numpy as np


class GreedyPolicy:
    """argmax-Q (ref: DQNPolicy)."""

    def __init__(self, q_fn: Callable[[np.ndarray], np.ndarray]):
        self.q_fn = q_fn

    def next_action(self, obs: np.ndarray) -> int:
        return int(np.argmax(self.q_fn(obs)))


class EpsGreedy(GreedyPolicy):
    """Annealed epsilon-greedy (ref: EpsGreedy.java — minEpsilon +
    epsilonNbStep annealing)."""

    def __init__(self, q_fn, eps_start: float = 1.0,
                 eps_min: float = 0.05, anneal_steps: int = 1000,
                 seed: int = 0):
        super().__init__(q_fn)
        self.eps_start = eps_start
        self.eps_min = eps_min
        self.anneal_steps = max(1, anneal_steps)
        self.step_count = 0
        self._rng = np.random.RandomState(seed)

    @property
    def epsilon(self) -> float:
        frac = min(1.0, self.step_count / self.anneal_steps)
        return self.eps_start + (self.eps_min - self.eps_start) * frac

    def next_action(self, obs: np.ndarray) -> int:
        q = self.q_fn(obs)
        self.step_count += 1
        if self._rng.rand() < self.epsilon:
            return int(self._rng.randint(len(q)))
        return int(np.argmax(q))


class BoltzmannPolicy(GreedyPolicy):
    """Softmax-over-Q sampling (ref: BoltzmannPolicy)."""

    def __init__(self, q_fn, temperature: float = 1.0, seed: int = 0):
        super().__init__(q_fn)
        self.temperature = temperature
        self._rng = np.random.RandomState(seed)

    def next_action(self, obs: np.ndarray) -> int:
        q = np.asarray(self.q_fn(obs), np.float64) / self.temperature
        p = np.exp(q - q.max())
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))


def play(mdp, policy, episodes: int = 1) -> float:
    """Run greedy episodes, return mean total reward (ref:
    Policy.play)."""
    total = 0.0
    for _ in range(episodes):
        obs = mdp.reset()
        done = False
        while not done:
            obs, r, done = mdp.step(policy.next_action(obs))
            total += r
    return total / episodes
