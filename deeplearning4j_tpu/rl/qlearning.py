"""Synchronous DQN (ref: `rl4j-core/.../learning/sync/qlearning/discrete/
QLearningDiscrete.java:115` trainStep — eps-greedy act, ExpReplay buffer,
target network with periodic hard sync, TD(0) targets, double-DQN
option; configuration mirror of `QLearning.QLConfiguration`).

The Q-network is a framework MultiLayerNetwork (mse head); each TD
update is ONE batched fit step — the replay minibatch trains in a single
jitted program.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import List, Optional

import jax
import numpy as np

from ..datasets import ArrayDataSetIterator
from .mdp import MDP
from .policy import EpsGreedy, GreedyPolicy


@dataclass
class QLearningConfiguration:
    """Ref: QLearning.QLConfiguration (seed, maxEpochStep, expRepMaxSize,
    batchSize, targetDqnUpdateFreq, gamma, epsilon schedule...)."""
    seed: int = 0
    gamma: float = 0.99
    batch_size: int = 32
    exp_replay_size: int = 10000
    target_update_freq: int = 100
    eps_start: float = 1.0
    eps_min: float = 0.05
    eps_anneal_steps: int = 1000
    warmup_steps: int = 64
    double_dqn: bool = False
    max_steps_per_episode: int = 10000


class QLearningDiscrete:
    """Ref: QLearningDiscrete.java. `net` is an (un)initialized
    MultiLayerNetwork whose output layer is an mse regression over
    n_actions."""

    def __init__(self, mdp: MDP, net, config: QLearningConfiguration):
        from ..nn.multilayer import MultiLayerNetwork
        self.mdp = mdp
        self.conf = config
        self.net = net
        if self.net._params is None:
            self.net.init()
        # target network: same conf, hard-synced copies of the params
        self.target = MultiLayerNetwork(net.conf).init()
        self._sync_target()
        self.replay = deque(maxlen=config.exp_replay_size)
        self.policy = EpsGreedy(self._q, config.eps_start, config.eps_min,
                                config.eps_anneal_steps, config.seed)
        self._rng = np.random.RandomState(config.seed)
        self.total_steps = 0
        self.episode_rewards: List[float] = []

    def _q(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self.net.output(obs[None]))[0]

    def _sync_target(self):
        # deep copy: fit() donates the online params' buffers to XLA, so
        # aliasing them here would leave the target net holding freed
        # buffers
        self.target._params = jax.tree_util.tree_map(
            jax.numpy.copy, self.net._params)
        self.target._net_state = jax.tree_util.tree_map(
            jax.numpy.copy, self.net._net_state)

    def _train_batch(self):
        idx = self._rng.choice(len(self.replay), self.conf.batch_size,
                               replace=False)
        batch = [self.replay[i] for i in idx]
        s = np.stack([b[0] for b in batch])
        a = np.asarray([b[1] for b in batch])
        r = np.asarray([b[2] for b in batch], np.float32)
        s2 = np.stack([b[3] for b in batch])
        done = np.asarray([b[4] for b in batch], np.float32)
        q = np.asarray(self.net.output(s))
        q_next_t = np.asarray(self.target.output(s2))
        if self.conf.double_dqn:
            # online net picks the action, target net evaluates it
            a_star = np.argmax(np.asarray(self.net.output(s2)), axis=1)
            boot = q_next_t[np.arange(len(a_star)), a_star]
        else:
            boot = q_next_t.max(axis=1)
        targets = q.copy()
        targets[np.arange(len(a)), a] = r + self.conf.gamma * boot \
            * (1.0 - done)
        self.net.fit(ArrayDataSetIterator(s, targets,
                                          batch=self.conf.batch_size),
                     epochs=1)

    def train_step(self, obs: np.ndarray):
        """One environment interaction + one TD update (ref: trainStep
        :115)."""
        action = self.policy.next_action(obs)
        obs2, reward, done = self.mdp.step(action)
        self.replay.append((obs, action, reward, obs2, float(done)))
        self.total_steps += 1
        if len(self.replay) >= max(self.conf.warmup_steps,
                                   self.conf.batch_size):
            self._train_batch()
        if self.total_steps % self.conf.target_update_freq == 0:
            self._sync_target()
        return obs2, reward, done

    def train(self, episodes: int = 50) -> List[float]:
        for _ in range(episodes):
            obs = self.mdp.reset()
            total, done, steps = 0.0, False, 0
            while not done and steps < self.conf.max_steps_per_episode:
                obs, r, done = self.train_step(obs)
                total += r
                steps += 1
            self.episode_rewards.append(total)
        return self.episode_rewards

    def get_policy(self) -> GreedyPolicy:
        return GreedyPolicy(self._q)
