"""Gym HTTP client (ref: `gym-java-client/` — a ~1k-LoC REST client for
the OpenAI gym-http-api server: `ClientFactory.java`, `Client.java`
with envCreate/envReset/envStep/envClose, `GymObservationSpace.java`,
and `rl4j-gym`'s `GymEnv` adapter onto the MDP SPI).

Same protocol, Python-native: the client speaks the gym-http-api JSON
REST surface (POST /v1/envs/, POST /v1/envs/{id}/reset/,
POST /v1/envs/{id}/step/, GET action/observation space, DELETE close)
over stdlib http.client, and :class:`GymEnv` adapts a remote env onto
this framework's :class:`~deeplearning4j_tpu.rl.mdp.MDP` interface so
every agent (DQN/A3C) can train against a remote gym server unchanged.

Testing follows the reference's DummyTransport philosophy: the suite
runs an in-process fake gym-http-api server (no egress, no gym
install) and drives the full client/env/agent path against it.
"""
from __future__ import annotations

import json
from http.client import HTTPConnection
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .mdp import MDP


class GymClientError(RuntimeError):
    pass


class GymClient:
    """REST client for a gym-http-api server (ref: `Client.java` —
    the v1 route constants and the envCreate/reset/step calls)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 5000,
                 timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    # -- wire ----------------------------------------------------------
    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        conn = HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            payload = None if body is None else json.dumps(body)
            headers = {"Content-Type": "application/json"}
            conn.request(method, path, payload, headers)
            resp = conn.getresponse()
            data = resp.read().decode("utf-8") or "{}"
            if resp.status >= 400:
                raise GymClientError(
                    f"{method} {path} -> HTTP {resp.status}: {data[:200]}")
            try:
                return json.loads(data)
            except json.JSONDecodeError as e:
                raise GymClientError(
                    f"{method} {path} -> malformed JSON body "
                    f"{data[:200]!r}") from e
        except (ConnectionError, OSError) as e:
            raise GymClientError(
                f"gym server unreachable at {self.host}:{self.port}: {e}"
            ) from e
        finally:
            conn.close()

    # -- gym-http-api surface (ref: Client.java route constants) -------
    def env_create(self, env_id: str) -> str:
        out = self._request("POST", "/v1/envs/", {"env_id": env_id})
        return out["instance_id"]

    def env_list(self) -> Dict[str, str]:
        return self._request("GET", "/v1/envs/").get("all_envs", {})

    def env_reset(self, instance_id: str) -> np.ndarray:
        out = self._request("POST", f"/v1/envs/{instance_id}/reset/")
        return np.asarray(out["observation"], np.float32)

    def env_step(self, instance_id: str, action: int,
                 render: bool = False) -> Tuple[np.ndarray, float, bool,
                                                Dict[str, Any]]:
        out = self._request(
            "POST", f"/v1/envs/{instance_id}/step/",
            {"action": int(action), "render": bool(render)})
        return (np.asarray(out["observation"], np.float32),
                float(out["reward"]), bool(out["done"]),
                out.get("info", {}))

    def env_action_space(self, instance_id: str) -> Dict[str, Any]:
        return self._request(
            "GET", f"/v1/envs/{instance_id}/action_space/")["info"]

    def env_observation_space(self, instance_id: str) -> Dict[str, Any]:
        return self._request(
            "GET", f"/v1/envs/{instance_id}/observation_space/")["info"]

    def env_close(self, instance_id: str) -> None:
        self._request("POST", f"/v1/envs/{instance_id}/close/")

    def env_monitor_start(self, instance_id: str, directory: str,
                          force: bool = False) -> None:
        self._request("POST", f"/v1/envs/{instance_id}/monitor/start/",
                      {"directory": directory, "force": force})

    def env_monitor_close(self, instance_id: str) -> None:
        self._request("POST", f"/v1/envs/{instance_id}/monitor/close/")


class GymEnv(MDP):
    """Remote gym environment as an MDP (ref: rl4j-gym `GymEnv.java` —
    wraps the client behind the MDP SPI so QLearning/A3C run on it
    unchanged)."""

    def __init__(self, env_id: str, client: Optional[GymClient] = None,
                 host: str = "127.0.0.1", port: int = 5000):
        self.client = client or GymClient(host, port)
        self.env_id = env_id
        self.instance_id = self.client.env_create(env_id)
        act = self.client.env_action_space(self.instance_id)
        obs = self.client.env_observation_space(self.instance_id)
        if act.get("name") != "Discrete":
            raise GymClientError(
                f"only Discrete action spaces supported, got {act}")
        self.n_actions = int(act["n"])
        shape = obs.get("shape") or [1]
        self.obs_size = int(np.prod(shape))
        self._done = True

    def reset(self) -> np.ndarray:
        self._done = False
        return self.client.env_reset(self.instance_id).reshape(-1)

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        obs, reward, done, _ = self.client.env_step(self.instance_id,
                                                    action)
        self._done = done
        return obs.reshape(-1), reward, done

    def is_done(self) -> bool:
        return self._done

    def close(self):
        try:
            self.client.env_close(self.instance_id)
        except GymClientError:
            pass
