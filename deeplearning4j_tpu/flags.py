"""Central flag / environment-variable registry.

Ref: `nd4j/nd4j-common/src/main/java/org/nd4j/config/ND4JSystemProperties.java`
(115 lines) and `ND4JEnvironmentVars.java` (122 lines) — the reference
declares every tunable system property / env var in one place with
javadoc, instead of scattering `System.getenv` calls. Same discipline
here: every environment variable this framework reads is declared below
with a type, default, and description. Modules import :data:`flags`
(the singleton) instead of touching ``os.environ`` directly.

TPU note: JAX/XLA's own flags (``XLA_FLAGS``, ``JAX_PLATFORMS``…) are
owned by JAX; they are *documented* here when the framework's tests or
tools set them, but reads go through JAX itself.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


def _as_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


@dataclass(frozen=True)
class Flag:
    """One declared environment variable (ref: the per-constant javadoc
    blocks in ND4JSystemProperties)."""
    name: str
    default: Any
    parse: Callable[[str], Any]
    doc: str

    def get(self) -> Any:
        raw = os.environ.get(self.name)
        if raw is None or raw == "":
            return self.default
        try:
            return self.parse(raw)
        except (ValueError, TypeError):
            return self.default


class FlagRegistry:
    """The registry. Attribute access returns the *current* parsed value
    (env re-read each time, like the reference's System.getProperty use),
    so tests can monkeypatch os.environ."""

    def __init__(self):
        self._flags: Dict[str, Flag] = {}

    def declare(self, attr: str, name: str, default: Any,
                parse: Callable[[str], Any], doc: str) -> None:
        self._flags[attr] = Flag(name, default, parse, doc)

    def __getattr__(self, attr: str) -> Any:
        flags = object.__getattribute__(self, "_flags")
        if attr in flags:
            return flags[attr].get()
        raise AttributeError(attr)

    def env_name(self, attr: str) -> str:
        return self._flags[attr].name

    def describe(self) -> str:
        """Human-readable table of every declared flag (ref: the javadoc
        surface of ND4JSystemProperties)."""
        lines = []
        for attr, f in sorted(self._flags.items()):
            cur = f.get()
            lines.append(f"{f.name} (flags.{attr})")
            lines.append(f"    default={f.default!r} current={cur!r}")
            lines.append(f"    {f.doc}")
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, Any]:
        return {attr: f.get() for attr, f in self._flags.items()}


flags = FlagRegistry()

# -- data locations (ref: ND4JSystemProperties.ND4J_RESOURCES_CACHE_DIR) --
flags.declare(
    "data_dir", "DL4J_TPU_DATA_DIR", os.path.expanduser("~/.deeplearning4j_tpu"),
    str, "Root directory for downloaded/cached datasets and fixtures.")
flags.declare(
    "mnist_dir", "MNIST_DATA_DIR", "", str,
    "Directory holding the 4 MNIST idx files (raw or .gz). Empty = probe "
    "standard locations, then fall back to the labeled synthetic set.")
flags.declare(
    "cifar10_dir", "CIFAR10_DATA_DIR", "", str,
    "Directory holding CIFAR-10 binary batches. Empty = probe standard "
    "locations, then fall back to the labeled synthetic set.")

# -- dtype / precision (ref: ND4JSystemProperties.DTYPE) ------------------
flags.declare(
    "dtype", "DL4J_TPU_DTYPE", "float32", str,
    "Default network dtype for newly built configurations: float32 | "
    "bfloat16. bfloat16 = mixed precision (bf16 compute on the MXU, "
    "f32 master params/updater state/loss).")

# -- kernels --------------------------------------------------------------
flags.declare(
    "flash_attention", "DL4J_TPU_FLASH_ATTENTION", True, _as_bool,
    "Allow the Pallas flash-attention kernel where it wins (TPU, long "
    "sequences). false = always use plain fused XLA attention.")
flags.declare(
    "flash_min_seq", "DL4J_TPU_FLASH_MIN_SEQ", 1024, int,
    "Minimum sequence length at which implementation='auto' selects the "
    "Pallas flash kernel on TPU (tuned from measured crossover, see "
    "BENCH extras attention_flash_vs_xla).")

# -- profiler / debugging (ref: OpExecutioner.ProfilingMode) --------------
flags.declare(
    "profiling_mode", "DL4J_TPU_PROFILING_MODE", "", str,
    "Global default profiling mode: '' | nan_panic | inf_panic | "
    "any_panic | operations. Mirrors profiler.ProfilerConfig modes.")
flags.declare(
    "verbose", "DL4J_TPU_VERBOSE", False, _as_bool,
    "Verbose runtime logging (ref: libnd4j Environment verbose flag).")

# -- native runtime -------------------------------------------------------
flags.declare(
    "native_lib", "DL4J_TPU_NATIVE_LIB", "", str,
    "Path to the prebuilt native runtime shared object. Empty = build "
    "on demand from native/ (falls back to pure numpy on failure).")
flags.declare(
    "native_disable", "DL4J_TPU_NATIVE_DISABLE", False, _as_bool,
    "Force the pure-numpy fallback even if the native runtime builds.")

# -- UI / serving ---------------------------------------------------------
flags.declare(
    "ui_port", "DL4J_TPU_UI_PORT", 9000, int,
    "Default port for the training UI stats server (ref: PlayUIServer "
    "org.deeplearning4j.ui.port).")

# -- benchmarking ---------------------------------------------------------
flags.declare(
    "bench_iters", "DL4J_TPU_BENCH_ITERS", 0, int,
    "Override the timed iteration count in bench.py (0 = per-model "
    "default). Used to shorten smoke runs.")
flags.declare(
    "bench_skip_secondary", "DL4J_TPU_BENCH_SKIP_SECONDARY", False, _as_bool,
    "Skip the secondary bench models (b128 / BERT / attention sweep / "
    "word2vec) and report only the headline.")
