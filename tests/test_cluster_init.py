"""Two-process `initialize_cluster` loopback test (VERDICT r4 #8 —
ref: docs/deeplearning4j-scaleout/templates/technicalref.md:115-135
cluster handshake semantics; here the PJRT distributed runtime over
localhost). Coordinator + one worker on CPU; both must see the GLOBAL
device set — the framework's one multi-host entry point actually
executes."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _mp_util import run_two_process

WORKER = """
import sys
sys.path.insert(0, {root!r})
from deeplearning4j_tpu.parallel.elastic import initialize_cluster
ok = initialize_cluster(coordinator_address={addr!r}, num_processes=2,
                        process_id={pid})
import jax
print("RESULT", {pid}, ok, jax.process_count(), jax.local_device_count(),
      jax.device_count(), flush=True)
"""


def test_two_process_cluster_sees_global_devices():
    raw = run_two_process(WORKER, timeout=240)
    results = {pid: (v[0], int(v[1]), int(v[2]), int(v[3]))
               for pid, v in raw.items()}
    for pid, (ok, nproc, local, glob) in results.items():
        assert ok == "True"
        assert nproc == 2, results
        # the global view = union of both processes' local devices
        assert glob == 2 * local, results


def test_single_process_is_noop():
    from deeplearning4j_tpu.parallel.elastic import initialize_cluster
    assert initialize_cluster(num_processes=1) is False
