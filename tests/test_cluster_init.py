"""Two-process `initialize_cluster` loopback test (VERDICT r4 #8 —
ref: docs/deeplearning4j-scaleout/templates/technicalref.md:115-135
cluster handshake semantics; here the PJRT distributed runtime over
localhost). Coordinator + one worker on CPU; both must see the GLOBAL
device set — the framework's one multi-host entry point actually
executes."""
import os
import socket
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import sys
sys.path.insert(0, {root!r})
from deeplearning4j_tpu.parallel.elastic import initialize_cluster
ok = initialize_cluster(coordinator_address={addr!r}, num_processes=2,
                        process_id={pid})
import jax
print("RESULT", {pid}, ok, jax.process_count(), jax.local_device_count(),
      jax.device_count(), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cluster_sees_global_devices():
    addr = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env.pop("XLA_FLAGS", None)  # 1 CPU device per process
    procs = [subprocess.Popen(
        [sys.executable, "-c",
         WORKER.format(root=ROOT, addr=addr, pid=pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, (out, err[-2000:])
    results = {}
    for rc, out, err in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, ok, nproc, local, glob = line.split()
                results[int(pid)] = (ok, int(nproc), int(local),
                                     int(glob))
    assert set(results) == {0, 1}, results
    for pid, (ok, nproc, local, glob) in results.items():
        assert ok == "True"
        assert nproc == 2, results
        # the global view = union of both processes' local devices
        assert glob == 2 * local, results


def test_single_process_is_noop():
    from deeplearning4j_tpu.parallel.elastic import initialize_cluster
    assert initialize_cluster(num_processes=1) is False
