"""Continuous-batching generation runtime tests: decode-attention
kernels, cached-decode layer parity, slot KV cache, the iteration-level
scheduler (mixed-length concurrency, slot reuse, EOS/max_tokens
retirement, zero post-warmup recompiles), sampling reproducibility,
HTTP generate endpoint (JSON + chunked streaming), and error-path
metrics (503 shed / 504 deadline) for both the generation queue and the
micro-batcher."""
import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.kernels.decode_attention import (
    decode_attention_pallas, decode_attention_xla)
from deeplearning4j_tpu.nn.layers.attention import (SelfAttentionLayer,
                                                    TransformerEncoderLayer)
from deeplearning4j_tpu.serving import (ClientError, DeadlineExceededError,
                                        GenerationEngine, InferenceServer,
                                        KVCache, QueueFullError, SlotTable)
from deeplearning4j_tpu.zoo.transformer_lm import CausalTransformerLM


def _lm(vocab=64, d_model=32, n_layers=2, n_heads=4, max_seq_len=32,
        seed=0):
    return CausalTransformerLM(vocab_size=vocab, d_model=d_model,
                               n_layers=n_layers, n_heads=n_heads,
                               max_seq_len=max_seq_len, seed=seed,
                               implementation="plain").init()


def _ref_greedy(lm, prompt, n):
    """Uncached full-prefix greedy decode — the correctness oracle the
    cached slot path must reproduce exactly."""
    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = np.asarray(lm.logits(np.asarray(toks)[None]))[0, -1]
        t = int(logits.argmax())
        out.append(t)
        toks.append(t)
    return out


@pytest.fixture(scope="module")
def lm():
    return _lm()


@pytest.fixture(scope="module")
def engine(lm):
    eng = GenerationEngine(lm, num_slots=4, max_queue=64,
                           min_prompt_bucket=4)
    eng.warmup()
    yield eng
    eng.stop()


class TestDecodeAttentionKernel:
    def test_pallas_matches_xla(self):
        rng = jax.random.PRNGKey(0)
        ks = jax.random.split(rng, 3)
        S, T, H, D = 3, 16, 4, 8
        q = jax.random.normal(ks[0], (S, H, D))
        k = jax.random.normal(ks[1], (S, H, T, D))
        v = jax.random.normal(ks[2], (S, H, T, D))
        lens = jnp.array([1, 7, 16], jnp.int32)
        a = np.asarray(decode_attention_xla(q, k, v, lens))
        b = np.asarray(decode_attention_pallas(q, k, v, lens,
                                               interpret=True))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_empty_slot_is_zero_not_nan(self):
        """A freed slot rides the decode batch with length 0 — its lane
        must stay finite (and zero), never poison the step."""
        S, T, H, D = 2, 8, 2, 4
        q = jnp.ones((S, H, D))
        k = jnp.ones((S, H, T, D))
        v = jnp.ones((S, H, T, D))
        lens = jnp.array([0, 8], jnp.int32)
        for impl in (decode_attention_xla,
                     lambda *a: decode_attention_pallas(*a,
                                                        interpret=True)):
            out = np.asarray(impl(q, k, v, lens))
            assert np.isfinite(out).all()
            assert np.abs(out[0]).max() == 0.0

    def test_masked_tail_ignored(self):
        """Keys past the live length must not influence the output —
        even NON-FINITE ones (a quarantined poison request can leave
        NaN K/V in the slot it vacates; 0 * NaN = NaN would otherwise
        leak through the masked probabilities into the sum)."""
        S, T, H, D = 1, 8, 2, 4
        rng = jax.random.PRNGKey(1)
        ks = jax.random.split(rng, 3)
        q = jax.random.normal(ks[0], (S, H, D))
        k = jax.random.normal(ks[1], (S, H, T, D))
        v = jax.random.normal(ks[2], (S, H, T, D))
        lens = jnp.array([5], jnp.int32)
        for tail in (99.0, jnp.nan):
            for impl in (decode_attention_xla,
                         lambda *a: decode_attention_pallas(
                             *a, interpret=True)):
                base = np.asarray(impl(q, k, v, lens))
                k2 = k.at[:, :, 5:].set(tail)
                v2 = v.at[:, :, 5:].set(-tail)
                poisoned = np.asarray(impl(q, k2, v2, lens))
                np.testing.assert_allclose(base, poisoned, rtol=1e-6)


class TestCachedDecodeLayers:
    def test_block_prefill_and_decode_match_full_forward(self):
        B, T, C, Tmax = 2, 6, 16, 8
        lay = TransformerEncoderLayer(n_heads=4, causal=True,
                                      implementation="plain")
        lay.build((T, C))
        p = lay.init_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (B, T, C))
        y_full, _, _ = lay.apply_seq(p, x, None, False, None, (), None)
        H, Dh = 4, 4
        kc = jnp.zeros((B, H, Tmax, Dh))
        vc = jnp.zeros((B, H, Tmax, Dh))
        y_pre, k, v = lay.apply_prefill(p, x[:, :4])
        np.testing.assert_allclose(np.asarray(y_pre),
                                   np.asarray(y_full[:, :4]), atol=1e-5)
        kc = kc.at[:, :, :4].set(k)
        vc = vc.at[:, :, :4].set(v)
        for t in range(4, T):
            o, kc, vc = lay.apply_decode(
                p, x[:, t], kc, vc, jnp.full((B,), t, jnp.int32))
            np.testing.assert_allclose(np.asarray(o),
                                       np.asarray(y_full[:, t]),
                                       atol=1e-5)

    def test_acausal_prefill_rejected(self):
        lay = SelfAttentionLayer(n_heads=2, causal=False)
        lay.build((4, 8))
        p = lay.init_params(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="causal"):
            lay.apply_prefill(p, jnp.zeros((1, 4, 8)))

    def test_cache_shape(self):
        lay = SelfAttentionLayer(n_heads=2, n_out=8, causal=True)
        lay.build((4, 8))
        assert lay.cache_shape(16) == (2, 16, 4)


class TestKVCacheSlots:
    def test_alloc_free_cycle(self):
        st = SlotTable(3)
        slots = [st.alloc(object()) for _ in range(3)]
        assert sorted(slots) == [0, 1, 2]
        assert st.alloc(object()) is None        # full
        st.free(slots[1])
        assert st.free_count == 1
        assert st.alloc(object()) == slots[1]    # reused
        st.free(0)
        with pytest.raises(ValueError):
            st.free(0)                           # double-free guard

    def test_cache_bytes(self):
        cache = KVCache([(2, 8, 4), (2, 8, 4)], num_slots=4)
        # 2 layers * K+V * 4 slots * 2*8*4 f32
        assert cache.nbytes() == 2 * 2 * 4 * 2 * 8 * 4 * 4


class TestGenerationEngine:
    def test_greedy_matches_uncached_reference(self, lm, engine):
        r = engine.generate([1, 2, 3], max_tokens=6)
        assert r["tokens"] == _ref_greedy(lm, [1, 2, 3], 6)
        assert r["finish_reason"] == "length"
        assert r["prompt_tokens"] == 3

    def test_concurrent_mixed_lengths_all_exact(self, lm, engine):
        """More requests than slots, different prompt lengths and
        generation lengths — every result must still match the
        sequential oracle (continuous batching must not leak state
        across slots or steps)."""
        cases = [(list(range(1, 2 + i)), 3 + i) for i in range(6)]
        results = {}

        def go(i, prompt, n):
            results[i] = engine.generate(prompt, max_tokens=n)

        threads = [threading.Thread(target=go, args=(i, p, n))
                   for i, (p, n) in enumerate(cases)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, (prompt, n) in enumerate(cases):
            assert results[i]["tokens"] == _ref_greedy(lm, prompt, n), \
                f"request {i} diverged"
        # all slots were exercised and freed
        assert engine._slots.free_count == engine.num_slots
        occ = engine.metrics.occupancy_hist.snapshot()
        assert any(int(k) > 1 for k in occ), \
            f"no step ever ran >1 slot: {occ}"

    def test_zero_recompiles_after_warmup(self, engine):
        before = engine.metrics.compiles
        threads = [threading.Thread(
            target=lambda i=i: engine.generate([1 + i, 2], max_tokens=4,
                                               temperature=0.5, seed=i))
            for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert engine.metrics.compiles == before

    def test_seeded_sampling_reproducible(self, engine):
        a = engine.generate([5, 6], max_tokens=8, temperature=0.9,
                            top_k=8, seed=42)
        b = engine.generate([5, 6], max_tokens=8, temperature=0.9,
                            top_k=8, seed=42)
        c = engine.generate([5, 6], max_tokens=8, temperature=0.9,
                            top_k=8, seed=7)
        assert a["tokens"] == b["tokens"]       # same seed, same tokens
        assert a["tokens"] != c["tokens"]       # different seed differs

    def test_eos_retires_immediately(self, engine):
        probe = engine.generate([5, 6], max_tokens=8, temperature=0.9,
                                top_k=8, seed=42)
        eos = probe["tokens"][2]
        r = engine.generate([5, 6], max_tokens=8, temperature=0.9,
                            top_k=8, seed=42, eos_id=eos)
        assert r["finish_reason"] == "eos"
        assert r["tokens"] == probe["tokens"][:3]
        assert engine._slots.free_count == engine.num_slots

    def test_max_tokens_clamped_to_cache_capacity(self, lm, engine):
        prompt = list(range(1, 30))                   # max_seq_len=32
        r = engine.generate(prompt, max_tokens=1000)
        assert len(r["tokens"]) == engine.max_seq_len - len(prompt)

    def test_client_errors(self, engine):
        with pytest.raises(ClientError):
            engine.generate([], max_tokens=4)         # empty prompt
        with pytest.raises(ClientError):
            engine.generate([1, 999999], max_tokens=4)  # out of vocab
        with pytest.raises(ClientError):
            engine.generate([[1, 2]], max_tokens=4)   # not 1-D
        with pytest.raises(ClientError):
            engine.generate(list(range(1, 33)))       # no room to gen
        with pytest.raises(ClientError):
            engine.generate([1], max_tokens=0)

    def test_streaming_matches_blocking(self, engine):
        kw = dict(max_tokens=5, temperature=0.7, top_k=4, seed=11)
        blocking = engine.generate([3, 4], **kw)
        chunks = list(engine.stream([3, 4], **kw))
        tokens = [c["token"] for c in chunks if "token" in c]
        assert tokens == blocking["tokens"]
        assert chunks[-1]["done"] is True
        assert chunks[-1]["finish_reason"] == blocking["finish_reason"]

    def test_extreme_top_k_is_normalized_not_poisonous(self, lm, engine):
        """top_k >= vocab (any magnitude, incl. > int32) is the
        documented 'no filter' spelling — it must sample normally, not
        overflow np.int32 in the scheduler and poison the batch."""
        r = engine.generate([4, 5], max_tokens=4, temperature=0.8,
                            top_k=2**31, seed=9)
        u = engine.generate([4, 5], max_tokens=4, temperature=0.8,
                            top_k=0, seed=9)
        assert r["tokens"] == u["tokens"]   # same as unfiltered
        r2 = engine.generate([4, 5], max_tokens=4, temperature=0.8,
                             top_k=-2**40, seed=9)
        assert r2["tokens"] == u["tokens"]
        with pytest.raises(ClientError, match="top-k cap"):
            # between the cap and vocab would silently mis-filter
            from deeplearning4j_tpu.serving.generation import TOP_K_CAP
            eng2 = GenerationEngine(
                _lm(vocab=TOP_K_CAP + 10), num_slots=1)
            try:
                eng2.generate([1], max_tokens=2, top_k=TOP_K_CAP + 1)
            finally:
                eng2.stop()

    def test_misconfiguration_rejected_at_construction(self, lm):
        with pytest.raises(ValueError, match="num_slots"):
            GenerationEngine(lm, num_slots=0)
        with pytest.raises(ValueError, match="prompt_buckets"):
            GenerationEngine(lm, num_slots=1, prompt_buckets=[4096])

    def test_registry_rejects_mode_flip(self, lm):
        """One name serves ONE mode: registering a generator over a
        predict name (or vice versa) must fail loudly, not silently
        flip the route for existing clients."""
        from deeplearning4j_tpu.serving import ModelRegistry

        class _Duck:
            def output(self, x):
                return x
        reg = ModelRegistry()
        reg.register("m", _Duck(), batching=False)
        with pytest.raises(ValueError, match="serving"):
            reg.register_generator("m", lm, num_slots=1)
        reg.register_generator("g", lm, num_slots=1)
        with pytest.raises(ValueError, match="serving"):
            reg.register("g", _Duck(), batching=False)
        reg.stop()

    def test_engine_max_seq_len_sizes_cache(self, lm):
        """An engine bound below the model's position table must
        allocate (and scan) a cache of ITS capacity, not the model's."""
        full = GenerationEngine(lm, num_slots=2)            # 32
        half = GenerationEngine(lm, num_slots=2, max_seq_len=16)
        assert half.metrics.cache_bytes * 2 == full.metrics.cache_bytes
        half.warmup()
        r = half.generate([1, 2], max_tokens=3)
        assert r["tokens"] == _ref_greedy(lm, [1, 2], 3)
        full.stop()
        half.stop()

    def test_never_started_stream_is_abandoned(self, lm):
        """Dropping a stream WITHOUT iterating (crashed caller, client
        gone before headers) must still release the request."""
        eng = GenerationEngine(lm, num_slots=1, max_queue=8,
                               min_prompt_bucket=4)
        eng.warmup([4])
        it = eng.stream([1, 2], max_tokens=25, temperature=0.5)
        it.close()          # consumer never called next()
        deadline = time.time() + 5.0
        while eng._slots.free_count == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert eng._slots.free_count == eng.num_slots
        r = eng.generate([1, 2, 3], max_tokens=3)
        assert r["tokens"] == _ref_greedy(lm, [1, 2, 3], 3)
        eng.stop()

    def test_dropped_stream_frees_its_slot(self, lm):
        """A consumer that abandons a streaming iterator mid-generate
        (client disconnect) must not pin its KV-cache slot until
        max_tokens — the scheduler frees it on the next step."""
        eng = GenerationEngine(lm, num_slots=1, max_queue=8,
                               min_prompt_bucket=4)
        eng.warmup([4])
        it = eng.stream([1, 2], max_tokens=25, temperature=0.5)
        next(it)            # take one token...
        it.close()          # ...then hang up
        deadline = time.time() + 5.0
        while eng._slots.free_count == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert eng._slots.free_count == eng.num_slots
        # the engine is still fully servable afterwards
        r = eng.generate([1, 2, 3], max_tokens=3)
        assert r["tokens"] == _ref_greedy(lm, [1, 2, 3], 3)
        assert eng.metrics.server_errors == 0
        eng.stop()

    def test_queue_expiry_is_504_and_counted(self, lm):
        eng = GenerationEngine(lm, num_slots=1, max_queue=8,
                               min_prompt_bucket=4)
        eng.warmup([4])
        before = eng.metrics.timeouts
        with pytest.raises(DeadlineExceededError):
            eng.generate([1, 2], max_tokens=4, timeout_ms=0)
        assert eng.metrics.timeouts > before
        eng.stop()

    def test_queue_full_is_503_and_counted(self, lm):
        eng = GenerationEngine(lm, num_slots=1, max_queue=1,
                               min_prompt_bucket=4)
        eng.warmup([4])
        results = []

        def client(i):
            try:
                results.append(
                    ("ok", eng.generate([1 + i % 8], max_tokens=24)))
            except QueueFullError:
                results.append(("shed", None))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert any(kind == "shed" for kind, _ in results)
        assert eng.metrics.shed >= 1
        eng.stop()

    def test_custom_prompt_buckets_route_without_compiles(self, lm):
        """A custom (gappy) bucket list must route prompts UP to the
        next configured bucket — never to an unwarmed pow2 size that
        would compile under traffic — and max_seq_len is always a
        bucket so every admissible prompt has a compiled home."""
        eng = GenerationEngine(lm, num_slots=2, prompt_buckets=[16])
        assert eng.prompt_buckets == [16, 32]   # max_seq_len appended
        eng.warmup()
        before = eng.metrics.compiles
        r = eng.generate([1, 2, 3], max_tokens=3)        # 3 -> 16
        assert r["tokens"] == _ref_greedy(lm, [1, 2, 3], 3)
        r = eng.generate(list(range(1, 21)), max_tokens=3)  # 20 -> 32
        assert r["tokens"] == _ref_greedy(lm, list(range(1, 21)), 3)
        assert eng.metrics.compiles == before
        assert set(eng.metrics.prompt_bucket_hist.snapshot()) == \
            {"16", "32"}
        eng.stop()

    def test_stats_surface(self, engine):
        engine.generate([1, 2], max_tokens=4)
        s = engine.stats()
        assert s["tokens_generated"] > 0
        assert s["tokens_per_sec"] >= 0
        assert s["ttft_ms"]["count"] > 0
        assert s["itl_ms"]["count"] > 0
        assert s["slots"]["num_slots"] == engine.num_slots
        assert s["slots"]["occupancy_hist"]
        assert s["prompt_bucket_hist"]
        assert s["kv_cache_bytes"] > 0
        assert set(s["compile_cache"]["warmed_buckets"]) == set(
            engine.prompt_buckets)


class TestGenerationHTTP:
    @pytest.fixture(scope="class")
    def server(self):
        srv = InferenceServer(port=0)
        g = srv.register_generator("lm", _lm(), num_slots=4)
        g.warmup()
        yield srv
        srv.stop()

    def _post(self, srv, path, payload, timeout=60):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req,
                                                 timeout=timeout).read())

    def test_generate_roundtrip(self, server):
        r = self._post(server, "/v1/models/lm/generate",
                       {"prompt": [1, 2, 3], "max_tokens": 5})
        assert len(r["tokens"]) == 5
        assert r["finish_reason"] in ("length", "eos")

    def test_streaming_chunked(self, server):
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=60)
        body = json.dumps({"prompt": [1, 2, 3], "max_tokens": 5,
                           "stream": True}).encode()
        conn.request("POST", "/v1/models/lm/generate", body=body)
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Transfer-Encoding") == "chunked"
        items = [json.loads(line) for line in
                 resp.read().decode().strip().splitlines()]
        conn.close()
        tokens = [c["token"] for c in items if "token" in c]
        assert len(tokens) == 5
        assert items[-1]["done"] is True
        # streamed tokens match the final result object
        assert items[-1]["tokens"] == tokens

    def test_keepalive_socket_survives_stream(self, server):
        """Chunked framing is self-delimiting: the same connection must
        serve a normal request after a streamed one."""
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=60)
        conn.request("POST", "/v1/models/lm/generate",
                     body=json.dumps({"prompt": [2], "max_tokens": 3,
                                      "stream": True}).encode())
        conn.getresponse().read()
        conn.request("POST", "/v1/models/lm/generate",
                     body=json.dumps({"prompt": [2],
                                      "max_tokens": 3}).encode())
        r2 = json.loads(conn.getresponse().read())
        conn.close()
        assert len(r2["tokens"]) == 3

    def test_error_codes(self, server):
        for payload, want in ((["list"], 400),
                              ({"prompt": []}, 400),
                              ({"no_prompt": 1}, 400),
                              ({"prompt": [1], "max_tokens": "x"}, 400)):
            with pytest.raises(urllib.error.HTTPError) as e:
                self._post(server, "/v1/models/lm/generate", payload)
            assert e.value.code == want
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(server, "/v1/models/ghost/generate",
                       {"prompt": [1]})
        assert e.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(server, "/v1/models/lm/predict",
                       {"inputs": [[1.0]]})
        assert e.value.code == 400   # generator can't predict

    def test_stats_exposes_generation_metrics(self, server):
        self._post(server, "/v1/models/lm/generate",
                   {"prompt": [4, 5], "max_tokens": 4})
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/stats", timeout=10).read())
        m = stats["models"]["lm"]
        assert m["serving_mode"] == "generation"
        assert m["tokens_generated"] > 0
        assert m["ttft_ms"]["count"] > 0
        assert m["slots"]["occupancy_hist"]
        assert "tokens_per_sec" in m

    def test_shed_and_timeout_counted_in_stats(self):
        """ISSUE satellite: 503/504 from the generation queue appear in
        GET /stats."""
        srv = InferenceServer(port=0)
        g = srv.register_generator("g", _lm(), num_slots=1, max_queue=1)
        g.warmup([8])
        base = f"http://127.0.0.1:{srv.port}"
        codes = []

        def client(i, timeout_ms):
            try:
                self._post(srv, "/v1/models/g/generate",
                           {"prompt": [1 + i], "max_tokens": 24,
                            "timeout_ms": timeout_ms})
                codes.append(200)
            except urllib.error.HTTPError as e:
                codes.append(e.code)

        threads = [threading.Thread(target=client, args=(i, 60_000))
                   for i in range(6)]
        threads.append(threading.Thread(target=client, args=(9, 0)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = json.loads(urllib.request.urlopen(
            base + "/stats", timeout=10).read())["models"]["g"]
        assert 503 in codes or 504 in codes
        assert stats["shed"] + stats["timeouts"] >= 1
        if 503 in codes:
            assert stats["shed"] >= 1
        if 504 in codes:
            assert stats["timeouts"] >= 1
        srv.stop()
