"""Elastic multi-worker training (ISSUE 7): coordinated preemption
over a fleet-wide channel, sharded format-v3 checkpoints with a
manifest-last commit, and elastic re-meshing resume (W -> W' with
re-bucketed gradient-sharing state).

Acceptance asserted here, all on the CPU backend:
- multi-worker kill-and-resume at UNCHANGED worker count is bit-exact
  vs the uninterrupted run (plain + both compressed wrapper modes),
  through sharded checkpoints;
- 8->4 and 4->8 re-meshed resume converges within the documented
  tolerance (docs/distributed.md: rel L2 param distance <= 0.05) of
  the fixed-shape trajectory, with zero post-warmup recompiles after
  the re-meshed step rebuild;
- torn sharded writes (faults between shard writes, and between the
  last shard and the manifest commit) are never listed or resumed.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import zipfile

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ArrayDataSetIterator
from deeplearning4j_tpu.faults import (FaultInjector, PreemptionFault,
                                       TransientFault)
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import (GradientSharingAccumulator,
                                         ParallelWrapper,
                                         rebucket_worker_array)
from deeplearning4j_tpu.parallel.elastic import (FaultTolerantTrainer,
                                                 PreemptionHandler)
from deeplearning4j_tpu.parallel.multihost import (PreemptionCoordinator,
                                                   split_data_cursor)
from deeplearning4j_tpu.util.serializer import (CheckpointFormatError,
                                                ModelSerializer)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the documented re-mesh tolerance (docs/distributed.md): relative L2
#: parameter distance of a re-meshed resume vs the fixed-shape run
REMESH_REL_TOL = 0.05


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax"))
            .input_type_feed_forward(4).build())
    return MultiLayerNetwork(conf).init()


def _arrays(n=64, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.rand(n, 4).astype(np.float32)
    return X, np.eye(2, dtype=np.float32)[rs.randint(0, 2, n)]


def _it(X, Y, batch=16):
    return ArrayDataSetIterator(X, Y, batch=batch, shuffle=True, seed=3)


def _leaves(m):
    return [np.array(a, copy=True)
            for a in jax.tree_util.tree_leaves(m._params)]


def _same(a, b):
    return all(np.array_equal(x, y) for x, y in zip(a, b))


def _flat(m):
    return np.concatenate([a.ravel() for a in _leaves(m)])


def _rel(a, b):
    return float(np.linalg.norm(a - b) / np.linalg.norm(a))


def _wrapped(model, workers, mode):
    acc = (None if mode == "plain"
           else GradientSharingAccumulator(mode=mode))
    return ParallelWrapper(model, workers=workers, accumulator=acc)


class TestPerWorkerInjectorSeams:
    def test_worker_plan_targets_one_worker_at_its_own_count(self):
        inj = FaultInjector(plan={"preempt": {1: [3]}})
        # worker 0 never fires, no matter how many calls
        for _ in range(6):
            assert inj.fire("preempt", worker=0) is False
        # worker 1 fires at ITS 3rd call — independent of worker 0's
        assert inj.fire("preempt", worker=1) is False
        assert inj.fire("preempt", worker=1) is False
        with pytest.raises(PreemptionFault):
            inj.fire("preempt", worker=1)
        snap = inj.snapshot()
        assert snap["fired"]["preempt"] == 1
        assert snap["by_worker"]["preempt"][1]["fired"] == 1
        assert snap["by_worker"]["preempt"][0]["fired"] == 0

    def test_flat_plan_applies_per_worker_independently(self):
        inj = FaultInjector(plan={"checkpoint_io": [2]})
        assert inj.fire("checkpoint_io", worker=0) is False
        assert inj.fire("checkpoint_io", worker=1) is False
        # each worker's OWN 2nd call fires
        with pytest.raises(TransientFault):
            inj.fire("checkpoint_io", worker=0)
        with pytest.raises(TransientFault):
            inj.fire("checkpoint_io", worker=1)

    def test_worker_streams_deterministic_and_independent(self):
        def pattern(order):
            inj = FaultInjector(seed=5, rates={"train_step": 0.5})
            out = {0: [], 1: []}
            for w in order:
                try:
                    inj.fire("train_step", worker=w)
                    out[w].append("ok")
                except TransientFault:
                    out[w].append("fault")
            return out
        a = pattern([0] * 8 + [1] * 8)
        # interleaving the workers' calls must not change either stream
        b = pattern([0, 1] * 8)
        assert a == b
        assert "fault" in a[0] + a[1]        # the rate actually fires

    def test_worker_scoped_unknown_seam_still_rejected(self):
        with pytest.raises(ValueError, match="unknown fault seams"):
            FaultInjector(plan={"nope": {0: [1]}})


class TestPreemptionCoordinator:
    def test_generation_monotonic_and_reset(self, tmp_path):
        c = PreemptionCoordinator()
        g0 = c.generation()
        t1 = c.signal(source=3)
        assert c.generation() == t1 > g0
        t2 = c.signal(source=4)
        assert t2 > t1 and c.last_source == 4
        c.reset()
        assert c.generation() == 0.0

    def test_file_channel_crosses_instances(self, tmp_path):
        a = PreemptionCoordinator(channel_dir=str(tmp_path))
        b = PreemptionCoordinator(channel_dir=str(tmp_path))
        gb0 = b.generation()
        a.signal(source="worker-a")
        assert b.generation() > gb0          # saw the sentinel
        assert b.last_source == "worker-a"
        assert os.path.isfile(tmp_path / PreemptionCoordinator.SENTINEL)
        b.reset()                            # clears the file too
        assert not os.path.isfile(tmp_path / PreemptionCoordinator.SENTINEL)

    def test_fresh_signaller_never_regresses_the_sentinel(self, tmp_path):
        """A FRESH coordinator (operator shell / restarted process,
        _gen=0) signalling into a channel whose sentinel carries a
        HIGHER token (clock-skewed writer) must absorb the file first —
        otherwise it would overwrite the sentinel with a lower token
        and the notice would be invisible to workers whose gen0 came
        from the file."""
        a = PreemptionCoordinator(channel_dir=str(tmp_path))
        a.signal(source="skewed")
        # simulate a far-future writer
        path = tmp_path / PreemptionCoordinator.SENTINEL
        data = json.loads(path.read_text())
        future = data["token"] + 3600.0
        path.write_text(json.dumps(dict(data, token=future)))
        fresh = PreemptionCoordinator(channel_dir=str(tmp_path))
        tok = fresh.signal(source="operator")
        assert tok > future
        b = PreemptionCoordinator(channel_dir=str(tmp_path))
        assert b.generation() == tok

    def test_stale_notice_ignored_by_new_fit(self, tmp_path):
        """A sentinel predating fit() must not preempt the restarted
        fleet — the trainer compares against the token captured at its
        own start."""
        coord = PreemptionCoordinator(channel_dir=str(tmp_path / "ch"))
        coord.signal(source="previous-life")
        X, Y = _arrays()
        m = _mlp()
        tr = FaultTolerantTrainer(m, str(tmp_path / "ck"),
                                  save_every_n_steps=100,
                                  coordinator=coord)
        tr.fit(_it(X, Y), epochs=1)          # completes, no preemption
        assert tr.supervisor.preemptions.value() == 0

    def test_split_data_cursor(self):
        cur = {"epoch": 2, "batches_into_epoch": 7,
               "iterator": {"epoch": 2}}
        parts = split_data_cursor(cur, 4)
        assert len(parts) == 4
        for i, p in enumerate(parts):
            # same GLOBAL position for every worker; coordinates ride
            # alongside so input pipelines can re-derive their slice
            assert p["epoch"] == 2 and p["batches_into_epoch"] == 7
            assert p["worker"] == i and p["num_workers"] == 4
        assert split_data_cursor(None, 3) == [None, None, None]
        with pytest.raises(ValueError):
            split_data_cursor(cur, 0)


class TestCoordinatedPreemption:
    @staticmethod
    def _fleet_injector():
        """Preempt exactly worker 1 at ITS 4th step; every worker's
        train_step sleeps a few ms (slow_ms fires return, not raise) so
        no thread can race through its whole schedule before the
        originator reaches step 4 and the broadcast lands."""
        return FaultInjector(plan={"preempt": {1: [4]}},
                             rates={"train_step": 1.0},
                             slow_ms={"train_step": 4.0})

    def _run_fleet(self, base, coord, injector, n_workers=3, epochs=4):
        """N plain trainers (threads) sharing one coordinator + one
        worker-scoped injector. A first-step barrier holds everyone
        until every worker has COMPILED and run one step — without it,
        a worker whose compile finished early could sprint through its
        whole schedule before the originator ever reaches its preempt
        step. Returns (models, trainers, outcomes)."""
        X, Y = _arrays(n=96)
        models = [_mlp() for _ in range(n_workers)]
        barrier = threading.Barrier(n_workers)

        class SyncFirstStep:
            def __init__(self):
                self.passed = False

            def iteration_done(self, m, step, epoch):
                if not self.passed:
                    self.passed = True
                    barrier.wait(timeout=90)
        for m in models:
            m.set_listeners(SyncFirstStep())
        trainers = [FaultTolerantTrainer(
            models[i], str(base / f"w{i}"), save_every_n_steps=100,
            fault_injector=injector, coordinator=coord, worker_id=i)
            for i in range(n_workers)]
        outcomes = [None] * n_workers

        def run(i):
            try:
                trainers[i].fit(_it(X, Y, batch=8), epochs=epochs)
                outcomes[i] = "done"
            except PreemptionFault:
                outcomes[i] = "preempted"
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        return models, trainers, outcomes

    def test_one_workers_preempt_drains_the_fleet(self, tmp_path):
        """The tentpole's coordination clause: an injected
        PreemptionFault on ONE worker makes EVERY worker flush a
        step-granular checkpoint at its next boundary and exit with
        PreemptionFault — nobody dies checkpoint-less."""
        coord = PreemptionCoordinator()
        models, trainers, outcomes = self._run_fleet(
            tmp_path, coord, self._fleet_injector())
        assert outcomes == ["preempted"] * 3, outcomes
        # the originator broadcast once; the others received the notice
        assert trainers[1].supervisor.preempts_broadcast.value() == 1
        for i in (0, 2):
            assert trainers[i].supervisor.preempts_received.value() == 1
            assert trainers[i].supervisor.preempts_broadcast.value() == 0
        # every worker has a STEP-granular checkpoint to restart from
        for i in range(3):
            names = [os.path.basename(p) for p in
                     FaultTolerantTrainer.list_checkpoints(
                         str(tmp_path / f"w{i}"))]
            assert names and "_step" in names[-1], (i, names)

    def test_fleet_resume_is_bit_exact_per_worker(self, tmp_path):
        """Kill-and-resume across the COORDINATED stop replays each
        worker's uninterrupted trajectory bit-exactly (the PR 5
        guarantee extended fleet-wide)."""
        X, Y = _arrays(n=96)
        refs = []
        for i in range(3):
            mr = _mlp()
            FaultTolerantTrainer(mr, str(tmp_path / f"ref{i}"),
                                 save_every_n_steps=100).fit(
                _it(X, Y, batch=8), epochs=4)
            refs.append(mr)
        coord = PreemptionCoordinator()
        _, _, outcomes = self._run_fleet(tmp_path, coord,
                                         self._fleet_injector())
        assert outcomes == ["preempted"] * 3
        for i in range(3):
            m = FaultTolerantTrainer.resume(str(tmp_path / f"w{i}"))
            FaultTolerantTrainer(m, str(tmp_path / f"w{i}"),
                                 save_every_n_steps=100).fit(
                _it(X, Y, batch=8), epochs=4)
            assert _same(_leaves(refs[i]), _leaves(m)), \
                f"worker {i} diverged after coordinated resume"

    def test_sigterm_broadcasts_through_handler_channel(self, tmp_path):
        """PreemptionHandler(coordinator=): a real SIGTERM on the
        main-thread worker drains a background worker too. The handler
        contract stays flag-only — the broadcast happens on the loop
        thread at the step boundary."""
        X, Y = _arrays(n=96)
        coord = PreemptionCoordinator(channel_dir=str(tmp_path / "ch"))
        # background worker: long schedule (slowed a few ms/step so it
        # cannot finish before the main worker's SIGTERM at step 3),
        # observes the channel
        m_bg = _mlp()
        tr_bg = FaultTolerantTrainer(
            m_bg, str(tmp_path / "bg"), save_every_n_steps=100,
            coordinator=coord, worker_id=1,
            fault_injector=FaultInjector(rates={"train_step": 1.0},
                                         slow_ms={"train_step": 4.0}))
        bg_out = []

        def run_bg():
            try:
                tr_bg.fit(_it(X, Y, batch=8), epochs=4)
                bg_out.append("done")
            except PreemptionFault:
                bg_out.append("preempted")
        bg = threading.Thread(target=run_bg)
        # main-thread worker: SIGTERM delivered from a listener at
        # step 3 (mid-loop — the frame an in-handler save could
        # deadlock in)
        m = _mlp()
        tr = FaultTolerantTrainer(m, str(tmp_path / "main"),
                                  save_every_n_steps=100, worker_id=0)
        sent = []

        class KillAtStep3:
            def iteration_done(self, mm, step, epoch):
                if step == 3 and not sent:
                    sent.append(True)
                    os.kill(os.getpid(), signal.SIGTERM)
        m.set_listeners(KillAtStep3())
        bg.start()
        try:
            with PreemptionHandler(tr, signals=(signal.SIGTERM,),
                                   reraise=False,
                                   coordinator=coord) as h:
                with pytest.raises(PreemptionFault):
                    tr.fit(_it(X, Y, batch=8), epochs=4)
        finally:
            bg.join(timeout=120)
        assert h.preempted
        assert tr.coordinator is coord       # handler installed it
        assert bg_out == ["preempted"]
        assert tr_bg.supervisor.preempts_received.value() == 1
        # both flushed step-granular checkpoints
        for d in ("main", "bg"):
            names = [os.path.basename(p) for p in
                     FaultTolerantTrainer.list_checkpoints(
                         str(tmp_path / d))]
            assert names and "_step" in names[-1], (d, names)


@pytest.mark.parametrize("mode", ["plain", "update", "gradient"])
class TestShardedCheckpointsBitExact:
    """Same-shape kill-and-resume through format-v3 sharded
    checkpoints stays BIT-EXACT — plain wrapper and both compressed
    modes (the acceptance's unchanged-worker-count clause)."""

    def test_kill_and_resume_bit_exact(self, tmp_path, mode):
        X, Y = _arrays(n=64)
        # uninterrupted reference (same sharded-checkpoint trainer)
        mA = _mlp()
        trA = FaultTolerantTrainer(
            mA, str(tmp_path / "a"), save_every_n_steps=3,
            wrapper=_wrapped(mA, 4, mode), sharded_checkpoints=True)
        trA.fit(_it(X, Y), epochs=3)
        assert trA.supervisor.sharded_checkpoints.value() >= 1
        # killed mid-epoch by a scripted preemption
        mB = _mlp()
        trB = FaultTolerantTrainer(
            mB, str(tmp_path / "b"), save_every_n_steps=3,
            wrapper=_wrapped(mB, 4, mode), sharded_checkpoints=True,
            fault_injector=FaultInjector(plan={"preempt": [7]}))
        with pytest.raises(PreemptionFault):
            trB.fit(_it(X, Y), epochs=3)
        # restart: v3 restore, fresh wrapper at the SAME worker count
        mC = FaultTolerantTrainer.resume(str(tmp_path / "b"))
        assert mC._step == 7
        pwC = _wrapped(mC, 4, mode)
        trC = FaultTolerantTrainer(mC, str(tmp_path / "b"),
                                   save_every_n_steps=3, wrapper=pwC,
                                   sharded_checkpoints=True)
        trC.fit(_it(X, Y), epochs=3)
        assert pwC.last_remesh is None       # same shape = no re-mesh
        assert mA._step == mC._step == 12
        assert _same(_leaves(mA), _leaves(mC)), \
            f"{mode}: sharded same-shape resume diverged"


class TestShardedCheckpointLayout:
    def _fit_sharded(self, d, steps=3, epochs=2, workers=4,
                     injector=None, **kw):
        X, Y = _arrays(n=64)
        m = _mlp()
        pw = ParallelWrapper(
            m, workers=workers,
            accumulator=GradientSharingAccumulator(mode="update"))
        tr = FaultTolerantTrainer(m, d, save_every_n_steps=steps,
                                  wrapper=pw, sharded_checkpoints=True,
                                  fault_injector=injector, **kw)
        return m, pw, tr, _it(X, Y)

    def test_directory_layout_and_manifest(self, tmp_path):
        m, pw, tr, it = self._fit_sharded(str(tmp_path))
        tr.fit(it, epochs=2)
        last = FaultTolerantTrainer.list_checkpoints(str(tmp_path))[-1]
        assert last.endswith(".ckpt") and os.path.isdir(last)
        files = sorted(os.listdir(last))
        assert files == ["manifest.json"] + [
            f"shard_{i:05d}.zip" for i in range(4)]
        with open(os.path.join(last, "manifest.json")) as f:
            man = json.load(f)
        assert man["format_version"] == 3
        assert man["num_workers"] == 4
        assert man["meta"]["step"] == m._step
        assert man["meta"]["cursor"]["epoch"] == 2
        # per-worker arrays are the worker-sliced set
        assert any(k.startswith("gradient_sharing/residuals/")
                   for k in man["worker_sliced"])
        assert any(k.startswith("gradient_sharing/opt_state/")
                   for k in man["worker_sliced"])
        assert "gradient_sharing/threshold" not in man["worker_sliced"]
        for entry in man["shards"]:
            p = os.path.join(last, entry["file"])
            assert os.path.getsize(p) == entry["bytes"]
            assert sum(entry["entries"].values()) > 0
        # model-wide entries are DISTRIBUTED, not mirrored: no shard
        # holds everything (the models-outgrow-host-RAM requirement)
        total_params = sum(e["entries"]["params"] for e in man["shards"])
        assert total_params == 4             # 2 layers x W,b
        assert max(e["entries"]["params"] for e in man["shards"]) < 4

    def test_mixed_v2_v3_listing_and_migration(self, tmp_path):
        """A directory holding BOTH formats lists chronologically, and
        a v2 checkpoint resumes into a sharded-checkpoint trainer —
        the v2->v3 migration path is just 'resume and keep going'."""
        X, Y = _arrays(n=64)
        m = _mlp()
        # epoch 1 written as a v2 zip
        FaultTolerantTrainer(m, str(tmp_path), save_every_n_steps=100,
                             wrapper=ParallelWrapper(m, workers=4)).fit(
            _it(X, Y), epochs=1)
        # resume, continue with SHARDED checkpoints to epoch 3
        m2 = FaultTolerantTrainer.resume(str(tmp_path))
        pw2 = ParallelWrapper(m2, workers=4)
        FaultTolerantTrainer(m2, str(tmp_path), save_every_n_steps=100,
                             wrapper=pw2, sharded_checkpoints=True).fit(
            _it(X, Y), epochs=3)
        names = [os.path.basename(p) for p in
                 FaultTolerantTrainer.list_checkpoints(str(tmp_path))]
        assert names == ["checkpoint_epoch1.zip",
                         "checkpoint_epoch2.ckpt",
                         "checkpoint_epoch3.ckpt"]
        assert FaultTolerantTrainer.resume(str(tmp_path))._epoch == 3

    def test_torn_between_shard_writes_never_listed(self, tmp_path):
        """checkpoint_io fault on shard 2's write with no retries: the
        'crash' lands between shard writes. list_checkpoints must not
        surface the partial; resume falls back to the previous good
        checkpoint."""
        inj = FaultInjector(plan={"checkpoint_io": {2: [2]}})
        m, pw, tr, it = self._fit_sharded(
            str(tmp_path), injector=inj, async_write=False,
            max_step_retries=0)
        with pytest.raises(TransientFault):
            tr.fit(it, epochs=2)
        good = FaultTolerantTrainer.list_checkpoints(str(tmp_path))
        # the first cadence checkpoint (step 3) succeeded — shard 2's
        # 2nd call is the SECOND checkpoint's write (step 6)
        assert [os.path.basename(p) for p in good] == \
            ["checkpoint_epoch0_step3.ckpt"]
        assert FaultTolerantTrainer.resume(str(tmp_path))._step == 3

    def test_torn_before_manifest_commit_never_listed(self, tmp_path):
        """Fault in the last-shard -> manifest window (the global
        checkpoint_io fire after all 4 worker-scoped shard fires):
        every shard is durable, the manifest is not — the checkpoint
        must still be invisible."""
        # per checkpoint attempt: 4 worker-scoped fires then 1 global;
        # the global counter counts them all, so call #10 is the
        # SECOND checkpoint's manifest fire
        inj = FaultInjector(plan={"checkpoint_io": [10]})
        m, pw, tr, it = self._fit_sharded(
            str(tmp_path), injector=inj, async_write=False,
            max_step_retries=0)
        with pytest.raises(TransientFault):
            tr.fit(it, epochs=2)
        good = [os.path.basename(p) for p in
                FaultTolerantTrainer.list_checkpoints(str(tmp_path))]
        assert good == ["checkpoint_epoch0_step3.ckpt"]
        assert FaultTolerantTrainer.resume(str(tmp_path))._step == 3

    def test_manifestless_directory_is_invisible_and_diagnosable(
            self, tmp_path):
        """A torn directory that somehow landed at the LIVE name (e.g.
        a partial rsync) is still rejected: the manifest is the commit
        marker, not the directory rename."""
        m, pw, tr, it = self._fit_sharded(str(tmp_path))
        tr.fit(it, epochs=1)
        good = FaultTolerantTrainer.list_checkpoints(str(tmp_path))[-1]
        torn = os.path.join(str(tmp_path), "checkpoint_epoch9.ckpt")
        os.makedirs(torn)
        with open(os.path.join(torn, "shard_00000.zip"), "wb") as f:
            f.write(b"partial")
        assert FaultTolerantTrainer.list_checkpoints(
            str(tmp_path))[-1] == good        # torn dir not listed
        with pytest.raises(CheckpointFormatError, match="manifest"):
            ModelSerializer.restore(torn)

    def test_shard_temp_sweep_dead_swept_live_spared(self, tmp_path):
        """Satellite 1: the stale-temp sweep extended to shard temps —
        a dead writer's orphaned partial shard DIRECTORY (and an
        orphaned inner shard temp) are swept; a live concurrent
        writer's are spared (same embedded-pid rule as monolithic
        temps)."""
        m, pw, tr, it = self._fit_sharded(str(tmp_path))
        tr.fit(it, epochs=1)
        dead_pid = 999999999
        live_pid = os.getpid()
        # dead writer's partial checkpoint dir with an inner temp
        dead_dir = str(tmp_path / f"checkpoint_epoch8.ckpt.tmp.{dead_pid}")
        os.makedirs(dead_dir)
        open(os.path.join(dead_dir, "shard_00000.zip"), "wb").close()
        # live concurrent writer's partial dir
        live_dir = str(tmp_path / f"checkpoint_epoch8.ckpt.tmp.{live_pid}")
        os.makedirs(live_dir)
        open(os.path.join(live_dir, "shard_00000.zip"), "wb").close()
        # orphaned dead-pid shard temp inside a COMMITTED dir
        committed = FaultTolerantTrainer.list_checkpoints(
            str(tmp_path))[-1]
        dead_inner = os.path.join(committed,
                                  f"shard_00009.zip.tmp.{dead_pid}")
        open(dead_inner, "wb").close()
        live_inner = os.path.join(committed,
                                  f"shard_00008.zip.tmp.{live_pid}")
        open(live_inner, "wb").close()
        tr._prune_and_sweep()
        assert not os.path.exists(dead_dir)      # dead dir swept
        assert os.path.isdir(live_dir)           # live dir spared
        assert not os.path.exists(dead_inner)    # dead inner temp swept
        assert os.path.exists(live_inner)        # live inner temp spared

    def test_stranded_old_checkpoint_is_renamed_back(self, tmp_path):
        """The rewrite path steps an existing checkpoint ASIDE
        (`*.ckpt.old.<pid>`) instead of rmtree-ing it before the new
        dir lands. If a kill strands the .old copy with the live name
        missing, the sweep must rename it BACK — with keep_last=1 it
        can be the only durable training state."""
        m, pw, tr, it = self._fit_sharded(str(tmp_path))
        tr.fit(it, epochs=1)
        live = FaultTolerantTrainer.list_checkpoints(str(tmp_path))[-1]
        # simulate the crash window: live name stepped aside by a
        # now-dead writer, replacement never landed
        stranded = f"{live}.old.999999999"
        os.rename(live, stranded)
        assert live not in FaultTolerantTrainer.list_checkpoints(
            str(tmp_path))
        tr._prune_and_sweep()
        assert FaultTolerantTrainer.list_checkpoints(
            str(tmp_path))[-1] == live       # recovered, resumable
        assert not os.path.exists(stranded)
        # ...while a LIVE writer's .old (ours, mid-rewrite) is spared
        aside = f"{live}.old.{os.getpid()}"
        os.makedirs(aside)
        tr._prune_and_sweep()
        assert os.path.isdir(aside)

    def test_format_rewrite_removes_stale_twin(self, tmp_path):
        """A checkpoint re-written in the OTHER format must delete its
        same-(epoch, step) twin — otherwise the stale twin ties in the
        listing sort and can shadow the fresh state at resume."""
        X, Y = _arrays(n=64)
        m = _mlp()
        FaultTolerantTrainer(m, str(tmp_path),
                             save_every_n_steps=100).fit(
            _it(X, Y), epochs=1)             # checkpoint_epoch1.zip
        assert os.path.exists(tmp_path / "checkpoint_epoch1.zip")
        m2 = _mlp()
        pw2 = ParallelWrapper(m2, workers=4)
        FaultTolerantTrainer(m2, str(tmp_path), save_every_n_steps=100,
                             wrapper=pw2, sharded_checkpoints=True).fit(
            _it(X, Y), epochs=1)             # checkpoint_epoch1.ckpt
        names = [os.path.basename(p) for p in
                 FaultTolerantTrainer.list_checkpoints(str(tmp_path))]
        assert names == ["checkpoint_epoch1.ckpt"]   # twin removed
        # and the reverse direction: v2 rewrite removes the .ckpt twin
        m3 = _mlp()
        FaultTolerantTrainer(m3, str(tmp_path),
                             save_every_n_steps=100).fit(
            _it(X, Y), epochs=1)
        names = [os.path.basename(p) for p in
                 FaultTolerantTrainer.list_checkpoints(str(tmp_path))]
        assert names == ["checkpoint_epoch1.zip"]

    def test_keep_last_prunes_shard_directories(self, tmp_path):
        m, pw, tr, it = self._fit_sharded(str(tmp_path), steps=2,
                                          keep_last=2)
        tr.fit(it, epochs=2)
        ckpts = FaultTolerantTrainer.list_checkpoints(str(tmp_path))
        assert len(ckpts) == 2
        assert all(os.path.isdir(p) for p in ckpts)


class TestElasticRemesh:
    def _run_fixed(self, d, mode, workers, epochs=3):
        X, Y = _arrays(n=64)
        m = _mlp()
        pw = _wrapped(m, workers, mode)
        FaultTolerantTrainer(m, d, save_every_n_steps=4, wrapper=pw,
                             sharded_checkpoints=True).fit(
            _it(X, Y), epochs=epochs)
        return m

    @pytest.mark.parametrize("w_from,w_to", [(8, 4), (4, 8)])
    @pytest.mark.parametrize("mode", ["update", "gradient"])
    def test_remeshed_resume_within_tolerance(self, tmp_path, mode,
                                              w_from, w_to):
        """The acceptance's changed-shape clause: preempt a W-worker
        compressed run, resume onto W' workers — the re-bucketed run
        finishes the schedule and lands within the documented
        tolerance of the fixed-shape trajectory, with zero post-warmup
        recompiles after the re-meshed step rebuild."""
        X, Y = _arrays(n=64)
        ref = self._run_fixed(str(tmp_path / "ref"), mode, w_from)
        # preempted at step 7 (mid-epoch 1) on the ORIGINAL fleet
        mB = _mlp()
        trB = FaultTolerantTrainer(
            mB, str(tmp_path / "b"), save_every_n_steps=4,
            wrapper=_wrapped(mB, w_from, mode), sharded_checkpoints=True,
            fault_injector=FaultInjector(plan={"preempt": [7]}))
        with pytest.raises(PreemptionFault):
            trB.fit(_it(X, Y), epochs=3)
        # restart on the NEW fleet shape
        mC = FaultTolerantTrainer.resume(str(tmp_path / "b"))
        assert mC._step == 7
        pwC = _wrapped(mC, w_to, mode)
        pwC.ensure_step()                    # consumes + re-buckets
        assert pwC.last_remesh == (w_from, w_to)
        res = pwC.accumulator.residuals
        assert all(np.asarray(a).shape[0] == w_to
                   for a in jax.tree_util.tree_leaves(res))
        trC = FaultTolerantTrainer(mC, str(tmp_path / "b"),
                                   save_every_n_steps=4, wrapper=pwC,
                                   sharded_checkpoints=True)
        trC.fit(_it(X, Y), epochs=3)
        assert mC._step == ref._step == 12   # schedule completed
        rel = _rel(_flat(ref), _flat(mC))
        assert rel <= REMESH_REL_TOL, \
            f"{mode} {w_from}->{w_to}: rel err {rel} > {REMESH_REL_TOL}"
        assert np.isfinite(_flat(mC)).all()
        # zero post-warmup recompiles after the re-meshed rebuild: the
        # continued multi-epoch fit ran on exactly one compiled program
        assert pwC._sharded_step._jit._cache_size() == 1

    def test_plain_wrapper_remesh_keeps_dense_trajectory(self, tmp_path):
        """No per-worker state to re-bucket: a dense DP checkpoint
        resumed at a different worker count computes the same global
        math (tolerance covers cross-shard reduction-order float
        noise)."""
        X, Y = _arrays(n=64)
        ref = self._run_fixed(str(tmp_path / "ref"), "plain", 4)
        mB = _mlp()
        trB = FaultTolerantTrainer(
            mB, str(tmp_path / "b"), save_every_n_steps=4,
            wrapper=_wrapped(mB, 4, "plain"), sharded_checkpoints=True,
            fault_injector=FaultInjector(plan={"preempt": [7]}))
        with pytest.raises(PreemptionFault):
            trB.fit(_it(X, Y), epochs=3)
        mC = FaultTolerantTrainer.resume(str(tmp_path / "b"))
        pwC = _wrapped(mC, 2, "plain")
        FaultTolerantTrainer(mC, str(tmp_path / "b"),
                             save_every_n_steps=4, wrapper=pwC,
                             sharded_checkpoints=True).fit(
            _it(X, Y), epochs=3)
        assert mC._step == 12
        assert _rel(_flat(ref), _flat(mC)) <= 1e-2

    # -- re-bucket unit semantics --------------------------------------
    def test_rebucket_shrink_is_group_mean(self):
        arr = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
        out = rebucket_worker_array(arr, 4)
        assert out.shape == (4, 3)
        np.testing.assert_allclose(out[0], arr[:2].mean(0))
        np.testing.assert_allclose(out[3], arr[6:].mean(0))

    def test_rebucket_grow_is_replication(self):
        arr = np.random.RandomState(0).rand(4, 3).astype(np.float32)
        out = rebucket_worker_array(arr, 8)
        assert out.shape == (8, 3)
        np.testing.assert_array_equal(out[0], arr[0])
        np.testing.assert_array_equal(out[1], arr[0])
        np.testing.assert_array_equal(out[7], arr[3])

    @pytest.mark.parametrize("w_to", [2, 4, 16, 3])
    def test_rebucket_preserves_pmean_mass(self, w_to):
        """The invariant the rule is built on: the per-step pmean
        contribution (1/W) * sum_w state_w is preserved exactly (up to
        float noise) under shrink, growth, AND the non-divisible
        fallback."""
        arr = np.random.RandomState(1).rand(8, 5).astype(np.float32)
        out = rebucket_worker_array(arr, w_to)
        np.testing.assert_allclose(out.mean(axis=0), arr.mean(axis=0),
                                   rtol=1e-5)
        assert out.dtype == arr.dtype

    def test_rebucket_identity_and_validation(self):
        arr = np.ones((4, 2), np.float32)
        assert rebucket_worker_array(arr, 4) is arr
        with pytest.raises(ValueError):
            rebucket_worker_array(arr, 0)


class TestFormatValidation:
    def test_unknown_zip_version_is_actionable(self, tmp_path):
        """Satellite 6: resume() on an unknown payload fails with the
        expected/found versions and the path — not a KeyError."""
        X, Y = _arrays(n=16)
        m = _mlp()
        FaultTolerantTrainer(m, str(tmp_path),
                             save_every_n_steps=100).fit(
            _it(X, Y, batch=16), epochs=1)
        path = FaultTolerantTrainer.list_checkpoints(str(tmp_path))[-1]
        # rewrite meta.json with a future format version
        tmp = path + ".rewrite"
        with zipfile.ZipFile(path) as zin, \
                zipfile.ZipFile(tmp, "w") as zout:
            for name in zin.namelist():
                data = zin.read(name)
                if name == "meta.json":
                    meta = json.loads(data.decode())
                    meta["format_version"] = 99
                    data = json.dumps(meta).encode()
                zout.writestr(name, data)
        os.replace(tmp, path)
        with pytest.raises(CheckpointFormatError) as ei:
            FaultTolerantTrainer.resume(str(tmp_path))
        msg = str(ei.value)
        assert "99" in msg and str(path) in msg and "supports" in msg

    def test_unknown_manifest_version_is_actionable(self, tmp_path):
        X, Y = _arrays(n=16)
        m = _mlp()
        FaultTolerantTrainer(m, str(tmp_path), save_every_n_steps=100,
                             sharded_checkpoints=True).fit(
            _it(X, Y, batch=16), epochs=1)
        path = FaultTolerantTrainer.list_checkpoints(str(tmp_path))[-1]
        mpath = os.path.join(path, "manifest.json")
        with open(mpath) as f:
            man = json.load(f)
        man["format_version"] = 42
        with open(mpath, "w") as f:
            json.dump(man, f)
        with pytest.raises(CheckpointFormatError) as ei:
            FaultTolerantTrainer.resume(str(tmp_path))
        assert "42" in str(ei.value) and path in str(ei.value)

    def test_v1_missing_version_still_restores(self, tmp_path):
        """Pre-v2 checkpoints carried no format_version — they must
        keep loading (missing == v1), not trip the gate."""
        X, Y = _arrays(n=16)
        m = _mlp()
        FaultTolerantTrainer(m, str(tmp_path),
                             save_every_n_steps=100).fit(
            _it(X, Y, batch=16), epochs=1)
        path = FaultTolerantTrainer.list_checkpoints(str(tmp_path))[-1]
        tmp = path + ".rewrite"
        with zipfile.ZipFile(path) as zin, \
                zipfile.ZipFile(tmp, "w") as zout:
            for name in zin.namelist():
                data = zin.read(name)
                if name == "meta.json":
                    meta = json.loads(data.decode())
                    meta.pop("format_version", None)
                    data = json.dumps(meta).encode()
                zout.writestr(name, data)
        os.replace(tmp, path)
        assert FaultTolerantTrainer.resume(str(tmp_path)) is not None


class TestInspectCheckpointTool:
    def _build_both(self, tmp_path):
        """v2 zips for epoch 1, then a sharded trainer RESUMES the run
        to epoch 3 — distinct (epoch, step) positions, so both formats
        coexist (same-name rewrites would rightly remove their twin)."""
        X, Y = _arrays(n=32)
        m = _mlp()
        FaultTolerantTrainer(m, str(tmp_path), save_every_n_steps=2,
                             keep_last=10).fit(
            _it(X, Y, batch=16), epochs=1)
        m2 = FaultTolerantTrainer.resume(str(tmp_path))
        pw = ParallelWrapper(
            m2, workers=4,
            accumulator=GradientSharingAccumulator(mode="update"))
        FaultTolerantTrainer(m2, str(tmp_path), save_every_n_steps=2,
                             keep_last=10, wrapper=pw,
                             sharded_checkpoints=True).fit(
            _it(X, Y, batch=16), epochs=3)

    def test_inspects_v2_and_v3_via_cli(self, tmp_path):
        self._build_both(tmp_path)
        r = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "inspect_checkpoint.py"),
             str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        rep = json.loads(r.stdout)
        kinds = {c["kind"] for c in rep["checkpoints"]}
        assert "file (v1/v2 zip)" in kinds
        assert "shard directory (v3)" in kinds
        for c in rep["checkpoints"]:
            assert c["step"] is not None and c["has_rng"] is True
            assert c["cursor"] is not None
        v3 = [c for c in rep["checkpoints"]
              if c["kind"].startswith("shard")]
        assert all(c["num_workers"] == 4 and len(c["shards"]) == 4
                   for c in v3)
        assert all(s["present"] for c in v3 for s in c["shards"])
        assert any(c["worker_sliced_keys"] for c in v3)

    def test_flags_torn_directory(self, tmp_path):
        self._build_both(tmp_path)
        torn = os.path.join(str(tmp_path), "checkpoint_epoch7.ckpt")
        os.makedirs(torn)
        open(os.path.join(torn, "shard_00000.zip"), "wb").close()
        r = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "inspect_checkpoint.py"),
             torn, "--json"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 1              # broken => nonzero
        rep = json.loads(r.stdout)
        assert rep["checkpoints"][0]["torn"] is True
        assert "never committed" in rep["checkpoints"][0]["error"]
