"""Golden-file integration regression runner (VERDICT r3 #7 — ref:
`dl4j-integration-tests/.../IntegrationTestRunner.java`: each TestCase's
predictions, parameters, and scores after N seeded updates are compared
against checked-in baselines generated once).

If a legitimate change alters numerics (e.g. a new updater formula),
regenerate with tests/fixtures/integration/generate.py and commit the
diff — exactly the reference's baseline-regeneration workflow.
"""
import os

import numpy as np
import pytest

from integration_cases import CASES, N_STEPS, run_case

BASE = os.path.join(os.path.dirname(__file__), "fixtures", "integration")


def _load(name):
    data = np.load(os.path.join(BASE, f"{name}.npz"))
    params = {k[2:]: data[k] for k in data.files if k.startswith("p:")}
    return params, data["__preds__"], data["__losses__"]


def test_baselines_are_committed():
    missing = [n for n in CASES
               if not os.path.exists(os.path.join(BASE, f"{n}.npz"))]
    assert not missing, (
        f"missing golden baselines {missing}; run "
        "tests/fixtures/integration/generate.py and commit the outputs")


@pytest.mark.parametrize("name", sorted(CASES))
def test_case_matches_golden(name):
    want_params, want_preds, want_losses = _load(name)
    got_params, got_preds, got_losses = run_case(name)
    assert set(got_params) == set(want_params), (
        set(got_params) ^ set(want_params))
    # losses first: the most interpretable drift signal
    np.testing.assert_allclose(got_losses, want_losses, rtol=1e-5,
                               atol=1e-6, err_msg=f"{name}: loss curve")
    np.testing.assert_allclose(got_preds, want_preds, rtol=1e-4,
                               atol=1e-5, err_msg=f"{name}: predictions")
    for k in sorted(want_params):
        np.testing.assert_allclose(
            got_params[k], want_params[k], rtol=1e-4, atol=1e-5,
            err_msg=f"{name}: param {k} after {N_STEPS} steps")
