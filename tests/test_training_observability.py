"""Training observability tests (ISSUE 13): step-phase tracing,
per-worker fleet telemetry, the structured event timeline, and the
training /metrics plane on the UIServer.

Covers:
- zero-cost-when-disabled discipline for the training step loop: the
  hot functions carry NO tracing code at all (source-scanned) and an
  instrumented-but-disabled fit allocates nothing attributable to the
  tracing module (tracemalloc-asserted);
- the retroactive span construction: a traced fit yields per-phase
  spans (data_wait, device_step, host_snapshot, checkpoint_submit,
  checkpoint_write) hung off one `fit` root without the loop ever
  calling the tracer;
- EventTimeline bounds/dump/counts and FleetTelemetry EWMAs/straggler
  spread;
- satellite exposure: RemoteUIStatsStorageRouter.dropped, the
  supervisor's checkpoint_write_s, and AsyncCheckpointWriter
  queue/stall state all land on the training `GET /metrics`;
- tools/trace_report.py's training sections (phase breakdown,
  straggler report, event timeline);
- the stitched acceptance scenario: a 3-worker elastic run with one
  injected mid-run preemption, reconstructed ENTIRELY from
  /debug/traces + /events + /metrics via trace_report.
"""
import importlib.util
import inspect
import json
import os
import threading
import time
import tracemalloc
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ArrayDataSetIterator
from deeplearning4j_tpu.faults import FaultInjector, PreemptionFault
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.elastic import FaultTolerantTrainer
from deeplearning4j_tpu.parallel.multihost import PreemptionCoordinator
from deeplearning4j_tpu.parallel.resilience import (AsyncCheckpointWriter,
                                                    TrainingSupervisor)
from deeplearning4j_tpu.parallel.telemetry import (EventTimeline,
                                                   FleetTelemetry)
from deeplearning4j_tpu.tracing import Tracer
from deeplearning4j_tpu.ui import (InMemoryStatsStorage,
                                   RemoteUIStatsStorageRouter,
                                   StatsListener, UIServer)

from _obs_util import assert_exposition_parity, parse_prometheus

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax"))
            .input_type_feed_forward(4).build())
    return MultiLayerNetwork(conf).init()


def _arrays(n=64, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.rand(n, 4).astype(np.float32)
    return X, np.eye(2, dtype=np.float32)[rs.randint(0, 2, n)]


def _it(X, Y, batch=16):
    return ArrayDataSetIterator(X, Y, batch=batch, shuffle=True, seed=3)


def _get_json(url, timeout=30):
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trp_training", os.path.join(ROOT, "tools", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------
# zero-cost-when-disabled
# ---------------------------------------------------------------------
class TestZeroCostDisabled:

    def test_hot_path_sources_carry_no_tracing_code(self):
        """The retroactive-span design means the per-step functions
        must not even MENTION tracing: the loop appends plain tuples to
        a ring that is None unless a trace is live. A 'trace' string
        appearing in these sources is a design regression, not a
        style nit."""
        for fn in (FaultTolerantTrainer._run_one_step,
                   FaultTolerantTrainer._after_step,
                   TrainingSupervisor.step):
            src = inspect.getsource(fn).lower()
            assert "trace" not in src, \
                f"{fn.__qualname__} mentions tracing in the hot path"

    def test_disabled_instrumented_fit_allocates_nothing_in_tracing(
            self, tmp_path):
        """An attached-but-disabled Tracer costs the step loop nothing:
        no allocation in the run is attributable to tracing.py."""
        X, Y = _arrays()
        m = _mlp()
        tr = FaultTolerantTrainer(m, str(tmp_path / "ck"),
                                  save_every_n_steps=3,
                                  tracer=Tracer(enabled=False))
        tr.fit(_it(X, Y), epochs=1)          # warm/compile pass
        trace_py = os.path.join("deeplearning4j_tpu", "tracing.py")
        tracemalloc.start()
        try:
            tr.fit(_it(X, Y), epochs=1)
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        hits = [st for st in snap.statistics("filename")
                if st.traceback[0].filename.endswith(trace_py)]
        assert not hits, (
            "disabled training tracing must allocate nothing: "
            f"{[(h.traceback[0].filename, h.size) for h in hits]}")
        assert tr._obs is None and tr.supervisor.obs is None

    def test_traced_fit_builds_phase_spans_retroactively(self, tmp_path):
        """Enabled tracer: one `fit` root per fit() call, per-step
        data_wait/device_step spans and checkpoint-cadence spans all
        reconstructed from the ring at fit exit."""
        X, Y = _arrays()
        m = _mlp()
        tracer = Tracer(enabled=True)
        tr = FaultTolerantTrainer(m, str(tmp_path / "ck"),
                                  save_every_n_steps=2, tracer=tracer,
                                  worker_id=5)
        tr.fit(_it(X, Y), epochs=1)          # 4 steps, ckpt every 2
        traces = tracer.dump()
        assert len(traces) == 1
        t = traces[0]
        assert t["request_id"].startswith("train-w5-")
        kinds = {}
        for s in t["spans"]:
            kinds[s["kind"]] = kinds.get(s["kind"], 0) + 1
        assert kinds["fit"] == 1
        assert kinds["data_wait"] == 4
        assert kinds["device_step"] == 4
        assert kinds["host_snapshot"] == 2
        assert kinds["checkpoint_submit"] == 2
        assert kinds["checkpoint_write"] >= 1
        # every non-root span hangs off the fit root
        root = next(s for s in t["spans"] if s["kind"] == "fit")
        assert all(s["parent_id"] == root["span_id"]
                   for s in t["spans"] if s is not root)
        # the root carries the phase totals the fractions derive from
        assert "data_wait_s" in root["attrs"]
        assert "checkpoint_stall_s" in root["attrs"]
        # device_step spans are worker-attributed for straggler reports
        ds = next(s for s in t["spans"] if s["kind"] == "device_step")
        assert ds["attrs"]["worker"] == 5
        # and the trainer's own snapshot exposes the phase fractions
        ph = tr.telemetry_snapshot()["phases"]
        assert ph["device_step_s"] > 0
        assert 0.0 <= ph["data_wait_frac"] <= 1.0


# ---------------------------------------------------------------------
# telemetry primitives
# ---------------------------------------------------------------------
class TestEventTimeline:

    def test_ring_is_bounded_but_counts_survive_eviction(self):
        ev = EventTimeline(capacity=4)
        for i in range(10):
            ev.record("anomaly_skip", worker=0, step=i)
        assert len(ev) == 4
        assert ev.counts() == {"anomaly_skip": 10}
        # oldest evicted: the dump starts at step 6
        assert [e["step"] for e in ev.dump()] == [6, 7, 8, 9]

    def test_dump_filters_by_kind_and_limits(self):
        ev = EventTimeline()
        ev.record("preempt_broadcast", worker=1, step=4)
        ev.record("preempt_received", worker=0, step=4)
        ev.record("preempt_received", worker=2, step=4)
        ev.record("checkpoint_commit", worker=1, duration_ms=2.0)
        got = ev.dump(kind="preempt_received")
        assert [e["worker"] for e in got] == [0, 2]
        assert len(ev.dump(limit=2)) == 2
        assert all("ts" in e for e in got)
        ev.clear()
        assert len(ev) == 0 and ev.counts() == {}


class TestFleetTelemetry:

    def test_ewma_seeds_on_first_observation(self):
        ft = FleetTelemetry(alpha=0.5)
        ft.observe_step(0, 0.100)
        assert ft.snapshot()["workers"]["0"]["ewma_ms"] == 100.0
        ft.observe_step(0, 0.200)              # 0.5*100 + 0.5*200
        assert ft.snapshot()["workers"]["0"]["ewma_ms"] == 150.0

    def test_straggler_spread_is_slowest_over_median(self):
        ft = FleetTelemetry()
        for w, s in ((0, 0.010), (1, 0.010), (2, 0.030)):
            ft.observe_step(w, s)
        st = ft.straggler()
        assert st["slowest_worker"] == 2
        assert st["median_ms"] == 10.0
        assert st["spread"] == 3.0

    def test_counters_and_unknown_key_raises(self):
        ft = FleetTelemetry()
        ft.inc(1, "preempts")
        ft.inc(1, "rollbacks", 2)
        w = ft.snapshot()["workers"]["1"]
        assert (w["preempts"], w["rollbacks"], w["anomaly_skips"]) \
            == (1, 2, 0)
        with pytest.raises(KeyError):
            ft.inc(1, "nonsense")


# ---------------------------------------------------------------------
# training /metrics exposure (satellite: dropped / checkpoint_write_s /
# writer queue state)
# ---------------------------------------------------------------------
class TestTrainingMetricsPlane:

    def test_trainer_snapshot_exports_with_full_parity(self, tmp_path):
        """The whole telemetry_snapshot tree — supervisor counters
        (checkpoint_write_s included), phase breakdown, async-writer
        queue/stall state — lands on the UIServer's /metrics with
        documented names/types/values (generic walker)."""
        X, Y = _arrays()
        m = _mlp()
        tr = FaultTolerantTrainer(m, str(tmp_path / "ck"),
                                  save_every_n_steps=2,
                                  fleet_telemetry=FleetTelemetry(),
                                  events=EventTimeline(), worker_id=0)
        tr.fit(_it(X, Y), epochs=1)
        snap = tr.telemetry_snapshot()
        assert snap["supervisor"]["checkpoint_write_s"] > 0
        assert snap["checkpoint_writer"]["writes"] >= 1
        assert snap["checkpoint_writer"]["busy"] in (0, 1)
        assert snap["checkpoint_writer"]["pending"] in (0, 1)
        ui = UIServer(port=0)
        try:
            ui.add_metrics_provider("training", tr.telemetry_snapshot)
            base = f"http://127.0.0.1:{ui.port}"
            resp = urllib.request.urlopen(base + "/metrics", timeout=30)
            assert resp.headers.get("Content-Type", "").startswith(
                "text/plain; version=0.0.4")
            samples, types = parse_prometheus(resp.read().decode())
            assert_exposition_parity(ui.metrics_snapshot(), samples,
                                     types)
            # the satellite's named leaves, by their exposition names
            assert ("dl4j_training_supervisor_checkpoint_write_s",
                    "") in samples
            assert samples[("dl4j_training_checkpoint_writer_"
                            "writes_total", "")] >= 1
            assert types["dl4j_training_checkpoint_writer_busy"] \
                == "gauge"
            # per-worker fleet telemetry renders as nested families
            assert ("dl4j_training_fleet_workers_workers_0_steps_total",
                    "") in samples
        finally:
            ui.stop()

    def test_stats_router_dropped_is_scrapable(self, tmp_path):
        """RemoteUIStatsStorageRouter.dropped (always counted, never
        exposed before) reaches /metrics as a counter."""
        ui = UIServer(port=0)   # remote listener NOT enabled -> 403
        try:
            router = RemoteUIStatsStorageRouter(
                f"http://127.0.0.1:{ui.port}", max_retries=1,
                retry_backoff_s=0.01)
            router.put_update("s1", {"iteration": 0, "score": 1.0})
            router.shutdown()
            assert router.snapshot()["dropped"] == 1
            ui.add_metrics_provider("stats_router", router.snapshot)
            base = f"http://127.0.0.1:{ui.port}"
            samples, types = parse_prometheus(urllib.request.urlopen(
                base + "/metrics", timeout=30).read().decode())
            assert samples[("dl4j_stats_router_dropped_total", "")] == 1
            assert types["dl4j_stats_router_dropped_total"] == "counter"
            assert samples[("dl4j_stats_router_queued", "")] == 0
        finally:
            ui.stop()

    def test_broken_provider_does_not_take_down_the_scrape(self):
        ui = UIServer(port=0)
        try:
            ui.add_metrics_provider("good", lambda: {"steps": 3})
            ui.add_metrics_provider(
                "bad", lambda: (_ for _ in ()).throw(RuntimeError("x")))
            base = f"http://127.0.0.1:{ui.port}"
            samples, _ = parse_prometheus(urllib.request.urlopen(
                base + "/metrics", timeout=30).read().decode())
            assert samples[("dl4j_good_steps_total", "")] == 3
            snap = ui.metrics_snapshot()
            assert "provider_error" in snap["bad"]
        finally:
            ui.stop()

    def test_stats_listener_reports_phases_and_samples_per_sec(
            self, tmp_path):
        """StatsListener picks up the trainer-maintained phase
        breakdown and the on_timing-fed samples/sec, and the latest
        update reaches /metrics under training_sessions."""
        X, Y = _arrays()
        m = _mlp()
        storage = InMemoryStatsStorage()
        m.set_listeners(StatsListener(storage, session_id="sess",
                                      collect_params=False))
        tr = FaultTolerantTrainer(m, str(tmp_path / "ck"),
                                  save_every_n_steps=100)
        tr.fit(_it(X, Y), epochs=1)
        ups = storage.get_updates("sess")
        assert ups, "no StatsListener updates collected"
        last = ups[-1]
        assert last["samples_per_sec"] > 0
        assert last["phases"]["device_step_s"] > 0
        assert "data_wait_s" in last["phases"]
        ui = UIServer(port=0)
        try:
            ui.attach(storage)
            base = f"http://127.0.0.1:{ui.port}"
            samples, types = parse_prometheus(urllib.request.urlopen(
                base + "/metrics", timeout=30).read().decode())
            assert ("dl4j_training_sessions_sess_samples_per_sec",
                    "") in samples
            assert samples[("dl4j_training_sessions_sess_phases_"
                            "device_step_s", "")] == \
                last["phases"]["device_step_s"]
            assert_exposition_parity(ui.metrics_snapshot(), samples,
                                     types)
        finally:
            ui.stop()

    def test_traces_and_events_endpoints_404_until_attached(self):
        ui = UIServer(port=0)
        try:
            base = f"http://127.0.0.1:{ui.port}"
            for path in ("/debug/traces", "/events"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(base + path, timeout=30)
                assert ei.value.code == 404
            ui.attach_tracer(Tracer(enabled=True))
            ui.attach_events(EventTimeline())
            assert _get_json(base + "/debug/traces")["traces"] == []
            assert _get_json(base + "/events")["events"] == []
        finally:
            ui.stop()


# ---------------------------------------------------------------------
# trace_report training sections (unit)
# ---------------------------------------------------------------------
def _span(sid, pid, kind, off, dur, **attrs):
    return {"span_id": sid, "parent_id": pid, "kind": kind,
            "t_offset_ms": off, "duration_ms": dur, "attrs": attrs}


def _training_trace(worker, step_ms):
    spans = [_span(1, None, "fit", 0.0, 100.0, worker=worker)]
    sid = 2
    off = 0.0
    for i in range(4):
        spans.append(_span(sid, 1, "data_wait", off, 1.0))
        spans.append(_span(sid + 1, 1, "device_step", off + 1.0,
                           step_ms, worker=worker, step=i))
        sid += 2
        off += 1.0 + step_ms
    spans.append(_span(sid, 1, "host_snapshot", off, 2.0))
    spans.append(_span(sid + 1, 1, "checkpoint_submit", off + 2.0, 0.5))
    spans.append(_span(sid + 2, 1, "checkpoint_write", off + 2.5, 30.0))
    return {"trace_id": f"t{worker}", "request_id": f"train-w{worker}",
            "duration_ms": 100.0, "error": False, "spans": spans}


class TestTraceReportTraining:

    def test_training_phases_fractions(self):
        trp = _load_trace_report()
        tp = trp.training_phases([_training_trace(0, 10.0)])
        # wall = 4*1 data_wait + 4*10 device + 2 snapshot + 0.5 submit
        assert tp["totals_ms"]["device_step"] == 40.0
        assert tp["data_wait_frac"] == round(4.0 / 46.5, 4)
        assert tp["checkpoint_stall_frac"] == round(2.5 / 46.5, 4)
        # the writer-thread spans are listed but NOT in the stall frac
        assert tp["totals_ms"]["checkpoint_write"] == 30.0
        assert tp["kinds"]["device_step"]["count"] == 4
        assert trp.training_phases([]) == {}

    def test_straggler_report_groups_device_steps_by_worker(self):
        trp = _load_trace_report()
        sr = trp.straggler_report(
            [_training_trace(0, 10.0), _training_trace(1, 10.0),
             _training_trace(2, 30.0)])
        assert set(sr["workers"]) == {"0", "1", "2"}
        assert sr["slowest_worker"] == "2"
        assert sr["median_p50_ms"] == 10.0
        assert sr["spread"] == 3.0
        assert trp.straggler_report([]) == {}

    def test_event_timeline_rebases_and_orders(self):
        trp = _load_trace_report()
        evs = [{"ts": 105.0, "kind": "checkpoint_commit", "worker": 1,
                "duration_ms": 4.0, "bytes": 2048},
               {"ts": 100.0, "kind": "preempt_broadcast", "worker": 1,
                "step": 4}]
        tl = trp.event_timeline(evs)
        assert [e["kind"] for e in tl] == ["preempt_broadcast",
                                          "checkpoint_commit"]
        assert tl[0]["t_offset_s"] == 0.0
        assert tl[1]["t_offset_s"] == 5.0
        assert tl[1]["attrs"]["bytes"] == 2048
        assert trp.event_timeline([]) == []

    def test_report_partitions_event_dumps_and_renders_human(
            self, tmp_path):
        trp = _load_trace_report()
        tf = tmp_path / "traces.json"
        tf.write_text(json.dumps(
            {"traces": [_training_trace(0, 10.0),
                        _training_trace(1, 20.0)]}))
        ef = tmp_path / "events.json"
        ef.write_text(json.dumps({"events": [
            {"ts": 10.0, "kind": "preempt_broadcast", "worker": 1,
             "step": 4},
            {"ts": 10.2, "kind": "checkpoint_commit", "worker": 0,
             "duration_ms": 3.0, "bytes": 4096}],
            "counts": {"preempt_broadcast": 1, "checkpoint_commit": 1}}))
        rep = trp.report([str(tf), str(ef)])
        assert rep["n_traces"] == 2
        assert rep["training"]["kinds"]["device_step"]["count"] == 8
        assert rep["stragglers"]["spread"] == round(20.0 / 15.0, 4)
        assert [e["kind"] for e in rep["events"]] == \
            ["preempt_broadcast", "checkpoint_commit"]
        human = trp._fmt_human(rep)
        assert "training phase breakdown" in human
        assert "stragglers" in human
        assert "event timeline" in human


# ---------------------------------------------------------------------
# the acceptance scenario: a 3-worker elastic fleet with one injected
# mid-run preemption, reconstructed from the three HTTP endpoints alone
# ---------------------------------------------------------------------
class TestStitchedFleetObservability:

    def test_preempted_fleet_reconstructs_from_endpoints(self, tmp_path):
        X, Y = _arrays(n=96)
        n_workers = 3
        coord = PreemptionCoordinator()
        injector = FaultInjector(plan={"preempt": {1: [4]}},
                                 rates={"train_step": 1.0},
                                 slow_ms={"train_step": 4.0})
        tracer = Tracer(enabled=True, ring=16)
        events = EventTimeline()
        fleet = FleetTelemetry()
        models = [_mlp() for _ in range(n_workers)]
        barrier = threading.Barrier(n_workers)

        class SyncFirstStep:
            def __init__(self):
                self.passed = False

            def iteration_done(self, m, step, epoch):
                if not self.passed:
                    self.passed = True
                    barrier.wait(timeout=90)
        for m in models:
            m.set_listeners(SyncFirstStep())
        trainers = [FaultTolerantTrainer(
            models[i], str(tmp_path / f"w{i}"), save_every_n_steps=100,
            fault_injector=injector, coordinator=coord, worker_id=i,
            tracer=tracer, events=events, fleet_telemetry=fleet)
            for i in range(n_workers)]
        outcomes = [None] * n_workers

        def run(i):
            try:
                trainers[i].fit(_it(X, Y, batch=8), epochs=4)
                outcomes[i] = "done"
            except PreemptionFault:
                outcomes[i] = "preempted"
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert outcomes == ["preempted"] * n_workers, outcomes

        ui = UIServer(port=0)
        try:
            ui.attach_tracer(tracer)
            ui.attach_events(events)
            for i, tr in enumerate(trainers):
                ui.add_metrics_provider(f"w{i}", tr.telemetry_snapshot)
            base = f"http://127.0.0.1:{ui.port}"
            traces_doc = _get_json(base + "/debug/traces?limit=16")
            events_doc = _get_json(base + "/events")
            metrics_txt = urllib.request.urlopen(
                base + "/metrics", timeout=30).read().decode()

            # -- /metrics: full parity + the fleet story in counters
            samples, types = parse_prometheus(metrics_txt)
            assert_exposition_parity(ui.metrics_snapshot(), samples,
                                     types)
            assert samples[("dl4j_w1_supervisor_preempts_broadcast"
                            "_total", "")] == 1
            for i in (0, 2):
                assert samples[(f"dl4j_w{i}_supervisor_preempts_"
                                "received_total", "")] == 1
            # shared fleet telemetry: every worker has steps + an EWMA
            for i in range(n_workers):
                assert samples[("dl4j_w0_fleet_workers_workers_"
                                f"{i}_steps_total", "")] >= 4
            assert ("dl4j_w0_fleet_workers_straggler_spread",
                    "") in samples

            # -- /events: broadcast precedes the receipts, every
            # worker committed a drain checkpoint
            kinds = [e["kind"] for e in events_doc["events"]]
            b = kinds.index("preempt_broadcast")
            assert [e["worker"] for e in events_doc["events"]
                    if e["kind"] == "preempt_broadcast"] == [1]
            recv = [i for i, k in enumerate(kinds)
                    if k == "preempt_received"]
            assert len(recv) == 2 and all(i > b for i in recv)
            commits = [e for e in events_doc["events"]
                       if e["kind"] == "checkpoint_commit"]
            assert {e["worker"] for e in commits} == {0, 1, 2}
            assert all(e["duration_ms"] > 0 and e["bytes"] > 0
                       for e in commits)
            assert events_doc["counts"]["preempt_broadcast"] == 1
            assert events_doc["counts"]["preempt_received"] == 2

            # -- trace_report over the dumped endpoints alone
            tf = tmp_path / "traces.json"
            tf.write_text(json.dumps(traces_doc))
            ef = tmp_path / "events.json"
            ef.write_text(json.dumps(events_doc))
            trp = _load_trace_report()
            rep = trp.report([str(tf), str(ef)])
            assert rep["n_traces"] == n_workers
            tp = rep["training"]
            for kind in ("data_wait", "device_step", "preemption_drain"):
                assert tp["kinds"][kind]["count"] >= 1
                assert tp["kinds"][kind]["p99_ms"] >= \
                    tp["kinds"][kind]["p50_ms"]
            assert 0.0 <= tp["data_wait_frac"] <= 1.0
            sr = rep["stragglers"]
            assert set(sr["workers"]) == {"0", "1", "2"}
            assert sr["spread"] >= 1.0
            tl = rep["events"]
            assert [e["t_offset_s"] for e in tl] == \
                sorted(e["t_offset_s"] for e in tl)
            story = [e["kind"] for e in tl]
            assert story.index("preempt_broadcast") < \
                story.index("checkpoint_commit")
            human = trp._fmt_human(rep)
            assert "preempt_broadcast" in human
            assert "stragglers" in human
        finally:
            ui.stop()

    def test_resume_records_resume_event_and_span(self, tmp_path):
        """After the drain, a resumed worker's new fit records the
        `resume` event (and span) that closes the timeline's story."""
        X, Y = _arrays()
        m = _mlp()
        inj = FaultInjector(plan={"preempt": [3]})
        tr = FaultTolerantTrainer(m, str(tmp_path / "ck"),
                                  save_every_n_steps=100,
                                  fault_injector=inj)
        with pytest.raises(PreemptionFault):
            tr.fit(_it(X, Y), epochs=2)
        tracer = Tracer(enabled=True)
        events = EventTimeline()
        m2 = FaultTolerantTrainer.resume(str(tmp_path / "ck"))
        tr2 = FaultTolerantTrainer(m2, str(tmp_path / "ck"),
                                   save_every_n_steps=100,
                                   tracer=tracer, events=events,
                                   worker_id=0)
        tr2.fit(_it(X, Y), epochs=2)
        evs = events.dump(kind="resume")
        assert len(evs) == 1 and evs[0]["step"] == 3
        spans = [s for t in tracer.dump() for s in t["spans"]
                 if s["kind"] == "resume"]
        assert len(spans) == 1
        assert spans[0]["attrs"]["epoch"] >= 0
