"""CJK/Unicode tokenizer variants (ref: deeplearning4j-nlp-parent's
Chinese/Japanese/Korean tokenizer factories + UimaTokenizerFactory)."""
from deeplearning4j_tpu.nlp.tokenization import (CJKTokenizerFactory,
                                                 CommonPreprocessor,
                                                 UnicodeTokenizerFactory)


class TestCJKTokenizer:
    def test_han_bigrams(self):
        toks = CJKTokenizerFactory().tokenize("深度学习")
        assert toks == ["深度", "度学", "学习"]

    def test_han_unigrams(self):
        toks = CJKTokenizerFactory(unigrams=True).tokenize("深度学习")
        assert toks == ["深", "度", "学", "习"]

    def test_mixed_cjk_latin(self):
        toks = CJKTokenizerFactory().tokenize("用TPU训练模型fast")
        assert "TPU" in toks and "fast" in toks
        assert "训练" in toks and "练模" in toks and "模型" in toks

    def test_japanese_kana_runs_stay_whole(self):
        # katakana loanword stays one token; han bigrams around it
        toks = CJKTokenizerFactory().tokenize("テンソル計算")
        assert "テンソル" in toks
        assert "計算" in toks

    def test_hangul_runs(self):
        toks = CJKTokenizerFactory().tokenize("딥러닝 모델")
        assert toks == ["딥러닝", "모델"]

    def test_word2vec_integration(self):
        """CJK corpus through the Word2Vec stack end to end."""
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        fac = CJKTokenizerFactory()
        corpus = ["深度学习模型训练", "深度模型推理", "学习训练数据"] * 30
        w2v = Word2Vec(layer_size=16, window_size=2, min_word_frequency=1,
                       negative=3, seed=1, batch_size=64,
                       tokenizer_factory=fac)
        w2v.fit(corpus)
        vec = w2v.word_vector("深度")
        assert vec is not None and len(vec) == 16


class TestUnicodeTokenizer:
    def test_word_boundaries(self):
        toks = UnicodeTokenizerFactory().tokenize("héllo wörld, foo-bar!")
        assert toks == ["héllo", "wörld", "foo", "bar"]

    def test_preprocessor_applies(self):
        fac = UnicodeTokenizerFactory(preprocessor=CommonPreprocessor())
        assert fac.tokenize("Hello WORLD 123") == ["hello", "world"]
